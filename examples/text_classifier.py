"""End-to-end TEXT pipeline: raw strings -> WordPiece -> transformer.

The reference pipelines start from pre-vectorized features (its examples use
``VectorAssembler``/``OneHotEncoder`` over numeric MNIST columns); it has no
text front-end at all. Here the native C++ WordPiece tokenizer
(``WordpieceEncoder``) turns a string column into fixed-shape token-id and
attention-mask columns, which feed a transformer classifier through
``SparkAsyncDL``'s multi-input path — tokenize / train / predict / pipeline
save+load, all through the standard Spark ML surface.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

from sparkflow_tpu.compat import USING_PYSPARK
from sparkflow_tpu.models import build_registry_spec
from sparkflow_tpu.tensorflow_async import SparkAsyncDL

if USING_PYSPARK:
    from pyspark.sql import SparkSession
else:
    from sparkflow_tpu.localml import LocalSession as SparkSession
from sparkflow_tpu.localml import OneHotEncoder, WordpieceEncoder

SMOKE = bool(os.environ.get("SPARKFLOW_TPU_SMOKE"))


def synthetic_reviews(n, rs):
    """Tiny sentiment-ish corpus: a marker word decides the label."""
    pos = ["wonderful", "great", "loved", "excellent", "delightful"]
    neg = ["terrible", "awful", "hated", "boring", "dreadful"]
    filler = ["the", "movie", "was", "plot", "acting", "and", "very",
              "with", "scenes", "a", "story"]
    rows = []
    for _ in range(n):
        label = rs.randint(0, 2)
        words = [filler[i] for i in rs.randint(0, len(filler), 8)]
        words.insert(rs.randint(0, len(words)),
                     (pos if label else neg)[rs.randint(0, 5)])
        rows.append((float(label), " ".join(words)))
    return rows


if __name__ == "__main__":
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    spark = SparkSession.builder.appName("text-classifier").getOrCreate()
    rs = np.random.RandomState(0)
    seq_len = 16
    df = spark.createDataFrame(synthetic_reviews(200 if SMOKE else 2000, rs),
                               ["label", "text"])

    enc = WordpieceEncoder(inputCol="text", outputCol="tokens",
                           maskCol="mask", maxLen=seq_len)
    oh = OneHotEncoder(inputCol="label", outputCol="labels", dropLast=False)
    encoded = oh.transform(enc.transform(df))

    spec = build_registry_spec(
        "transformer_classifier", vocab_size=len(enc._vocab), num_classes=2,
        hidden=32 if SMOKE else 128, num_layers=2 if SMOKE else 4,
        num_heads=4, mlp_dim=64 if SMOKE else 256, max_len=seq_len,
        dropout=0.1)
    est = SparkAsyncDL(inputCol="tokens", tensorflowGraph=spec,
                       tfInput="input_ids:0", tfLabel="y:0",
                       tfOutput="pred:0", tfOptimizer="adam",
                       tfLearningRate=1e-3, iters=10 if SMOKE else 40,
                       partitions=2, labelCol="labels",
                       predictionCol="predicted", miniBatchSize=32,
                       extraInputCols="mask",
                       extraTfInputs="attention_mask:0")
    model = est.fit(encoded)
    preds = model.transform(encoded)
    acc = np.mean([float(r["predicted"]) == r["label"]
                   for r in preds.collect()])
    print(f"train accuracy: {acc:.3f}")
