"""Structured training metrics (replaces the reference's print-based logging,
``sparkflow/HogwildSparkModel.py:94-98`` — SURVEY.md §5 "observability").

A process-local registry of counters/gauges/timings/histograms with JSONL
export and an optional per-step callback fan-out. Cheap enough to leave on:
recording is a dict update; device syncs only happen where the caller already
has a value. Histograms (``observe``/``percentile``) back the serving-side
latency metrics (p50/p95/p99) and are bounded by a reservoir cap so a
long-lived server never grows without limit.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence

# Per-histogram sample cap. Beyond it, reservoir sampling keeps a uniform
# sample of the whole stream (percentiles stay unbiased) instead of the
# unbounded append a months-long serving process would otherwise pay for.
HISTOGRAM_RESERVOIR = 4096


class _Histogram:
    """Reservoir-sampled value distribution with exact count/min/max/sum."""

    __slots__ = ("samples", "count", "total", "vmin", "vmax", "_rng")

    def __init__(self, seed: int = 0):
        self.samples: List[float] = []
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)
        if len(self.samples) < HISTOGRAM_RESERVOIR:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < HISTOGRAM_RESERVOIR:
                self.samples[j] = value

    def percentile(self, q: float) -> float:
        """Linear-interpolated q-th percentile (q in [0, 100]) of the
        reservoir sample."""
        if not self.samples:
            raise ValueError("empty histogram")
        s = sorted(self.samples)
        if len(s) == 1:
            return s[0]
        pos = (q / 100.0) * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    def summary(self) -> Dict[str, float]:
        return {"count": self.count,
                "mean": self.total / self.count if self.count else 0.0,
                "min": self.vmin, "max": self.vmax,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}


class Metrics:
    def __init__(self):
        self._scalars: Dict[str, List[tuple]] = defaultdict(list)
        self._counters: Dict[str, float] = defaultdict(float)
        self._hists: Dict[str, _Histogram] = {}
        self._listeners: List[Callable[[str, float, int], None]] = []
        # serving handlers record from many threads; counter += and
        # histogram reservoir updates are read-modify-write, so both take
        # the lock (list.append in scalar() is atomic and stays lock-free)
        self._hist_lock = threading.Lock()

    def scalar(self, name: str, value: float, step: Optional[int] = None) -> None:
        step = step if step is not None else len(self._scalars[name])
        self._scalars[name].append((step, float(value), time.time()))
        for fn in self._listeners:
            fn(name, float(value), step)

    def incr(self, name: str, amount: float = 1.0) -> None:
        with self._hist_lock:
            self._counters[name] += amount

    def observe(self, name: str, value: float) -> None:
        """Record one observation into the ``name`` histogram (latencies,
        batch sizes, fill ratios — anything whose distribution matters more
        than its last value)."""
        with self._hist_lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(seed=len(self._hists))
            h.add(float(value))

    def percentile(self, name: str, q: float) -> float:
        """q-th percentile (q in [0, 100]) of histogram ``name``."""
        with self._hist_lock:
            if name not in self._hists:
                raise KeyError(f"no histogram named {name!r}")
            return self._hists[name].percentile(q)

    def percentiles(self, name: str,
                    qs: Sequence[float] = (50, 95, 99)) -> Dict[str, float]:
        """{'p50': ..., 'p95': ..., 'p99': ...} for histogram ``name``."""
        return {f"p{g:g}": self.percentile(name, g) for g in qs}

    def subscribe(self, fn: Callable[[str, float, int], None]) -> None:
        self._listeners.append(fn)

    def series(self, name: str) -> List[tuple]:
        return list(self._scalars.get(name, []))

    def counters(self) -> Dict[str, float]:
        return dict(self._counters)

    def histograms(self) -> Dict[str, Dict[str, float]]:
        with self._hist_lock:
            return {name: h.summary() for name, h in self._hists.items()
                    if h.count}

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"counters": self.counters()}
        for name, pts in self._scalars.items():
            vals = [v for _, v, _ in pts]
            out[name] = {"last": vals[-1], "min": min(vals), "max": max(vals),
                         "count": len(vals)}
        hists = self.histograms()
        if hists:
            out["histograms"] = hists
        return out

    def dump_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for name, pts in self._scalars.items():
                for step, value, ts in pts:
                    f.write(json.dumps({"name": name, "step": step,
                                        "value": value, "ts": ts}) + "\n")
            for name, value in self._counters.items():
                f.write(json.dumps({"name": name, "counter": value}) + "\n")
            for name, hist in self.histograms().items():
                f.write(json.dumps({"name": name, "histogram": hist}) + "\n")

    def reset(self) -> None:
        self._scalars.clear()
        self._counters.clear()
        with self._hist_lock:
            self._hists.clear()


default_metrics = Metrics()


class timer:
    """``with timer('stage'):`` records wall seconds into the registry."""

    def __init__(self, name: str, metrics: Optional[Metrics] = None):
        self.name = name
        self.metrics = metrics or default_metrics

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.scalar(f"time/{self.name}", time.perf_counter() - self._t0)
        return False
