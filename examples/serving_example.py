"""Online serving: train with Spark, serve over HTTP with micro-batching.

The full path from a fitted estimator to a live endpoint:

1. ``SparkAsyncDL.fit`` trains as usual; the fitted model's ``modelWeights``
   Param is the wire-format weights string.
2. ``InferenceEngine`` loads (graph JSON, weights) and AOT-compiles the apply
   function for a ladder of batch-size buckets — after warmup, no request
   size triggers a compile.
3. ``InferenceServer`` exposes ``/v1/predict`` (micro-batched: concurrent
   requests coalesce into one device call), ``/healthz``, ``/metrics``.
4. ``ServingClient`` hits the endpoint from a pool of threads, then reads the
   serving histograms (batch fill, padding waste, latency p50/p95/p99) back
   from ``/metrics``.
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkflow_tpu import nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.tensorflow_async import SparkAsyncDL
from sparkflow_tpu.compat import USING_PYSPARK

if USING_PYSPARK:
    from pyspark.sql import SparkSession
    from pyspark.ml.linalg import Vectors
else:
    from sparkflow_tpu.localml import LocalSession as SparkSession, Vectors


def model():
    x = nn.placeholder([None, 16], name='x')
    y = nn.placeholder([None, 1], name='y')
    h = nn.dense(x, 64, activation='relu')
    out = nn.dense(h, 1, activation='sigmoid', name='outer')
    nn.sigmoid_cross_entropy(y, out)


def main():
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    smoke = bool(os.environ.get('SPARKFLOW_TPU_SMOKE'))

    spark = SparkSession.builder.appName('serving-example').getOrCreate()
    rs = np.random.RandomState(0)
    rows = []
    for _ in range(100 if smoke else 400):
        rows.append((1.0, Vectors.dense(rs.normal(0.8, 1.0, 16))))
        rows.append((0.0, Vectors.dense(rs.normal(-0.8, 1.0, 16))))
    df = spark.createDataFrame(rows, ['label', 'features'])

    fitted = SparkAsyncDL(
        inputCol='features', tensorflowGraph=build_graph(model),
        tfInput='x:0', tfLabel='y:0', tfOutput='outer/Sigmoid:0',
        labelCol='label', tfLearningRate=.05, iters=3 if smoke else 15,
        miniBatchSize=128, verbose=0).fit(df)

    # fitted Params -> engine: same graph JSON, same weights wire format
    from sparkflow_tpu.serving import InferenceEngine, InferenceServer, ServingClient
    engine = InferenceEngine(
        fitted.getOrDefault(fitted.modelJson),
        fitted.getOrDefault(fitted.modelWeights),
        input_name='x:0', output_name='outer/Sigmoid:0', max_batch=32)
    print(f'engine ready: buckets={engine.buckets} '
          f'aot_compiles={engine.aot_compiles}')

    with InferenceServer(engine, max_delay_ms=2.0) as server:
        client = ServingClient(server.url)
        print(f'serving at {server.url}  healthz={client.healthz()["status"]}')

        n_clients = 4 if smoke else 16
        hits, lock = [], threading.Lock()

        def one_client(i):
            x = rs.normal(0.8 if i % 2 else -0.8, 1.0, (3, 16))
            pred = client.predict(x)
            correct = np.mean((pred[:, 0] > 0.5) == bool(i % 2))
            with lock:
                hits.append(correct)

        threads = [threading.Thread(target=one_client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        print(f'{n_clients} concurrent clients served, '
              f'accuracy={np.mean(hits):.3f}')

        m = client.metrics()
        lat = m['histograms']['serving/request_latency_ms']
        fill = m['histograms']['serving/batch_fill_ratio']
        print(f"latency ms p50={lat['p50']:.2f} p95={lat['p95']:.2f} "
              f"p99={lat['p99']:.2f}; mean batch fill={fill['mean']:.3f}")
        print(f'recompiles after warmup: {engine.fallback_compiles}')


if __name__ == '__main__':
    main()
