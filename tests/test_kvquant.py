"""Quantized KV cache (int8/fp8) with dequant-on-read paged attention.

Covers the PR's acceptance criteria directly: pallas kernel parity with the
jnp reference on quantized pools across page sizes (including empty slots
and garbage-page isolation), the running-scale append/write semantics in
``utils.quant``, pool-neutral churn on a quantized ``PagedKVCache``,
greedy token parity of int8/fp8 engines against the dense forward — alone
and composed with speculation + prefix cache + chunked prefill + a 2D
pp x tp mesh — and the up-front ctor validation battery.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkflow_tpu.models.registry import build_registry_spec, model_from_json
from sparkflow_tpu.ops import (paged_attention, paged_attention_reference,
                               paged_attention_verify,
                               paged_attention_verify_reference)
from sparkflow_tpu.ops.attention import last_attention_path
from sparkflow_tpu.parallel.mesh import make_mesh
from sparkflow_tpu.serving import DecodeEngine, PagedKVCache
from sparkflow_tpu.sharding import ShardingConfig
from sparkflow_tpu.utils import quant

QDTYPES = ["int8", "fp8"]

#: |quantized attention - full-precision attention| ceiling per dtype.
#: int8 carries ~0.4% relative rounding per element; e4m3 ~3%. After the
#: softmax contraction the observed max error is ~5x smaller than these.
ATT_TOL = {"int8": 0.05, "fp8": 0.25}


def _need(kv_dtype):
    if not quant.kv_quant_supported(kv_dtype):
        pytest.skip(f"{kv_dtype} KV pools unsupported by this jax install")


def _rand_paged(rs, b, h, d, page_size, max_pages, lengths):
    """Random q + float pools + a valid page table (page 0 is scratch)."""
    num_pages = 1 + b * max_pages
    q = rs.randn(b, h, d).astype(np.float32)
    k = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    v = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    table = np.zeros((b, max_pages), np.int32)
    nxt = 1
    for i, ln in enumerate(lengths):
        for p in range((ln + page_size - 1) // page_size):
            table[i, p] = nxt
            nxt += 1
    return q, k, v, table, np.asarray(lengths, np.int32)


def _quant_pools(k, v, kv_dtype):
    qk, ks = quant.quantize_kv_pages(k, kv_dtype)
    qv, vs = quant.quantize_kv_pages(v, kv_dtype)
    return qk, ks, qv, vs


# -- dequant-on-read kernel parity --------------------------------------------


@pytest.mark.parametrize("kv_dtype", QDTYPES)
@pytest.mark.parametrize("page_size", [8, 16, 64])
def test_paged_attention_quant_parity(page_size, kv_dtype):
    """The quantized pallas decode kernel == the quantized jnp reference on
    the same int8/fp8 pool (near-exact — both dequantize in f32), and both
    stay within the dtype's error envelope of the full-precision answer.
    Ragged lengths include an empty slot, which must come out exact zeros."""
    _need(kv_dtype)
    rs = np.random.RandomState(page_size)
    b, h, d, max_pages = 4, 4, 16, 3
    lengths = [0, 1, page_size + 3, max_pages * page_size]
    q, k, v, table, lens = _rand_paged(rs, b, h, d, page_size, max_pages,
                                       lengths)
    qk, ks, qv, vs = _quant_pools(k, v, kv_dtype)
    ref = paged_attention_reference(q, qk, qv, table, lens,
                                    k_scales=ks, v_scales=vs)
    out = paged_attention(q, qk, qv, table, lens, interpret=True,
                          k_scales=ks, v_scales=vs)
    assert last_attention_path() == "pallas"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert np.all(np.asarray(out)[0] == 0.0)  # empty slot: zeros, not NaN
    assert np.isfinite(np.asarray(out)).all()
    full = np.asarray(paged_attention_reference(q, k, v, table, lens))
    err = np.max(np.abs(np.asarray(out) - full))
    assert err < ATT_TOL[kv_dtype], (kv_dtype, err)


def _rand_paged_verify(rs, b, h, s, d, page_size, max_pages, starts):
    num_pages = 1 + b * max_pages
    q = rs.randn(b, h, s, d).astype(np.float32)
    k = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    v = rs.randn(num_pages, page_size, h, d).astype(np.float32)
    table = np.zeros((b, max_pages), np.int32)
    nxt = 1
    for i, st in enumerate(starts):
        for p in range((st + s + page_size - 1) // page_size):
            table[i, p] = nxt
            nxt += 1
    return q, k, v, table, np.asarray(starts, np.int32)


@pytest.mark.parametrize("kv_dtype", QDTYPES)
@pytest.mark.parametrize("page_size", [8, 16, 64])
def test_paged_verify_quant_parity(page_size, kv_dtype):
    """The quantized multi-query verify kernel == its quantized reference
    across ragged chunk starts (including start 0: no committed history),
    and within the dtype envelope of the full-precision verify."""
    _need(kv_dtype)
    rs = np.random.RandomState(page_size)
    b, h, s, d, max_pages = 4, 4, 4, 16, 4
    starts = [0, 1, page_size - 1, 2 * page_size + 3]
    q, k, v, table, st = _rand_paged_verify(rs, b, h, s, d, page_size,
                                            max_pages, starts)
    qk, ks, qv, vs = _quant_pools(k, v, kv_dtype)
    ref = paged_attention_verify_reference(q, qk, qv, table, st,
                                           k_scales=ks, v_scales=vs)
    out = paged_attention_verify(q, qk, qv, table, st, interpret=True,
                                 k_scales=ks, v_scales=vs)
    assert last_attention_path() == "pallas"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert np.isfinite(np.asarray(out)).all()
    full = np.asarray(paged_attention_verify_reference(q, k, v, table, st))
    err = np.max(np.abs(np.asarray(out) - full))
    assert err < ATT_TOL[kv_dtype], (kv_dtype, err)


@pytest.mark.parametrize("kv_dtype", QDTYPES)
def test_paged_attention_quant_garbage_isolation(kv_dtype):
    """Stored rows past a slot's length AND whole pages outside every
    table (stale pool content, poisoned scales included) must not leak
    into any output — the masks run before the dequant contributes."""
    _need(kv_dtype)
    rs = np.random.RandomState(3)
    q, k, v, table, lens = _rand_paged(rs, 1, 2, 8, 8, 2, [9])
    qk, ks, qv, vs = _quant_pools(k, v, kv_dtype)
    out1 = np.asarray(paged_attention(q, qk, qv, table, lens,
                                      interpret=True, k_scales=ks,
                                      v_scales=vs))
    qk2, qv2 = np.asarray(qk).copy(), np.asarray(qv).copy()
    ks2, vs2 = np.asarray(ks).copy(), np.asarray(vs).copy()
    # beyond token 9 inside the referenced second page (scale untouched:
    # rescaling the page would legitimately change the live rows)
    qk2[table[0, 1], 2:] = qk2.dtype.type(60)
    qv2[table[0, 1], 2:] = qv2.dtype.type(-60)
    # every page no table references, rows and scales both poisoned
    used = set(table.flatten().tolist())
    for p in range(qk2.shape[0]):
        if p not in used:
            qk2[p] = qk2.dtype.type(77)
            qv2[p] = qv2.dtype.type(-77)
            ks2[p] = 1e6
            vs2[p] = 1e6
    out2 = np.asarray(paged_attention(q, qk2, qv2, table, lens,
                                      interpret=True, k_scales=ks2,
                                      v_scales=vs2))
    np.testing.assert_allclose(out1, out2, atol=1e-6)
    # and the verify kernel under the same poisoning
    qv_q = rs.randn(1, 2, 3, 8).astype(np.float32)
    st = np.asarray([6], np.int32)
    o1 = np.asarray(paged_attention_verify(qv_q, qk, qv, table, st,
                                           interpret=True, k_scales=ks,
                                           v_scales=vs))
    o2 = np.asarray(paged_attention_verify(qv_q, qk2, qv2, table, st,
                                           interpret=True, k_scales=ks2,
                                           v_scales=vs2))
    np.testing.assert_allclose(o1, o2, atol=1e-6)


# -- quantization primitives (utils.quant) ------------------------------------


@pytest.mark.parametrize("kv_dtype", QDTYPES)
def test_quantize_roundtrip_bound_and_empty_pages(kv_dtype):
    """quantize -> dequantize stays inside the symmetric-quantization error
    bound per (page, head) block; all-zero pages round-trip exactly with
    scale 0 (the empty-page convention)."""
    _need(kv_dtype)
    rs = np.random.RandomState(0)
    pages = rs.randn(5, 8, 4, 16).astype(np.float32) * 3.0
    pages[2] = 0.0                                    # an empty page
    q, s = quant.quantize_kv_pages(pages, kv_dtype)
    deq = np.asarray(quant.dequantize_kv_pages(q, s))
    s = np.asarray(s)
    assert s.shape == (5, 4)
    assert (s[2] == 0.0).all() and (deq[2] == 0.0).all()
    # int8: half-step absolute bound per block; e4m3: ~2^-3 relative
    amax = np.abs(pages).max(axis=(1, 3))             # [pages, H]
    bound = (s * 0.5 + 1e-6 if kv_dtype == "int8"
             else amax * 2.0 ** -3 + 1e-6)
    err = np.abs(deq - pages).max(axis=(1, 3))
    assert (err <= bound).all(), (err, bound)


@pytest.mark.parametrize("kv_dtype", QDTYPES)
def test_paged_quant_append_running_scale(kv_dtype):
    """The append path maintains a per-page running absmax: growing rows
    rescale the page's stored history in place (old rows still dequantize
    to their values), and a row landing at offset 0 RESETS the page's
    scale — stale content from the page's previous tenant never poisons
    the new sequence's precision."""
    _need(kv_dtype)
    store, _ = quant.kv_pool_dtype(kv_dtype)
    L, P, page, h, d = 1, 3, 4, 2, 4
    pool = jnp.zeros((L, P, page, h, d), store)
    scales = jnp.zeros((L, P, h), jnp.float32)
    rs = np.random.RandomState(1)
    r0 = rs.randn(1, h, d).astype(np.float32) * 0.1   # small opener
    r1 = rs.randn(1, h, d).astype(np.float32) * 0.1
    big = rs.randn(1, h, d).astype(np.float32) * 8.0  # scale-growing row
    pid = jnp.asarray([1], jnp.int32)
    pool, scales = quant.paged_quant_append(pool, scales, 0, pid,
                                            jnp.asarray([0], jnp.int32), r0)
    pool, scales = quant.paged_quant_append(pool, scales, 0, pid,
                                            jnp.asarray([1], jnp.int32), r1)
    small_scale = float(np.asarray(scales)[0, 1].max())
    pool, scales = quant.paged_quant_append(pool, scales, 0, pid,
                                            jnp.asarray([2], jnp.int32), big)
    grown = float(np.asarray(scales)[0, 1].max())
    assert grown > small_scale * 4                    # the max really grew
    def atol(vals, scale):
        # int8: half a quantization step (+ rescale slop); e4m3: relative
        # ulp of the stored magnitude
        if kv_dtype == "int8":
            return scale * 0.5 + 0.02
        return float(np.abs(vals).max()) * 0.07 + 0.02

    deq = np.asarray(quant.dequantize_kv_pages(pool[0, 1], scales[0, 1]))
    np.testing.assert_allclose(deq[0], r0[0], atol=atol(r0, grown))
    np.testing.assert_allclose(deq[1], r1[0], atol=atol(r1, grown))
    np.testing.assert_allclose(deq[2], big[0], atol=atol(big, grown))
    # page reuse: offset 0 resets the running max to the new tenant's
    pool, scales = quant.paged_quant_append(pool, scales, 0, pid,
                                            jnp.asarray([0], jnp.int32), r1)
    reset = float(np.asarray(scales)[0, 1].max())
    assert reset < grown / 4, (reset, grown)
    deq = np.asarray(quant.dequantize_kv_pages(pool[0, 1], scales[0, 1]))
    np.testing.assert_allclose(deq[0], r1[0], atol=atol(r1, reset))
    # untouched pages never moved
    assert (np.asarray(scales)[0, [0, 2]] == 0.0).all()
    assert (np.asarray(pool)[0, [0, 2]].astype(np.float32) == 0.0).all()


def test_paged_quant_write_pages_matches_quantize():
    """The prefill ladder's whole-page commit is exactly the block
    quantizer applied per page, rows and scale entries both."""
    rs = np.random.RandomState(2)
    fresh = rs.randn(2, 4, 2, 4).astype(np.float32)
    pool = jnp.zeros((1, 5, 4, 2, 4), jnp.int8)
    scales = jnp.zeros((1, 5, 2), jnp.float32)
    pids = jnp.asarray([1, 3], jnp.int32)
    pool, scales = quant.paged_quant_write_pages(pool, scales, 0, pids,
                                                 fresh)
    q_ref, s_ref = quant.quantize_kv_pages(fresh, "int8")
    np.testing.assert_array_equal(np.asarray(pool)[0, [1, 3]],
                                  np.asarray(q_ref))
    np.testing.assert_allclose(np.asarray(scales)[0, [1, 3]],
                               np.asarray(s_ref))
    assert (np.asarray(pool)[0, [0, 2, 4]] == 0).all()


def test_kv_pool_dtype_validation(monkeypatch):
    with pytest.raises(ValueError, match="not quantized"):
        quant.kv_pool_dtype("bf16")
    with pytest.raises(ValueError, match="kv_dtype"):
        quant.kv_pool_dtype("int4")
    monkeypatch.setattr(quant, "_FP8_DTYPE", None)
    assert not quant.kv_quant_supported("fp8")
    with pytest.raises(ValueError, match="float8_e4m3fn"):
        quant.kv_pool_dtype("fp8")


# -- quantized page pool: byte accounting + churn neutrality ------------------


def test_kvcache_quantized_stats_and_validation():
    kv = PagedKVCache(num_pages=9, page_size=8, num_slots=2,
                      max_pages_per_slot=4, kv_dtype="int8",
                      kv_bytes_per_page=1088)
    st = kv.stats()
    assert st["kv_dtype"] == "int8" and st["kv_bytes_per_page"] == 1088
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedKVCache(num_pages=9, page_size=8, num_slots=2,
                     max_pages_per_slot=4, kv_dtype="int4")


def test_kvcache_quantized_no_leak_under_spec_churn():
    """200 iterations of speculative append-k / accept-a / truncate churn
    with prefix sharing on an int8-layout pool: the manager is byte-layout
    agnostic, so refcount conservation and full drain must hold exactly as
    they do for bf16 — quantization changes page CONTENT, never page
    accounting."""
    kv = PagedKVCache(num_pages=33, page_size=4, num_slots=4,
                      max_pages_per_slot=8, kv_dtype="int8",
                      kv_bytes_per_page=144)
    rs = np.random.RandomState(4)
    prefixes = [list(rs.randint(1, 50, size=8)) for _ in range(2)]
    live = {}
    for _ in range(200):
        slot = kv.free_slot()
        if slot is not None and rs.rand() < 0.5:
            pref = prefixes[rs.randint(len(prefixes))]
            prompt = pref + [int(x) for x in
                             rs.randint(1, 50, size=rs.randint(1, 5))]
            total = len(prompt) + int(rs.randint(4, 12))
            if kv.can_admit(total, prompt):
                kv.alloc(slot, prompt, total)
                kv.commit_prefix(slot, prompt)
                live[slot] = total
        for s in list(live):
            ln, total = kv.length(s), live[s]
            room = total - ln
            if room <= 0 or rs.rand() < 0.2:
                kv.free(s)
                del live[s]
                continue
            k = int(min(room, 1 + rs.randint(4)))      # speculative window
            kv.append(s, k)
            a = int(rs.randint(1, k + 1))              # accepted prefix
            kv.truncate(s, ln + a)
        rc = kv.refcounts()
        assert (rc >= 0).all()
        tables = kv.page_tables()
        held = int(np.count_nonzero(tables[sorted(live)])) if live else 0
        assert int(rc.sum()) == held, "refcount conservation broken"
    for s in list(live):
        kv.free(s)
    st = kv.stats()
    assert st["pages_used"] == 0 and st["pages_reserved"] == 0
    assert st["pages_free"] == 32 and st["tokens"] == 0
    assert (kv.refcounts() == 0).all()
    assert st["kv_dtype"] == "int8"


# -- quantized decode engine --------------------------------------------------


VOCAB = 61


@pytest.fixture(scope="module")
def lm():
    spec = build_registry_spec("transformer_lm", vocab_size=VOCAB, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=32, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def engine_q8(lm):
    """One int8 engine for the section with speculation AND chunked prefill
    on — every decode feature rides the quantized pool."""
    model, params = lm
    yield DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8, spec_k=3, kv_quant="int8")


def _dense_greedy(model, params, prompt, n):
    ids = list(prompt)
    out = []
    for _ in range(n):
        x = np.asarray(ids, np.int32)[None, :]
        logits = model.apply(params, {"input_ids": x}, ["logits"])["logits"]
        nxt = int(np.argmax(np.asarray(logits[0, -1])))
        out.append(nxt)
        ids.append(nxt)
    return out


def _engine_greedy(eng, prompt, n):
    info = eng.prefill(prompt, max_new_tokens=n, temperature=0.0)
    toks = [] if info["token"] is None else [info["token"]]
    while len(toks) < n:
        out = eng.step()
        if info["slot"] in out:
            toks.extend(out[info["slot"]])
    eng.release(info["slot"])
    return toks[:n], info


@pytest.mark.slow  # ~38s: full greedy battery on the shared engine; run by
# path (make kvquant-smoke) when touching the quantized decode plane
def test_quant_engine_greedy_parity_battery(engine_q8, lm):
    """int8 KV greedy decode is token-identical to the dense forward across
    a plain prompt, a prefix-publishing prompt, a chunked-admission prompt,
    and a prefix-COW replay — speculation on throughout, zero steady-state
    retraces. The quantization error moves logits by ~1e-4 here, far below
    any greedy argmax margin, so the text must not move at all."""
    model, params = lm
    eng = engine_q8
    sysp = [11, 3, 5, 8, 2, 9, 4, 6, 1, 13, 12, 10]
    prompts = [[5, 2, 8],            # plain short
               sysp + [17, 18],      # publishes the shared prefix blocks
               list(range(1, 25))]   # 24 tokens: chunked admission
    for p in prompts:
        toks, _ = _engine_greedy(eng, p, 6)
        assert toks == _dense_greedy(model, params, p, 6), f"diverged on {p}"
    # replay: COW prefix hit on the QUANTIZED pool + speculation — the
    # shared pages are reused as stored int8 rows + scales, byte-identical
    toks, info = _engine_greedy(eng, sysp + [17, 18], 6)
    assert info["shared_tokens"] == 8
    assert toks == _dense_greedy(model, params, sysp + [17, 18], 6)
    st = eng.stats()
    assert st["steady_traces"] == 0, (
        f"quantized decode retraced after warmup: {st}")
    assert st["spec"]["steps"] > 0
    assert eng.kv.stats()["prefix_hits"] >= 1


def test_quant_engine_stats_bytes_and_error_probe(engine_q8, lm):
    """The engine self-reports its pool layout: kv_quant in stats, byte
    accounting showing >= 1.9x pages-per-byte vs the float pool, and the
    warmup error probe pinned a finite, small max-logit delta vs bf16."""
    model, _ = lm
    st = engine_q8.stats()
    assert st["kv_quant"] == "int8"
    kv = st["kv"]
    assert kv["kv_dtype"] == "int8"
    cdt = model.compute_dtype if model.compute_dtype is not None \
        else jnp.float32
    float_bpp = (2 * int(model.num_layers) * engine_q8.page_size
                 * int(model.num_heads) * int(model.head_dim)
                 * np.dtype(cdt).itemsize)
    assert float_bpp >= 1.9 * kv["kv_bytes_per_page"], (
        "int8 pool must fit >= 1.9x the pages per byte: "
        f"{float_bpp} vs {kv['kv_bytes_per_page']}")
    err = st["kv_quant_error"]
    assert err is not None and np.isfinite(err) and 0.0 <= err < 0.05
    assert engine_q8.metrics.summary()["gauges"]["decode/kv_quant_error"] \
        == err


def test_quant_engine_pool_neutral_accept_reject(engine_q8):
    """Speculative accept/reject churn on the quantized pool drains
    page-neutral: after releasing every request the pool is back to its
    baseline free count (rollback truncates return quantized pages to the
    allocator unchanged)."""
    eng = engine_q8
    base = eng.kv.stats()
    assert base["pages_used"] == 0
    rs = np.random.RandomState(7)
    for _ in range(6):
        prompts = [[int(x) for x in rs.randint(1, VOCAB, size=rs.randint(
            1, 9))] for _ in range(3)]
        infos = [eng.prefill(p, max_new_tokens=16, temperature=0.0)
                 for p in prompts]
        for _ in range(3):                 # spec bursts: up to k+1 per step
            eng.step()
        for i in infos:
            eng.release(i["slot"])
    st = eng.kv.stats()
    assert st["pages_used"] == 0 and st["pages_reserved"] == 0
    assert st["pages_free"] == base["pages_free"]
    assert st["slots_active"] == 0
    assert eng.stats()["steady_traces"] == 0


@pytest.mark.slow  # ~14s: second engine build; run by path (kvquant-smoke)
def test_fp8_engine_greedy_parity(lm):
    """An fp8 pool serves greedy text identical to the dense forward on
    short prompts (e4m3's ~3% relative error still clears this model's
    argmax margins) with zero steady retraces."""
    _need("fp8")
    model, params = lm
    eng = DecodeEngine(model, params, num_slots=2, page_size=8, seed=0,
                       kv_quant="fp8")
    for p in ([5, 2, 8], [4, 4]):
        toks, _ = _engine_greedy(eng, p, 6)
        assert toks == _dense_greedy(model, params, p, 6), f"diverged on {p}"
    st = eng.stats()
    assert st["kv_quant"] == "fp8" and st["steady_traces"] == 0
    assert st["kv"]["kv_dtype"] == "fp8"


@pytest.mark.slow  # ~22s: pp2xtp2 mesh engine build; run by path
# (make kvquant-smoke) when touching the quantized decode plane
def test_quant_composition_pp_tp_spec_prefix_chunked_parity(lm):
    """The full stack at once: int8 pool + speculation + prefix cache +
    chunked prefill on a 2D pp x tp mesh. Rows shard on heads (tp) and
    layers (pp); scales shard on heads and layers with no page axis —
    greedy output stays token-identical to the dense forward, zero steady
    retraces, and the pool reports its quantized layout."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices (conftest forces 8 on CPU)")
    model, params = lm
    mesh2d = make_mesh({"pp": 2, "tp": 2}, devices=jax.devices()[:4])
    eng = DecodeEngine(model, params, num_slots=4, page_size=8, seed=0,
                       prefill_chunk=8, spec_k=3, kv_quant="int8",
                       mesh=mesh2d,
                       sharding=ShardingConfig(pp_axis="pp", tp_axis="tp"))
    sysp = [11, 3, 5, 8, 2, 9, 4, 6, 1, 13, 12, 10]
    for p in ([5, 2, 8], sysp + [17, 18], list(range(1, 25))):
        toks, _ = _engine_greedy(eng, p, 6)
        assert toks == _dense_greedy(model, params, p, 6), f"diverged on {p}"
    toks, info = _engine_greedy(eng, sysp + [17, 18], 6)
    assert info["shared_tokens"] == 8
    assert toks == _dense_greedy(model, params, sysp + [17, 18], 6)
    st = eng.stats()
    assert st["steady_traces"] == 0
    assert st["spec"]["steps"] > 0
    assert st["kv_quant"] == "int8"
    par = st["parallel"]
    assert par["pp"] == 2 and par["tp"] == 2


def test_quant_ctor_validation(lm, monkeypatch):
    """Misconfigurations surface at construction, before any compile."""
    model, params = lm
    with pytest.raises(ValueError, match="kv_quant"):
        DecodeEngine(model, params, num_slots=2, page_size=8,
                     kv_quant="int4", warmup=False)
    monkeypatch.setattr(quant, "_FP8_DTYPE", None)
    with pytest.raises(ValueError, match="float8_e4m3fn"):
        DecodeEngine(model, params, num_slots=2, page_size=8,
                     kv_quant="fp8", warmup=False)


def test_dense_cache_quant_parity(lm):
    """The non-paged decode cache also quantizes: init_decode_cache with a
    kv_dtype carries int8/fp8 rows + per-row scales, and token-by-token
    decode stays greedy-identical to the float cache."""
    model, params = lm
    prompt = [3, 9, 4, 1, 7]
    refs = _dense_greedy(model, params, prompt, 4)
    for kv_dtype in QDTYPES:
        if not quant.kv_quant_supported(kv_dtype):
            continue
        cache = model.init_decode_cache(1, max_len=16, kv_dtype=kv_dtype)
        assert "k_scale" in cache and "v_scale" in cache
        store, _ = quant.kv_pool_dtype(kv_dtype)
        assert cache["k"].dtype == store
        ids = list(prompt)
        logits = None
        for pos in range(len(prompt)):
            tok = jnp.asarray([ids[pos]], jnp.int32)
            logits, cache = model.decode_step(
                params, cache, tok, jnp.asarray([pos], jnp.int32))
        out = []
        for j in range(4):
            nxt = int(np.argmax(np.asarray(logits[0])))
            out.append(nxt)
            ids.append(nxt)
            tok = jnp.asarray([nxt], jnp.int32)
            logits, cache = model.decode_step(
                params, cache, tok,
                jnp.asarray([len(ids) - 1], jnp.int32))
        assert out == refs, f"{kv_dtype} dense cache diverged"
