"""Preemption guard: turn SIGTERM into a clean checkpoint-and-return.

TPU VMs are routinely preempted (maintenance events, spot reclaim) with a
SIGTERM and a short grace window. The reference had no story for this at all
(SURVEY.md §5: drop-and-print); here the Trainer checks the guard at every
epoch/step boundary and, when a signal arrived, saves a checkpoint and
returns the partial result — the next ``fit`` on the same ``checkpoint_dir``
resumes exactly where it stopped (same rng stream, optimizer state).

Only installed while a fit with a configured ``checkpoint_dir`` is running;
outside that window signals keep their default behavior.
"""

from __future__ import annotations

import logging
import signal
import threading

logger = logging.getLogger("sparkflow_tpu")


class PreemptionGuard:
    """Context manager: latches SIGTERM (and optionally other signals) into
    a flag instead of killing the process. Main-thread only (CPython routes
    signals to the main thread); elsewhere it degrades to a no-op guard."""

    def __init__(self, signals=(signal.SIGTERM,), on_signal=None):
        self._signals = tuple(signals)
        self._previous = {}
        self.requested = False
        self._armed = False
        # optional hook fired from the handler after the latch is set —
        # serving uses it to flip its lifecycle to DRAINING; must itself be
        # async-signal-tolerant (no locks the interrupted thread may hold)
        self._on_signal = on_signal

    def _handler(self, signum, frame):
        self.requested = True
        logger.warning("signal %d received: will checkpoint and stop at the "
                       "next epoch boundary", signum)
        if self._on_signal is not None:
            self._on_signal(signum)

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handler)
            self._armed = True
        return self

    def __exit__(self, *exc):
        if self._armed:
            for s, prev in self._previous.items():
                signal.signal(s, prev)
            self._previous.clear()
            self._armed = False
        return False


class NullGuard:
    """No-op stand-in when no checkpoint_dir is configured."""

    requested = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
