"""Unified observability: spans, trace assembly, flight recorder, exporters.

- :mod:`~sparkflow_tpu.obs.spans` — ``Span``/``Tracer``: nested host-side
  timing with Chrome-trace / JSONL export and cross-thread propagation,
  plus ``TraceContext``: the W3C-traceparent-style context that carries a
  trace across processes.
- :mod:`~sparkflow_tpu.obs.collector` — ``TraceCollector``: router-side
  tail-sampled assembly of cross-process request timelines (one waterfall
  per kept request, Chrome-trace / JSONL export).
- :mod:`~sparkflow_tpu.obs.flight` — ``FlightRecorder``: always-on bounded
  crash flight recorder, dumped on SIGTERM/atexit and harvested by the
  ``ReplicaManager`` when a replica dies.
- :mod:`~sparkflow_tpu.obs.stepstats` — ``StepStats``: per-step phase
  breakdown (transfer / compile / step / metrics / checkpoint) + derived
  throughput and MFU gauges for ``Trainer.fit``.
- :mod:`~sparkflow_tpu.obs.exporters` — ``prometheus_text`` exposition of
  the whole metrics registry and the ``MemoryWatcher`` device-memory
  sampler.

See ``docs/observability.md`` for the end-to-end walkthrough.
"""

from .spans import (Span, TraceContext, Tracer, current_tracer,
                    default_tracer, span)
from .stepstats import StepStats
from .collector import TraceCollector, trace_spans
from .flight import FlightRecorder, harvest_flight
from .exporters import MemoryWatcher, prometheus_name, prometheus_text

__all__ = [
    "Span", "TraceContext", "Tracer", "current_tracer", "default_tracer",
    "span",
    "StepStats",
    "TraceCollector", "trace_spans",
    "FlightRecorder", "harvest_flight",
    "MemoryWatcher", "prometheus_name", "prometheus_text",
]
