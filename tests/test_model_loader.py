"""Pre-trained model import: npz side-files, TF1 Saver checkpoints.

TF1 import mirrors the reference capability
(``/root/reference/sparkflow/tensorflow_model_loader.py:8-32``): a Saver
checkpoint's trainable variables become a served model's weights. Here the
graph must be re-expressed in the nn DSL (TF1 protobufs don't execute on this
framework) and weights are read straight off the checkpoint shards.
"""

import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.model_loader import (extract_tensorflow_weights,
                                        load_checkpoint_model,
                                        load_tensorflow_model,
                                        save_weights_npz)


def mlp_graph():
    x = nn.placeholder([None, 4], name="x")
    h = nn.dense(x, 3, activation="relu")
    out = nn.dense(h, 2, name="out")
    nn.mean_squared_error(x, out)  # loss unused for serving


def _ref_weights(seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randn(4, 3).astype(np.float32), rs.randn(3).astype(np.float32),
            rs.randn(3, 2).astype(np.float32), rs.randn(2).astype(np.float32)]


def _manual_forward(w, x):
    h = np.maximum(x @ w[0] + w[1], 0.0)
    return h @ w[2] + w[3]


def test_npz_checkpoint_model_roundtrip(tmp_path):
    w = _ref_weights()
    p = str(tmp_path / "w.npz")
    save_weights_npz(p, w)
    model = load_checkpoint_model(p, build_graph(mlp_graph), "features",
                                  "x:0", "out/BiasAdd:0")
    from sparkflow_tpu.localml import LocalSession, Vectors
    spark = LocalSession.builder.getOrCreate()
    x = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    df = spark.createDataFrame([(Vectors.dense(r),) for r in x], ["features"])
    preds = np.stack([np.asarray(r["predicted"].toArray())
                      for r in model.transform(df).collect()])
    np.testing.assert_allclose(preds, _manual_forward(w, x), rtol=1e-5,
                               atol=1e-5)


@pytest.fixture(scope="module")
def tf1_checkpoint(tmp_path_factory):
    tf = pytest.importorskip("tensorflow")
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    w = _ref_weights(seed=7)
    g = tf1.Graph()
    d = tmp_path_factory.mktemp("tfckpt")
    prefix = str(d / "to_load")
    with g.as_default(), tf1.Session(graph=g) as sess:
        # TF1-layer naming convention: dense/kernel, dense/bias, dense_1/...
        with tf1.variable_scope("dense"):
            tf1.get_variable("kernel", initializer=w[0])
            tf1.get_variable("bias", initializer=w[1])
        with tf1.variable_scope("dense_1"):
            tf1.get_variable("kernel", initializer=w[2])
            tf1.get_variable("bias", initializer=w[3])
        # an optimizer slot variable that must NOT be imported
        with tf1.variable_scope("dense/kernel"):
            tf1.get_variable("Adam", initializer=np.zeros((4, 3), np.float32))
        sess.run(tf1.global_variables_initializer())
        tf1.train.Saver().save(sess, prefix)
    return prefix, w


def test_extract_tf_weights_order_and_slot_filtering(tf1_checkpoint):
    prefix, w = tf1_checkpoint
    got = extract_tensorflow_weights(prefix)
    assert len(got) == 4  # Adam slot excluded
    for a, b in zip(got, w):
        np.testing.assert_array_equal(a, b)


def test_load_tensorflow_model_serves_checkpoint_weights(tf1_checkpoint):
    prefix, w = tf1_checkpoint
    model = load_tensorflow_model(prefix, "features", "x:0", "out/BiasAdd:0",
                                  graph_json=build_graph(mlp_graph))
    from sparkflow_tpu.localml import LocalSession, Vectors
    spark = LocalSession.builder.getOrCreate()
    x = np.random.RandomState(2).randn(5, 4).astype(np.float32)
    df = spark.createDataFrame([(Vectors.dense(r),) for r in x], ["features"])
    preds = np.stack([np.asarray(r["predicted"].toArray())
                      for r in model.transform(df).collect()])
    np.testing.assert_allclose(preds, _manual_forward(w, x), rtol=1e-5,
                               atol=1e-5)


def test_load_tensorflow_model_requires_graph_when_no_meta(tf1_checkpoint,
                                                           tmp_path):
    """Without a .meta next to the checkpoint (and no graph_json), the error
    is explicit. (With a .meta, the metagraph itself becomes the serving
    graph — tests/test_tf1_compat.py.)"""
    import shutil
    prefix, _ = tf1_checkpoint
    stripped = str(tmp_path / "to_load")
    for suf in (".index", ".data-00000-of-00001"):
        shutil.copy(prefix + suf, stripped + suf)
    with pytest.raises(ValueError, match="graph_json is required"):
        load_tensorflow_model(stripped, "features", "x:0", "out:0")


def test_load_tensorflow_model_shape_mismatch_message(tf1_checkpoint):
    prefix, _ = tf1_checkpoint

    def wrong_graph():
        x = nn.placeholder([None, 9], name="x")
        out = nn.dense(x, 2, name="out")
        nn.mean_squared_error(x, out)

    with pytest.raises(ValueError, match="var_order"):
        load_tensorflow_model(prefix, "features", "x:0", "out/BiasAdd:0",
                              graph_json=build_graph(wrong_graph))


def test_explicit_var_order(tf1_checkpoint):
    prefix, w = tf1_checkpoint
    got = extract_tensorflow_weights(
        prefix, var_order=["dense_1/kernel", "dense_1/bias"])
    np.testing.assert_array_equal(got[0], w[2])
    np.testing.assert_array_equal(got[1], w[3])
    with pytest.raises(KeyError):
        extract_tensorflow_weights(prefix, var_order=["nope/kernel"])


def test_shape_matching_survives_nonalphabetical_scopes(tmp_path):
    """Hand-named scopes that sort against creation order must still land in
    the right graph slots (shape-driven assignment)."""
    tf = pytest.importorskip("tensorflow")
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    rs = np.random.RandomState(3)
    w = [rs.randn(4, 3).astype(np.float32), rs.randn(3).astype(np.float32),
         rs.randn(3, 2).astype(np.float32), rs.randn(2).astype(np.float32)]
    g = tf1.Graph()
    prefix = str(tmp_path / "named")
    with g.as_default(), tf1.Session(graph=g) as sess:
        # creation order: zebra (layer 1) then alpha (layer 2) — alphabetical
        # sorting would swap them; shapes differ, so matching fixes it
        with tf1.variable_scope("zebra"):
            tf1.get_variable("kernel", initializer=w[0])
            tf1.get_variable("bias", initializer=w[1])
        with tf1.variable_scope("alpha"):
            tf1.get_variable("kernel", initializer=w[2])
            tf1.get_variable("bias", initializer=w[3])
        sess.run(tf1.global_variables_initializer())
        tf1.train.Saver().save(sess, prefix)

    model = load_tensorflow_model(prefix, "features", "x:0", "out/BiasAdd:0",
                                  graph_json=build_graph(mlp_graph))
    from sparkflow_tpu.localml import LocalSession, Vectors
    spark = LocalSession.builder.getOrCreate()
    x = np.random.RandomState(4).randn(5, 4).astype(np.float32)
    df = spark.createDataFrame([(Vectors.dense(r),) for r in x], ["features"])
    preds = np.stack([np.asarray(r["predicted"].toArray())
                      for r in model.transform(df).collect()])
    np.testing.assert_allclose(preds, _manual_forward(w, x), rtol=1e-5,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# torch state_dict import (load_torch_model / extract_torch_weights)
# ---------------------------------------------------------------------------

def test_torch_mlp_import_matches_torch_forward(tmp_path):
    """A real torch MLP's state_dict imports (with automatic Linear
    transpose) and the served predictions match torch's forward."""
    torch = pytest.importorskip("torch")

    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.model_loader import load_torch_model
    from sparkflow_tpu.localml import LocalSession, Vectors

    torch.manual_seed(0)
    net = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(),
        torch.nn.Linear(8, 2), torch.nn.Sigmoid())
    path = str(tmp_path / "mlp.pt")
    torch.save(net.state_dict(), path)

    def graph():
        x = nn.placeholder([None, 4], name="x")
        h = nn.dense(x, 8, activation="relu")
        nn.dense(h, 2, activation="sigmoid", name="out")

    model = load_torch_model(path, build_graph(graph), inputCol="features",
                             tfInput="x:0", tfOutput="out:0",
                             predictionCol="p")
    rs = np.random.RandomState(0)
    X = rs.randn(6, 4).astype(np.float32)
    with torch.no_grad():
        expect = net(torch.from_numpy(X)).numpy()

    spark = LocalSession.builder.getOrCreate()
    df = spark.createDataFrame([(Vectors.dense(x),) for x in X], ["features"])
    got = np.stack([np.asarray(r["p"].toArray())
                    for r in model.transform(df).collect()])
    np.testing.assert_allclose(got, expect, atol=1e-5)


def test_torch_conv_import_oihw_to_hwio(tmp_path):
    """torch conv weights (OIHW) permute to this framework's HWIO."""
    torch = pytest.importorskip("torch")

    import jax
    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.graphdef import list_to_params
    from sparkflow_tpu.model_loader import extract_torch_weights
    from sparkflow_tpu.models import model_from_json

    torch.manual_seed(1)
    net = torch.nn.Sequential(
        torch.nn.Conv2d(1, 3, 3, padding=1), torch.nn.ReLU(),
        torch.nn.Flatten(), torch.nn.Linear(3 * 16, 2))
    path = str(tmp_path / "cnn.pt")
    torch.save(net.state_dict(), path)

    def graph():
        x = nn.placeholder([None, 4, 4, 1], name="x")
        c = nn.conv2d(x, 3, 3, padding="same", activation="relu")
        nn.dense(nn.flatten(c), 2, name="out")

    gj = build_graph(graph)
    weights = extract_torch_weights(path, gj)
    m = model_from_json(gj)
    params = list_to_params(m, weights)

    rs = np.random.RandomState(2)
    X = rs.randn(2, 4, 4, 1).astype(np.float32)
    ours = np.asarray(m.apply(params, {"x": X}, ["out:0"])["out:0"])
    with torch.no_grad():
        # torch is NCHW; flatten order differs (CHW vs HWC), so compare
        # through torch's own flatten on the permuted activations instead:
        # just check the conv stage matches, then the linear is exact by
        # construction on matching flatten orders
        conv_t = net[1](net[0](torch.from_numpy(
            X.transpose(0, 3, 1, 2)))).numpy().transpose(0, 2, 3, 1)
    conv_ours = np.asarray(
        m.apply(params, {"x": X}, ["conv2d/Relu:0"])["conv2d/Relu:0"])
    np.testing.assert_allclose(conv_ours, conv_t, atol=1e-5)
    assert ours.shape == (2, 2)


def test_torch_import_shape_mismatch_fails_loudly(tmp_path):
    torch = pytest.importorskip("torch")

    import sparkflow_tpu.nn as nn
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.model_loader import extract_torch_weights

    torch.manual_seed(0)
    net = torch.nn.Linear(5, 3)
    path = str(tmp_path / "lin.pt")
    torch.save(net.state_dict(), path)

    def graph():
        x = nn.placeholder([None, 4], name="x")
        nn.dense(x, 2, name="out")

    with pytest.raises(ValueError, match="no torch state_dict tensor fits"):
        extract_torch_weights(path, build_graph(graph))


def test_tf1_batch_norm_moving_stats_import(tmp_path):
    """A TRAINED batch-norm model must serve with the checkpoint's moving
    statistics, matching a live tf.Session restore — the reference loses
    them (tensorflow_model_loader.py:23-24 imports trainables only; the
    import here bakes non-trainable state into the wire format)."""
    import warnings

    tf = pytest.importorskip("tensorflow")
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()

    rs = np.random.RandomState(3)
    mm_v = rs.randn(6).astype(np.float32)
    mv_v = (rs.rand(6) + 0.5).astype(np.float32)
    X = rs.randn(5, 4).astype(np.float32)

    g = tf1.Graph()
    prefix = str(tmp_path / "bn_model")
    with g.as_default(), tf1.Session(graph=g) as sess:
        x = tf1.placeholder(tf.float32, [None, 4], name="x")
        with tf1.variable_scope("dense"):
            k = tf1.get_variable("kernel",
                                 initializer=rs.randn(4, 6).astype(np.float32))
            b = tf1.get_variable("bias",
                                 initializer=rs.randn(6).astype(np.float32))
        h = tf1.nn.bias_add(tf1.matmul(x, k), b)
        with tf1.variable_scope("bn"):
            gamma = tf1.get_variable(
                "gamma", initializer=rs.randn(6).astype(np.float32))
            beta = tf1.get_variable(
                "beta", initializer=rs.randn(6).astype(np.float32))
            mm = tf1.get_variable("moving_mean", trainable=False,
                                  initializer=mm_v)
            mv = tf1.get_variable("moving_variance", trainable=False,
                                  initializer=mv_v)
        n, _, _ = tf1.nn.fused_batch_norm(
            tf.reshape(h, [-1, 1, 1, 6]), gamma, beta, mean=mm, variance=mv,
            is_training=False)
        tf1.identity(tf.reshape(n, [-1, 6]), name="out")
        sess.run(tf1.global_variables_initializer())
        tf_out = sess.run("out:0", {"x:0": X})  # live session, learned stats
        tf1.train.Saver().save(sess, prefix)

    # .meta becomes the serving graph; moving stats restore from the shards
    model = load_tensorflow_model(prefix, "features", "x:0", "out:0")

    from sparkflow_tpu.graphdef import list_to_params
    from sparkflow_tpu.ml_util import convert_json_to_weights
    from sparkflow_tpu.models import model_from_json

    m = model_from_json(model.getOrDefault(model.modelJson))
    params = list_to_params(m, convert_json_to_weights(
        model.getOrDefault(model.modelWeights)))
    with warnings.catch_warnings():
        # serving must NOT hit the fresh-init warning: stats are baked in
        warnings.simplefilter("error")
        out = np.asarray(m.apply(params, {"x": X}, ["out:0"])["out:0"])
    np.testing.assert_allclose(out, tf_out, atol=1e-5)


def test_bake_nontrainable_values_validation():
    """Baking rejects names that are not variable nodes in the graph."""
    from sparkflow_tpu.tf1_compat import bake_nontrainable_values

    tf = pytest.importorskip("tensorflow")
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    from google.protobuf import json_format
    g = tf1.Graph()
    with g.as_default():
        tf1.placeholder(tf.float32, [None, 2], name="x")
        mg = json_format.MessageToJson(tf1.train.export_meta_graph())
    with pytest.raises(ValueError, match="not a variable node"):
        bake_nontrainable_values(mg, {"x": np.zeros(2, np.float32)})
