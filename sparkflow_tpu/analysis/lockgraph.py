"""Whole-package lock-order & blocking-under-lock lint (GC-L304/305).

The per-class rules in :mod:`~sparkflow_tpu.analysis.locks` see one file at
a time; a lock-order inversion between ``membership.py`` and ``router.py``
is invisible to them. This pass parses EVERY file handed to it into one
model and reasons about the package as a whole:

- a **lock node** is one lock *identity*: ``module.Class._attr`` for an
  instance lock created in ``__init__`` (all instances of the class share
  the node, the standard conflation in lock-order analysis), or
  ``module:NAME`` for a module-level lock. ``threading.Condition(self._lock)``
  aliases to the wrapped lock's node.
- an **edge** L -> M means "some code path acquires M while holding L":
  either a nested ``with`` in one function, or a call made under L to a
  function that (transitively, through an approximate intra-package call
  graph) acquires M. Calls are resolved best-effort: ``self.m()``,
  ``self.attr.m()`` / ``local.m()`` where the attribute/local was assigned
  ``ClassName(...)`` of a known class, and bare ``f()`` to a same-module
  function. ``*_locked`` helpers scan with their class's locks assumed held
  (the GC-L303 convention), so edges through them land on their callers.

**GC-L304** reports every strongly-connected component of that graph — two
locks ever taken in opposite orders are a deadlock waiting for the right
interleaving — and re-acquisition of a non-reentrant lock through a call
chain (a self-cycle: the thread deadlocks against itself).

**GC-L305** reports blocking operations executed while any lock is held:
``time.sleep`` (and injectable ``*_sleep`` hooks), socket/HTTP I/O
(``getresponse``/``recv``/``connect``/``accept``/``sendall``/``urlopen``),
``Future.result()``, thread ``join()``, ``Event.wait()``,
``block_until_ready()``, and ``subprocess`` waits — directly or through a
resolved call chain. Holding a lock across a wait turns every peer thread's
bounded critical section into an unbounded one; under load that reads as a
stalled fleet. ``Condition.wait()`` on the class's own condition is exempt
(it *releases* the lock while waiting — that's the point of a condition).

Intentional sites (a chaos hook that sleeps under the store lock on
purpose) are allowlisted inline: ``# graftcheck: disable=GC-L305`` on the
flagged line, the same suppression syntax every AST analyzer honors.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .ast_lint import iter_py_files, _attr_chain
from .findings import Finding, parse_suppressions
from .locks import _LOCK_CTORS, _is_lock_ctor, _self_attr

__all__ = ["lint_paths", "build_graph", "LockGraph"]

#: attribute-call names that block the calling thread (see module docstring)
_BLOCKING_ATTRS = {"result", "getresponse", "recv", "recv_into", "accept",
                   "connect", "sendall", "communicate", "block_until_ready"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output"}
#: threading ctors that are waitable but NOT locks (Event.wait blocks while
#: Condition.wait releases) — tracked so `.wait()` receivers resolve
_EVENT_CTORS = {"Event", "Barrier"}


# ---------------------------------------------------------------------------
# package model
# ---------------------------------------------------------------------------


@dataclass
class _ClassInfo:
    name: str
    module: str
    path: str
    node: ast.ClassDef
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> ctor
    alias: Dict[str, str] = field(default_factory=dict)       # cond -> lock
    event_attrs: Set[str] = field(default_factory=set)
    #: attr -> candidate class names (every ctor mentioned in the assigned
    #: expression — `m if m else Metrics()` yields ["Metrics"]); resolution
    #: picks the first candidate that is a known class with the method
    attr_types: Dict[str, List[str]] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)

    def lock_node(self, attr: str) -> str:
        attr = self.alias.get(attr, attr)
        return f"{self.module}.{self.name}.{attr}"


@dataclass
class _Summary:
    """Per-function facts feeding the fixpoint."""
    acquires: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[Tuple[object, str, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    blocks: List[Tuple[str, str, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    edges: List[Tuple[str, str, str, int, str]] = field(default_factory=list)


class LockGraph:
    """The assembled model: lock nodes, ordering edges (with sites), and the
    raw per-function summaries — exposed so tests and docs can introspect
    what the lint saw."""

    def __init__(self):
        self.classes: Dict[str, Optional[_ClassInfo]] = {}  # bare name
        self.mod_funcs: Dict[Tuple[str, str], ast.AST] = {}
        self.mod_func_paths: Dict[Tuple[str, str], str] = {}
        self.mod_locks: Dict[Tuple[str, str], str] = {}     # -> ctor
        self.node_ctor: Dict[str, str] = {}                 # node -> ctor
        self.summaries: Dict[object, _Summary] = {}
        self.may_acquire: Dict[object, Set[str]] = {}
        self.may_block: Dict[object, Tuple[str, str]] = {}  # key -> (desc, via)
        # L -> M -> [(path, line, note)]
        self.edges: Dict[str, Dict[str, List[Tuple[str, int, str]]]] = {}

    def add_edge(self, src: str, dst: str, path: str, line: int,
                 note: str = "") -> None:
        self.edges.setdefault(src, {}).setdefault(dst, []).append(
            (path, line, note))


def _module_name(path: str) -> str:
    """Dotted module name from a file path (best effort: the trailing
    components from the last directory that lacks an __init__.py up)."""
    parts = os.path.normpath(path).split(os.sep)
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    # walk up while the directory is a package
    keep = [parts[-1]]
    d = os.path.dirname(os.path.normpath(path))
    while d and os.path.isfile(os.path.join(d, "__init__.py")):
        keep.append(os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(reversed(keep))


def _ctor_name(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


_TYPING_NOISE = {"Optional", "Union", "List", "Dict", "Set", "Tuple",
                 "Sequence", "Iterable", "Callable", "Any", "None", "str",
                 "int", "float", "bool", "bytes", "object", "type"}


def _ann_tokens(ann: ast.AST) -> List[str]:
    """Class-name candidates mentioned in a type annotation — handles
    ``Foo``, ``mod.Foo``, ``Optional[Foo]`` and string annotations
    (``engine: "DecodeEngine"``)."""
    import re
    toks: List[str] = []
    for n in ast.walk(ann):
        if isinstance(n, ast.Name):
            toks.append(n.id)
        elif isinstance(n, ast.Attribute):
            toks.append(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            toks.extend(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", n.value))
    return [t for t in toks if t not in _TYPING_NOISE]


def _index_class(cls: ast.ClassDef, module: str, path: str) -> _ClassInfo:
    info = _ClassInfo(cls.name, module, path, cls)
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        ctor = _ctor_name(node.value)
        for t in node.targets:
            attr = _self_attr(t)
            if attr is None:
                continue
            if _is_lock_ctor(node.value):
                info.lock_attrs[attr] = ctor or "Lock"
                # Condition(self._lock) shares the wrapped lock's identity
                if (ctor == "Condition" and node.value.args
                        and _self_attr(node.value.args[0]) is not None):
                    info.alias[attr] = _self_attr(node.value.args[0])
            elif ctor in _EVENT_CTORS:
                info.event_attrs.add(attr)
            else:
                cands = [c for c in (
                    _ctor_name(call) for call in ast.walk(node.value)
                    if isinstance(call, ast.Call)) if c is not None]
                if cands:
                    info.attr_types[attr] = cands
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[stmt.name] = stmt
            # annotated params stored onto self: `def __init__(self,
            # engine: "DecodeEngine")` + `self.engine = engine` types the
            # attribute (string annotations need no import, so they work
            # even where a real import would be circular)
            ann: Dict[str, List[str]] = {}
            a = stmt.args
            for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                if arg.annotation is not None:
                    toks = _ann_tokens(arg.annotation)
                    if toks:
                        ann[arg.arg] = toks
            if not ann:
                continue
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                if not (isinstance(node.value, ast.Name)
                        and node.value.id in ann):
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        info.attr_types.setdefault(attr, []).extend(
                            ann[node.value.id])
    return info


# ---------------------------------------------------------------------------
# per-function scan
# ---------------------------------------------------------------------------


def _blocking_desc(call: ast.Call, cls: Optional[_ClassInfo],
                   local_types: Dict[str, str]) -> Optional[str]:
    """A human-readable description if ``call`` blocks, else None."""
    fn = call.func
    attr = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if attr is None:
        return None
    chain = _attr_chain(fn)
    if attr == "sleep" or attr.endswith("_sleep"):
        return f"{'.'.join(chain) or attr}() sleeps"
    if chain and chain[0] == "subprocess" and attr in _SUBPROCESS_FNS:
        return f"subprocess.{attr}() waits on a child process"
    if attr == "urlopen":
        return "urlopen() performs network I/O"
    if attr in _BLOCKING_ATTRS:
        kind = {"result": "waits on a Future",
                "block_until_ready": "synchronizes with the device",
                "communicate": "waits on a child process"}.get(
                    attr, "performs socket/HTTP I/O")
        return f".{attr}() {kind}"
    recv = fn.value if isinstance(fn, ast.Attribute) else None
    if attr == "join":
        # str.join takes exactly one iterable positional; thread/process
        # join takes none or a numeric timeout
        if isinstance(recv, ast.Constant) and isinstance(recv.value, str):
            return None
        pos_ok = (not call.args
                  or (len(call.args) == 1
                      and isinstance(call.args[0], ast.Constant)
                      and isinstance(call.args[0].value, (int, float))))
        if pos_ok:
            return ".join() waits on a thread"
        return None
    if attr == "wait":
        # Condition.wait on this class's own condition RELEASES the lock
        if recv is not None:
            a = _self_attr(recv)
            if a is not None and cls is not None:
                if a in cls.lock_attrs:
                    return None
                if a in cls.event_attrs:
                    return ".wait() blocks on an Event"
                return None  # unknown attribute: don't guess
            if isinstance(recv, ast.Name):
                cands = local_types.get(recv.id, ())
                if any(t in _LOCK_CTORS for t in cands):
                    return None
                if any(t in _EVENT_CTORS for t in cands):
                    return ".wait() blocks on an Event"
        return None
    return None


def _scan_function(key, fn: ast.AST, graph: LockGraph,
                   cls: Optional[_ClassInfo], module: str, path: str,
                   assume_held: Tuple[str, ...] = ()) -> _Summary:
    s = _Summary()
    local_types: Dict[str, str] = {}

    def lock_node_of(expr: ast.AST) -> Optional[str]:
        """The lock node a ``with`` item acquires, or None."""
        if isinstance(expr, ast.Call):
            # with self._rw.reading(): / self._rw.w_locked(): -> node of _rw
            if isinstance(expr.func, ast.Attribute):
                inner = _self_attr(expr.func.value)
                if (inner is not None and cls is not None
                        and inner in cls.lock_attrs):
                    return cls.lock_node(inner)
            return None
        a = _self_attr(expr)
        if a is not None and cls is not None and a in cls.lock_attrs:
            return cls.lock_node(a)
        if isinstance(expr, ast.Name):
            if (module, expr.id) in graph.mod_locks:
                return f"{module}:{expr.id}"
        return None

    def resolve_call(call: ast.Call):
        """A summary key for the callee, or None."""
        fn_ = call.func
        if isinstance(fn_, ast.Attribute):
            recv = fn_.value
            a = _self_attr(recv)
            if a is not None:
                # self.X.m(): resolve ONLY through X's recorded class —
                # never against the enclosing class (self._entries.get()
                # must not match a same-named method of this class)
                cands = cls.attr_types.get(a, ()) if cls is not None else ()
                for tname in cands:
                    target = graph.classes.get(tname)
                    if target is not None and fn_.attr in target.methods:
                        return ("m", target.name, fn_.attr)
                return None
            if isinstance(recv, ast.Name):
                if recv.id == "self" and cls is not None \
                        and fn_.attr in cls.methods:
                    return ("m", cls.name, fn_.attr)
                for tname in local_types.get(recv.id, ()):
                    target = graph.classes.get(tname)
                    if target is not None and fn_.attr in target.methods:
                        return ("m", target.name, fn_.attr)
            return None
        if isinstance(fn_, ast.Name):
            if (module, fn_.id) in graph.mod_funcs:
                return ("f", module, fn_.id)
        return None

    def note_assign(st: ast.Assign) -> None:
        cands = [c for c in (
            _ctor_name(call) for call in ast.walk(st.value)
            if isinstance(call, ast.Call)) if c is not None]
        src_attr = None
        if isinstance(st.value, ast.Attribute):
            src_attr = _self_attr(st.value)
        for t in st.targets:
            if not isinstance(t, ast.Name):
                continue
            if cands:
                local_types[t.id] = cands
            elif src_attr is not None and cls is not None \
                    and src_attr in cls.attr_types:
                local_types[t.id] = cls.attr_types[src_attr]
            else:
                local_types.pop(t.id, None)

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later on an unknown thread with unknown
            # locks: scan its body as an independent empty-held context
            for child in node.body:
                visit(child, ())
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, ast.Assign):
            note_assign(node)
        if isinstance(node, ast.With):
            acquired = list(held)
            for item in node.items:
                visit(item.context_expr, tuple(acquired))
                n = lock_node_of(item.context_expr)
                if n is None:
                    continue
                s.acquires.append((n, path, item.context_expr.lineno))
                for h in acquired:
                    if h != n:
                        s.edges.append((h, n, path,
                                        item.context_expr.lineno, ""))
                if n not in acquired:
                    acquired.append(n)
            for stmt in node.body:
                visit(stmt, tuple(acquired))
            return
        if isinstance(node, ast.Call):
            desc = _blocking_desc(node, cls, local_types)
            if desc is not None:
                s.blocks.append((desc, path, node.lineno, held))
            else:
                callee = resolve_call(node)
                if callee is not None:
                    s.calls.append((callee, path, node.lineno, held))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, assume_held)
    return s


# ---------------------------------------------------------------------------
# graph assembly + fixpoints
# ---------------------------------------------------------------------------


def build_graph(paths: Iterable[str]) -> LockGraph:
    graph = LockGraph()
    trees: List[Tuple[str, str, ast.Module]] = []
    for f in iter_py_files(paths):
        try:
            with open(f, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
        except (SyntaxError, OSError):
            continue
        module = _module_name(f)
        trees.append((f, module, tree))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                info = _index_class(node, module, f)
                # bare-name collisions make resolution ambiguous: disable
                graph.classes[info.name] = (
                    None if info.name in graph.classes else info)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                graph.mod_funcs[(module, node.name)] = node
                graph.mod_func_paths[(module, node.name)] = f
            elif isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        graph.mod_locks[(module, t.id)] = \
                            _ctor_name(node.value) or "Lock"
                        graph.node_ctor[f"{module}:{t.id}"] = \
                            _ctor_name(node.value) or "Lock"

    for info in graph.classes.values():
        if info is None:
            continue
        for attr, ctor in info.lock_attrs.items():
            graph.node_ctor[info.lock_node(attr)] = ctor

    # summaries
    for path, module, tree in trees:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                info = graph.classes.get(node.name)
                if info is None or info.path != path:
                    info = _index_class(node, module, path)  # shadowed dup
                for mname, m in info.methods.items():
                    assume: Tuple[str, ...] = ()
                    if mname.endswith("_locked"):
                        assume = tuple(sorted({info.lock_node(a)
                                               for a in info.lock_attrs}))
                    graph.summaries[("m", info.name, mname)] = \
                        _scan_function(("m", info.name, mname), m, graph,
                                       info, module, path, assume)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                graph.summaries[("f", module, node.name)] = \
                    _scan_function(("f", module, node.name), node, graph,
                                   None, module, path)

    # fixpoint: which locks may each function (transitively) acquire, and
    # does it (transitively) block
    for key, s in graph.summaries.items():
        graph.may_acquire[key] = {n for n, _, _ in s.acquires}
        if s.blocks:
            graph.may_block[key] = (s.blocks[0][0], "")
    changed = True
    while changed:
        changed = False
        for key, s in graph.summaries.items():
            acq = graph.may_acquire[key]
            for callee, _p, _l, _h in s.calls:
                sub = graph.may_acquire.get(callee)
                if sub and not sub <= acq:
                    acq |= sub
                    changed = True
                if callee in graph.may_block and key not in graph.may_block:
                    desc, via = graph.may_block[callee]
                    name = callee[2] if len(callee) == 3 else str(callee)
                    graph.may_block[key] = (desc,
                                            f"{name}(){' -> ' + via if via else ''}")
                    changed = True

    # edges: direct nested-with + call-mediated
    for key, s in graph.summaries.items():
        for src, dst, path, line, note in s.edges:
            graph.add_edge(src, dst, path, line, note)
        for callee, path, line, held in s.calls:
            sub = graph.may_acquire.get(callee, ())
            cname = callee[2] if len(callee) == 3 else str(callee)
            for h in held:
                for m in sub:
                    if m != h:
                        graph.add_edge(h, m, path, line, f"via {cname}()")
                    else:
                        ctor = graph.node_ctor.get(m, "Lock")
                        if ctor != "RLock":
                            graph.add_edge(h, m, path, line,
                                           f"re-acquired via {cname}()")
    return graph


def _sccs(edges: Dict[str, Dict[str, List]]) -> List[List[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    nodes = set(edges)
    for tgts in edges.values():
        nodes.update(tgts)

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(edges.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(edges.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)

    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return out


def _cycle_path(comp: List[str],
                edges: Dict[str, Dict[str, List]]) -> List[str]:
    """One concrete cycle through a (size>=2) SCC, as an ordered node list
    ending where it started."""
    comp_set = set(comp)
    start = sorted(comp)[0]
    path = [start]
    seen = {start}
    v = start
    while True:
        nxt = sorted(w for w in edges.get(v, ()) if w in comp_set)
        if not nxt:
            return path  # shouldn't happen inside an SCC
        w = next((x for x in nxt if x == start), None)
        if w is None:
            w = next((x for x in nxt if x not in seen), nxt[0])
        path.append(w)
        if w == start:
            return path
        if w in seen:
            # trim to the loop we just closed
            i = path.index(w)
            return path[i:]
        seen.add(w)
        v = w


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


def _graph_findings(graph: LockGraph) -> List[Finding]:
    findings: List[Finding] = []

    # GC-L304: cycles
    for comp in _sccs(graph.edges):
        if len(comp) == 1:
            v = comp[0]
            selfsites = graph.edges.get(v, {}).get(v)
            if not selfsites:
                continue
            path, line, note = selfsites[0]
            findings.append(Finding(
                "GC-L304",
                f"lock {v} is re-acquired while already held "
                f"({note or 'nested with'}) — a non-reentrant lock "
                f"deadlocks its own thread",
                path=path, line=line, source="lock_graph",
                detail={"cycle": [v, v]}))
            continue
        cyc = _cycle_path(comp, graph.edges)
        legs = []
        for a, b in zip(cyc, cyc[1:]):
            site = graph.edges[a][b][0]
            legs.append(f"{a} -> {b} at {site[0]}:{site[1]}"
                        f"{' (' + site[2] + ')' if site[2] else ''}")
        first = graph.edges[cyc[0]][cyc[1]][0]
        findings.append(Finding(
            "GC-L304",
            f"lock-order cycle: {' ; '.join(legs)} — two threads taking "
            f"these paths concurrently deadlock; pick one order and stick "
            f"to it",
            path=first[0], line=first[1], source="lock_graph",
            detail={"cycle": cyc}))

    # GC-L305: blocking under a held lock
    for key, s in graph.summaries.items():
        for desc, path, line, held in s.blocks:
            if held:
                findings.append(Finding(
                    "GC-L305",
                    f"{_key_name(key)}: {desc} while holding "
                    f"{', '.join(held)} — every thread contending that "
                    f"lock stalls for the full wait",
                    path=path, line=line, source="lock_graph",
                    detail={"held": list(held)}))
        for callee, path, line, held in s.calls:
            if not held or callee not in graph.may_block:
                continue
            desc, via = graph.may_block[callee]
            cname = callee[2] if len(callee) == 3 else str(callee)
            chain = f"{cname}(){' -> ' + via if via else ''}"
            findings.append(Finding(
                "GC-L305",
                f"{_key_name(key)}: calls {chain} which blocks ({desc}) "
                f"while holding {', '.join(held)}",
                path=path, line=line, source="lock_graph",
                detail={"held": list(held), "via": chain}))
    findings.sort(key=lambda f: (f.path or "", f.line or 0, f.rule))
    return findings


def _key_name(key) -> str:
    if key[0] == "m":
        return f"{key[1]}.{key[2]}()"
    return f"{key[1]}.{key[2]}()"


def _filter_by_file(findings: List[Finding]) -> List[Finding]:
    """Apply inline suppressions file-by-file (a finding's site is where
    the suppression comment lives, even for cross-module cycles)."""
    by_path: Dict[str, Tuple[Set[str], Dict[int, Set[str]]]] = {}
    out: List[Finding] = []
    for f in findings:
        if f.path is None:
            out.append(f)
            continue
        if f.path not in by_path:
            try:
                with open(f.path, "r", encoding="utf-8") as fh:
                    by_path[f.path] = parse_suppressions(fh.read())
            except OSError:
                by_path[f.path] = (set(), {})
        file_wide, per_line = by_path[f.path]
        if f.rule in file_wide:
            continue
        if f.line is not None and f.rule in per_line.get(f.line, ()):
            continue
        out.append(f)
    return out


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    """The whole-package pass: build one lock graph over every ``.py``
    under ``paths`` and report GC-L304/GC-L305."""
    return _filter_by_file(_graph_findings(build_graph(paths)))
