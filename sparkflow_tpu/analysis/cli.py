"""graftcheck CLI: ``python -m sparkflow_tpu.analysis [paths...]``.

Runs the static passes (ast_lint + per-class lock coverage + the
whole-package lock-order/blocking graph) over every ``.py`` file under the
given paths, plus — unless ``--no-trace`` — the jaxpr self-check over the
repo's model presets and optimizer registry. Exit status is the finding
count clamped to 1, so CI can gate on it; ``--format json`` emits one
finding object per line (JSONL) for tooling.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from . import ast_lint, lockgraph, locks, policy_lint
from .findings import RULES, Finding, format_findings

__all__ = ["main", "run_static", "run_all"]


def run_static(paths: Sequence[str]) -> List[Finding]:
    """ast_lint + per-class lock coverage + the whole-package lock graph
    (deadlock/blocking-under-lock) + pure-policy purity over every .py
    under ``paths``."""
    return (ast_lint.lint_paths(paths) + locks.lint_paths(paths)
            + lockgraph.lint_paths(paths) + policy_lint.lint_paths(paths))


def run_all(paths: Sequence[str], trace: bool = True,
            ignore: Sequence[str] = ()) -> List[Finding]:
    """The full graftcheck pass: static rules over ``paths`` and, with
    ``trace``, the jaxpr repo self-check (model presets x optimizers)."""
    ignore = set(ignore)
    findings = [f for f in run_static(paths) if f.rule not in ignore]
    if trace:
        from . import jaxpr_lint
        findings.extend(jaxpr_lint.repo_self_check(ignore=ignore))
    return findings


def _list_rules() -> str:
    lines = ["graftcheck rule catalog (docs/analysis.md has the long form):"]
    for rule_id in sorted(RULES):
        name, desc = RULES[rule_id]
        lines.append(f"  {rule_id}  {name:<24} {desc}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkflow_tpu.analysis",
        description="graftcheck: sharding / tracing / concurrency lint "
                    "for sparkflow-tpu code")
    parser.add_argument("paths", nargs="*", default=["sparkflow_tpu"],
                        help="files or directories to lint "
                             "(default: sparkflow_tpu)")
    parser.add_argument("--no-trace", action="store_true",
                        help="skip the jaxpr self-check over the repo's "
                             "model presets and optimizer registry")
    parser.add_argument("--ignore", default="",
                        help="comma-separated rule ids to drop "
                             "(e.g. GC-A203,GC-L302)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    ignore = [r.strip() for r in args.ignore.split(",") if r.strip()]
    findings = run_all(args.paths, trace=not args.no_trace, ignore=ignore)

    if args.format == "json":
        # JSONL: one finding object per line, so editors/CI can stream-parse
        # (and `grep GC-L304 | head -1 | jq` just works); clean run = no output
        for f in findings:
            print(json.dumps(f.to_dict(), sort_keys=True))
    elif findings:
        print(format_findings(findings))
        print(f"\ngraftcheck: {len(findings)} finding(s)")
    else:
        print("graftcheck: clean")
    return 1 if findings else 0
