"""Import-compatibility alias: ``from sparkflow_tpu.HogwildSparkModel import
HogwildSparkModel`` works exactly like the reference's
``from sparkflow.HogwildSparkModel import HogwildSparkModel``
(``sparkflow/HogwildSparkModel.py:103``).

The real implementation lives in :mod:`sparkflow_tpu.hogwild`: the same
constructor surface and ``.train(rdd)`` entry point, backed by the synchronous
mesh trainer (no Flask parameter server exists; ``stop_server`` is a no-op)."""

from .hogwild import HogwildSparkModel

__all__ = ["HogwildSparkModel"]
