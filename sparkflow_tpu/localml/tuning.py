"""Model selection: the ``pyspark.ml.tuning`` subset (ParamGridBuilder,
CrossValidator, TrainValidationSplit).

The reference lists "Hyperopt implementation" as future work it never built
(reference ``README.md:234-236``); here grid search over any Estimator —
including ``SparkAsyncDL`` — is first-class. Fits run sequentially on the
local engine (the TPU mesh underneath is the real parallelism; for K
single-chip configs in ONE compiled program see
``sparkflow_tpu.parallel.hyperparameter_search``).

Semantics follow pyspark 2.4: CrossValidator averages the evaluator metric
over k folds per param map and refits the best map on the full dataset;
TrainValidationSplit evaluates each map once on a held-out split. Whether a
larger metric is better comes from the evaluator's ``isLargerBetter()``
(all localml evaluators: True).
"""

from __future__ import annotations

import itertools
import random as _random
from typing import Any, Dict, List

import numpy as np

from .base import Estimator, Model
from .param import Param, Params, TypeConverters, keyword_only
from .sql import DataFrame


class ParamGridBuilder:
    """Builds a list of param maps (the cartesian product of the grids)."""

    def __init__(self):
        self._grid: Dict[Any, List[Any]] = {}

    def addGrid(self, param, values) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        """Fixed (param, value) pairs included in every map."""
        if len(args) == 1 and isinstance(args[0], dict):
            pairs = args[0].items()
        else:
            pairs = args
        for param, value in pairs:
            self._grid[param] = [value]
        return self

    def build(self) -> List[Dict[Any, Any]]:
        keys = list(self._grid)
        out = []
        for combo in itertools.product(*(self._grid[k] for k in keys)):
            out.append(dict(zip(keys, combo)))
        return out or [{}]


class _ValidatorParams(Params):
    numFolds = Param(Params._dummy(), "numFolds", "number of folds",
                     typeConverter=TypeConverters.toInt)
    trainRatio = Param(Params._dummy(), "trainRatio", "train fraction",
                       typeConverter=TypeConverters.toFloat)
    seed = Param(Params._dummy(), "seed", "random seed",
                 typeConverter=TypeConverters.toInt)

    def __init__(self):
        super().__init__()
        self.estimator = None
        self.estimatorParamMaps = None
        self.evaluator = None

    def _is_larger_better(self) -> bool:
        fn = getattr(self.evaluator, "isLargerBetter", None)
        return bool(fn()) if callable(fn) else True

    def _fit_and_eval(self, pm, train_df, eval_df) -> float:
        model = self.estimator.copy(pm)._fit(train_df)
        return float(self.evaluator.evaluate(model.transform(eval_df)))

    def _pick_best(self, metrics: List[float]) -> int:
        arr = np.asarray(metrics, dtype=float)
        return int(np.argmax(arr) if self._is_larger_better()
                   else np.argmin(arr))


def _shuffled_rows(df: DataFrame, seed) -> list:
    rows = df.collect()
    _random.Random(seed).shuffle(rows)
    return rows


class CrossValidatorModel(Model):
    def __init__(self, bestModel=None, avgMetrics=None):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = list(avgMetrics or [])

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return self.bestModel.transform(dataset)


class CrossValidator(Estimator, _ValidatorParams):
    """k-fold grid search: avg metric per param map, best map refit on the
    full dataset (pyspark.ml.tuning.CrossValidator semantics)."""

    @keyword_only
    def __init__(self, estimator=None, estimatorParamMaps=None,
                 evaluator=None, numFolds=3, seed=None):
        super().__init__()
        self._setDefault(numFolds=3)
        kw = self._input_kwargs
        self.estimator = kw.pop("estimator", None)
        self.estimatorParamMaps = kw.pop("estimatorParamMaps", None)
        self.evaluator = kw.pop("evaluator", None)
        self._set(**{k: v for k, v in kw.items() if v is not None})

    def _fit(self, dataset: DataFrame) -> CrossValidatorModel:
        if not (self.estimator and self.estimatorParamMaps and self.evaluator):
            raise ValueError("CrossValidator needs estimator, "
                             "estimatorParamMaps and evaluator")
        k = self.getOrDefault(self.numFolds)
        if k < 2:
            raise ValueError(f"numFolds must be >= 2, got {k}")
        rows = _shuffled_rows(dataset, self.getOrDefault(self.seed)
                              if self.isSet(self.seed) else None)
        n = len(rows)
        folds = [rows[int(i * n / k):int((i + 1) * n / k)] for i in range(k)]
        metrics = []
        for pm in self.estimatorParamMaps:
            scores = []
            for i in range(k):
                train = [r for j, f in enumerate(folds) if j != i for r in f]
                train_df = DataFrame(train, dataset.columns,
                                     dataset.num_partitions)
                eval_df = DataFrame(folds[i], dataset.columns,
                                    dataset.num_partitions)
                scores.append(self._fit_and_eval(pm, train_df, eval_df))
            metrics.append(float(np.mean(scores)))
        best = self._pick_best(metrics)
        best_model = self.estimator.copy(
            self.estimatorParamMaps[best])._fit(dataset)
        return CrossValidatorModel(best_model, metrics)


class TrainValidationSplitModel(Model):
    def __init__(self, bestModel=None, validationMetrics=None):
        super().__init__()
        self.bestModel = bestModel
        self.validationMetrics = list(validationMetrics or [])

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return self.bestModel.transform(dataset)


class TrainValidationSplit(Estimator, _ValidatorParams):
    """Single held-out split grid search; cheaper than k-fold."""

    @keyword_only
    def __init__(self, estimator=None, estimatorParamMaps=None,
                 evaluator=None, trainRatio=0.75, seed=None):
        super().__init__()
        self._setDefault(trainRatio=0.75)
        kw = self._input_kwargs
        self.estimator = kw.pop("estimator", None)
        self.estimatorParamMaps = kw.pop("estimatorParamMaps", None)
        self.evaluator = kw.pop("evaluator", None)
        self._set(**{k: v for k, v in kw.items() if v is not None})

    def _fit(self, dataset: DataFrame) -> TrainValidationSplitModel:
        if not (self.estimator and self.estimatorParamMaps and self.evaluator):
            raise ValueError("TrainValidationSplit needs estimator, "
                             "estimatorParamMaps and evaluator")
        ratio = self.getOrDefault(self.trainRatio)
        if not 0.0 < ratio < 1.0:
            raise ValueError(f"trainRatio must be in (0, 1), got {ratio}")
        rows = _shuffled_rows(dataset, self.getOrDefault(self.seed)
                              if self.isSet(self.seed) else None)
        cut = int(round(len(rows) * ratio))
        train_df = DataFrame(rows[:cut], dataset.columns,
                             dataset.num_partitions)
        eval_df = DataFrame(rows[cut:], dataset.columns,
                            dataset.num_partitions)
        metrics = [self._fit_and_eval(pm, train_df, eval_df)
                   for pm in self.estimatorParamMaps]
        best = self._pick_best(metrics)
        best_model = self.estimator.copy(
            self.estimatorParamMaps[best])._fit(dataset)
        return TrainValidationSplitModel(best_model, metrics)
