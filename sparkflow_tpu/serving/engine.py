"""AOT-compiled online-inference engine.

The offline path (:func:`sparkflow_tpu.core.make_predict_fn` +
``predict_in_chunks``) relies on ``jax.jit``'s trace cache: the first request
at every new batch shape pays a compile, which is fine for a Spark partition
sweep but is a multi-second latency cliff for an online endpoint. The engine
removes the cliff by **pre-compiling** the apply function for a ladder of
padded batch-size buckets (1, 2, 4, ... max_batch) at construction time via
``jit(...).lower(...).compile()`` — steady-state serving then never traces or
compiles again, whatever mix of request sizes arrives. Requests pad up to the
nearest bucket (bounded waste: < 2x rows) and trim on return; padded rows are
zeros, and row-independent graph evaluation means they can't perturb real
rows' outputs.

Sharding: with a multi-device ``dp`` mesh, buckets that divide over the axis
shard their batch (params replicated, exactly like the batch-transform path);
smaller buckets compile replicated rather than failing divisibility.

Quantized serving reuses :mod:`sparkflow_tpu.utils.quant`: the engine
quantizes the full-precision tree once at load and compiles the int8 apply —
``weight_only`` and ``dynamic`` both serve through the same bucket ladder.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis.runtime_guards import RecompileGuard
from ..core import _sharded_trace_guard
from ..obs.spans import span as obs_span
from ..sharding import as_sharding_config, per_device_bytes
from ..resilience import faults
from ..utils import metrics as metrics_mod
from ..utils.tracing import annotate


def _bucket_ladder(max_batch: int) -> List[int]:
    """1, 2, 4, ... up to max_batch (max_batch itself always included, so a
    non-power-of-two cap still has a full-size bucket)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return buckets


class InferenceEngine:
    """Low-latency predictions from a trained model, no steady-state compiles.

    Parameters
    ----------
    graph : str | model
        Model spec JSON (nn DSL / registry spec / TF1 metagraph — anything
        :func:`sparkflow_tpu.models.model_from_json` loads) or an already
        constructed model object.
    weights : list of arrays | str | params pytree | None
        Flat weight list, the estimator's weights Param (inline JSON or
        ``npz:<path>``), or an already-structured params pytree.
    input_name : str | sequence of str
        Input tensor name(s) (``'x:0'`` style); a sequence means requests
        carry a tuple of arrays (multi-input models).
    output_name : str
        Output tensor to serve.
    max_batch : int
        Top of the bucket ladder; larger requests run in max_batch chunks.
    mesh : jax.sharding.Mesh | None
        Serving mesh. With only a data axis, batches shard over it and
        params replicate. With a ``sharding`` config naming ``tp_axis`` /
        ``ep_axis`` present on the mesh, params shard per the model's
        megatron rules instead (attention/MLP on heads/hidden over tp,
        expert banks over ep) and GSPMD partitions each bucket's forward —
        tensor-parallel predict from the same config the trainer used.
    sharding : ShardingConfig | dict | None
        Declarative placement (``sparkflow_tpu.sharding.ShardingConfig``);
        serving consumes its ``data_axis``/``dcn_axis`` for batch rows and
        ``tp_axis``/``ep_axis`` for model-parallel params — the same config
        a Trainer fit used works here unchanged (zero stages only affect
        training). ``quantize`` does not compose with tp/ep.
    quantize : None | 'weight_only' | 'dynamic'
        int8 serving via ``utils.quant``. ``quant_min_size`` forwards to
        :func:`~sparkflow_tpu.utils.quant.quantize_params` (kernels below it
        stay full precision).
    warmup : bool
        AOT-compile every bucket at construction (default). With
        ``warmup=False``, buckets compile on first use (each counted in
        ``stats()['fallback_compiles']``).
    executable_dir : str | None
        Zero-compile cold start: a :class:`~sparkflow_tpu.serving.
        coldstart.ExecutableStore` directory of ``jax.export``-serialized
        executables. Warmup deserializes the bucket ladder from here
        (sha256-verified) instead of compiling; anything missing or stale
        compiles as usual — hitting ``compile_cache_dir`` when set — and
        is saved back for the next boot.
    """

    def __init__(self, graph, weights=None, *,
                 input_name: Union[str, Sequence[str]] = "x:0",
                 output_name: str = "out:0",
                 dropout_name: Optional[str] = None,
                 dropout_value: float = 1.0,
                 max_batch: int = 64,
                 mesh=None,
                 sharding=None,
                 quantize: Optional[str] = None,
                 quant_min_size: int = 4096,
                 compute_dtype=None,
                 warmup: bool = True,
                 compile_cache_dir: Optional[str] = None,
                 executable_dir: Optional[str] = None,
                 metrics: Optional[metrics_mod.Metrics] = None):
        if isinstance(graph, str):
            from ..models import model_from_json
            self.model = model_from_json(graph, compute_dtype)
        else:
            self.model = graph
        self.input_name = input_name
        self.output_name = output_name
        self.dropout_name = dropout_name
        self.dropout_value = dropout_value
        self.max_batch = int(max_batch)
        self.mesh = mesh
        self.sharding = as_sharding_config(sharding)
        if self.mesh is not None:
            # fail on a typo'd axis at construction, not first request; a
            # mesh without the data axis is fine (rows replicate)
            self.sharding.validate(self.mesh, require_data_axis=False)
            if (self.sharding.pp_axis is not None
                    and int(self.mesh.shape.get(self.sharding.pp_axis, 1))
                    > 1):
                raise ValueError(
                    "pp_axis is a decode-plane axis: the single-shot "
                    "predict engine has no token cadence to hide pipeline "
                    "bubbles behind. Serve depth-sharded models through "
                    "DecodeEngine (serving/decode.py), or drop pp_axis "
                    "from this engine's sharding config.")
        self.quantize = quantize
        self.metrics = metrics if metrics is not None else metrics_mod.Metrics()

        self._multi = isinstance(input_name, (list, tuple))
        names = list(input_name) if self._multi else [input_name]
        self._in_keys = [n.split(":")[0] for n in names]
        # validate names against the model's tensor table up front — a typo
        # must fail at engine construction, not on the first live request
        for n in names + [output_name]:
            self.model.graphdef.resolve(n)

        params = self._load_params(weights)
        # shape/dtype template of the ctor weights in STANDARD layout,
        # captured before quantize/shard: every hot swap validates against
        # it (shapes pinned unchanged so the AOT ladder is reused as-is)
        self._weights_template = jax.tree.map(
            lambda a: (jax.ShapeDtypeStruct(a.shape, a.dtype)
                       if hasattr(a, "dtype")
                       else jax.ShapeDtypeStruct(np.shape(a),
                                                 np.asarray(a).dtype)),
            params)
        # model-parallel predict: a config naming tp_axis/ep_axis present on
        # the mesh shards attention/MLP weights (megatron rules) and expert
        # banks instead of replicating — GSPMD partitions the matmuls and
        # inserts the all-reduces from the param shardings alone
        self._tp_specs = None
        self._quant_min_size = int(quant_min_size)
        mp = (self.mesh is not None
              and self.sharding.tp_size(self.mesh)
              * self.sharding.ep_size(self.mesh) > 1)
        if mp and quantize:
            raise ValueError("quantize does not compose with tensor/expert-"
                             "parallel serving (int8 packing breaks the "
                             "megatron layout); pick one")
        if quantize:
            from ..utils.quant import MODES
            if quantize not in MODES:
                raise ValueError(f"quantize must be one of {MODES} (or None), "
                                 f"got {quantize!r}")
            self.model.quant_mode = quantize
        if mp:
            if not hasattr(self.model, "param_pspecs"):
                raise TypeError("model-parallel serving needs the model to "
                                "publish param_pspecs() (megatron rules)")
            from ..parallel.tp import derive_param_pspecs, filter_pspec
            pspecs = derive_param_pspecs(self.model, self.mesh, self.sharding)
            self._tp_specs = jax.tree.map(
                lambda s: filter_pspec(s, self.mesh), pspecs,
                is_leaf=lambda x: isinstance(x, P))
        self._params = self._place_params(params)

        self._in_shapes, self._in_dtypes = self._input_layouts()
        self.buckets = _bucket_ladder(self.max_batch)
        self._compiled: Dict[int, Any] = {}
        self._compile_lock = threading.Lock()
        self._stats_lock = threading.Lock()  # request counters only
        # one expected trace per ladder bucket; anything beyond warns
        self.recompile_guard = RecompileGuard(name="serving.predict",
                                              warn_after=len(self.buckets))
        self.aot_compiles = 0
        self.fallback_compiles = 0
        self._requests = 0
        self._rows = 0
        self._serving_version = 0  # bumped by swap_params; 0 = ctor weights
        self._swaps = 0
        # persistent XLA compilation cache: with a directory set, warmup's
        # bucket compiles hit cached executables from earlier processes
        # instead of re-running XLA — the restart-latency knob. hits/misses
        # are estimated from cache-entry deltas around our own compiles.
        self.compile_cache_dir: Optional[str] = None
        self.compile_cache_hits = 0
        self.compile_cache_misses = 0
        if compile_cache_dir is not None:
            from ..utils.hw import enable_compilation_cache
            self.compile_cache_dir = enable_compilation_cache(
                compile_cache_dir)
        # zero-compile cold start: warmup loads jax.export-serialized
        # executables from here (sha256-manifested, ExecutableStore) before
        # falling back to compiling (which may hit the compile cache above),
        # and saves what it had to compile for the next boot
        self.exec_store = None
        self.serialized_loads = 0
        self.serialized_saves = 0
        self._exec_prefix = ""
        if executable_dir is not None:
            from .coldstart import ExecutableStore
            self.exec_store = ExecutableStore(executable_dir,
                                              metrics=self.metrics)
            # key signature over every shape-determining knob: a store
            # shared across differently-configured engines must never
            # deserialize a wrong-shaped program
            desc = repr((
                self._in_shapes, [str(d) for d in self._in_dtypes],
                self.quantize, self.output_name, self._in_keys,
                dict(self.mesh.shape) if self.mesh is not None else None,
                self.sharding.describe(),
                [(tuple(s.shape), str(s.dtype))
                 for s in jax.tree.leaves(self._weights_template)]))
            sig = hashlib.sha256(desc.encode()).hexdigest()[:12]
            self._exec_prefix = f"predict/{sig}"
        if warmup:
            self.warmup()

    # -- loading -------------------------------------------------------------

    @classmethod
    def from_checkpoint(cls, directory: str, graph, retry=None, **kwargs
                        ) -> "InferenceEngine":
        """Load from a :class:`~sparkflow_tpu.checkpoint.CheckpointManager`
        directory (``weights.npz`` export or an orbax training checkpoint,
        whose restore verifies manifest checksums and falls back past
        corrupt steps). ``retry`` (a
        :class:`~sparkflow_tpu.resilience.retry.RetryPolicy`) governs
        transient read errors — network filesystems at replica-start time
        are exactly the flaky window it exists for."""
        from ..checkpoint import CheckpointManager
        from ..models import model_from_json
        model = (model_from_json(graph, kwargs.get("compute_dtype"))
                 if isinstance(graph, str) else graph)
        weights = CheckpointManager.load_weights(directory, model,
                                                 retry=retry)
        return cls(model, weights, **kwargs)

    def _load_params(self, weights):
        from ..graphdef import list_to_params
        if weights is None:
            raise ValueError("weights are required (flat list, weights JSON, "
                             "'npz:<path>', or a params pytree)")
        if isinstance(weights, str):
            from ..ml_util import resolve_weights
            weights = resolve_weights(weights)
        if isinstance(weights, (list, tuple)):
            return list_to_params(self.model, list(weights))
        return weights  # already a params pytree

    def _place_params(self, params):
        """Quantize/shard/replicate one standard-layout tree into this
        engine's serving placement. The ctor and every hot swap run exactly
        this path, so a swapped tree lands bit-identical to a cold start."""
        if self.quantize:
            from ..utils.quant import quantize_params
            params = quantize_params(params, min_size=self._quant_min_size)
        if self._tp_specs is not None:
            from ..parallel.tp import shard_params
            params = shard_params(params, self.mesh, self._tp_specs)
        elif self.mesh is not None and self.mesh.size > 1:
            params = jax.device_put(params, NamedSharding(self.mesh, P()))
        return params

    def _snapshot_params(self):
        with self._stats_lock:
            return self._params

    def _input_layouts(self) -> Tuple[List[Tuple[int, ...]], List[Any]]:
        specs = self.model.input_specs()
        shapes, dtypes = [], []
        for key in self._in_keys:
            if key not in specs:
                raise KeyError(f"input {key!r} is not a model input; inputs: "
                               f"{sorted(specs)}")
            shape, dtype = specs[key]
            if any(d is None for d in shape[1:]):
                raise ValueError(
                    f"input {key!r} has non-static feature dims {shape}; the "
                    f"bucket ladder needs fully static row shapes")
            shapes.append(tuple(int(d) for d in shape[1:]))
            dtypes.append(np.dtype(dtype))
        return shapes, dtypes

    # -- compilation ---------------------------------------------------------

    def _apply_fn(self):
        model = self.model
        in_keys, multi = self._in_keys, self._multi
        drop_key = (self.dropout_name.split(":")[0]
                    if self.dropout_name else None)
        drop_val = self.dropout_value
        out_name = self.output_name

        def predict(params, x):
            import jax.numpy as jnp
            feeds = dict(zip(in_keys, tuple(x) if multi else (x,)))
            if drop_key is not None:
                feeds[drop_key] = jnp.asarray(drop_val, jnp.float32)
            return model.apply(params, feeds, [out_name],
                               train=False)[out_name]

        return predict

    def _x_struct(self, bucket: int):
        structs = tuple(
            jax.ShapeDtypeStruct((bucket,) + shape, dtype)
            for shape, dtype in zip(self._in_shapes, self._in_dtypes))
        return structs if self._multi else structs[0]

    def _compile_bucket(self, bucket: int):
        # guard-wrapped so every trace (one per bucket compile) is counted;
        # after warmup() marks steady state, any further trace is a
        # regression the ladder was supposed to prevent (GC-R401)
        predict = self.recompile_guard.wrap(self._apply_fn())
        params = self._snapshot_params()
        params_struct = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
            if not hasattr(a, "aval") else jax.ShapeDtypeStruct(a.shape, a.dtype),
            params)
        mesh = self.mesh
        if mesh is None or mesh.size <= 1:
            jitted = jax.jit(predict)
        else:
            predict = _sharded_trace_guard(predict, mesh)
            repl = NamedSharding(mesh, P())
            # params keep their megatron shardings under tp/ep, else replicate
            pshard = (jax.tree.map(lambda s: NamedSharding(mesh, s),
                                   self._tp_specs,
                                   is_leaf=lambda x: isinstance(x, P))
                      if self._tp_specs is not None else repl)
            # rows shard over the config's batch axes (data_axis + optional
            # dcn_axis) when the bucket divides their product, else replicate
            cfg = self.sharding
            dp = 1
            for a in cfg.batch_axes(mesh):
                dp *= mesh.shape[a]
            rows = (cfg.data_sharding(mesh)
                    if dp > 1 and bucket % dp == 0
                    else repl)
            data = (jax.tree.map(lambda _: rows, self._x_struct(bucket))
                    if self._multi else rows)
            jitted = jax.jit(predict, in_shardings=(pshard, data),
                             out_shardings=rows)
        if (mesh is not None and self.sharding.tp_size(mesh) > 1):
            # pallas flash attention has no GSPMD partitioning rule; tracing
            # under this context makes it nest its own shard_map over
            # batch x heads (falling back to the XLA blockwise path when the
            # dims don't divide the mesh axes)
            from ..ops.attention import sharded_attention
            with sharded_attention(mesh, batch_axis=self.sharding.data_axis,
                                   head_axis=self.sharding.tp_axis):
                return jitted.lower(params_struct,
                                    self._x_struct(bucket)).compile()
        return jitted.lower(params_struct, self._x_struct(bucket)).compile()

    def _cache_entries(self) -> int:
        if self.compile_cache_dir is None:
            return 0
        try:
            return len([f for f in os.listdir(self.compile_cache_dir)
                        if not f.startswith(".")])
        except OSError:
            return 0

    def warmup(self) -> None:
        """AOT-compile every bucket. Idempotent; after it returns,
        ``predict`` never compiles for any request size."""
        pending = []
        with self._compile_lock:
            before = self._cache_entries()
            compiled_now = 0
            for b in self.buckets:
                if b not in self._compiled:
                    # tier 1: deserialize a stored executable (no trace,
                    # no XLA); tiers 2/3: compile (hitting the persistent
                    # compile cache when configured), then store for the
                    # next boot
                    if self.exec_store is not None:
                        exe = self.exec_store.load(
                            f"{self._exec_prefix}/b{b}")
                        if exe is not None:
                            self._compiled[b] = exe
                            self.serialized_loads += 1
                            continue
                    with annotate(f"serving/aot_compile_b{b}"):
                        self._compiled[b] = self._compile_bucket(b)
                    self.aot_compiles += 1
                    compiled_now += 1
                    if self.exec_store is not None:
                        pending.append((f"{self._exec_prefix}/b{b}",
                                        self._compiled[b]))
            if self.compile_cache_dir is not None and compiled_now:
                # every compile either wrote a fresh cache entry (miss) or
                # loaded an existing one (hit); the dir delta splits them
                added = max(0, self._cache_entries() - before)
                misses = min(added, compiled_now)
                self.compile_cache_misses += misses
                self.compile_cache_hits += compiled_now - misses
            self.recompile_guard.mark_steady()
        # save-back AFTER the lock: ExecutableStore.save waits on the
        # cross-process manifest lock, and that wait must not stall
        # threads contending the compile lock (GC-L305)
        saved = sum(1 for key, exe in pending
                    if self.exec_store.save(key, exe))
        if saved:
            with self._compile_lock:
                self.serialized_saves += saved

    def _executable(self, bucket: int):
        exe = self._compiled.get(bucket)
        if exe is None:
            # lazy path (warmup=False) or a foreign bucket — counted so tests
            # can assert the steady state compiles nothing
            with self._compile_lock:
                exe = self._compiled.get(bucket)
                if exe is None:
                    exe = self._compiled[bucket] = self._compile_bucket(bucket)
                    self.fallback_compiles += 1
        return exe

    # -- serving -------------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch

    def predict(self, x) -> np.ndarray:
        """Predict for ``x``: one array ``[n, ...]`` (or a tuple for
        multi-input models), any ``n >= 1``. Pads to the nearest bucket;
        requests beyond ``max_batch`` run in max_batch chunks."""
        faults.fire("engine.predict")  # chaos hook; no-op unless armed
        xs = tuple(np.asarray(a) for a in x) if self._multi \
            else (np.asarray(x),)
        if xs[0].ndim == len(self._in_shapes[0]):  # single unbatched row
            xs = tuple(a[None] for a in xs)
        for a, shape, key in zip(xs, self._in_shapes, self._in_keys):
            if tuple(a.shape[1:]) != shape:
                raise ValueError(
                    f"input {key!r}: rows have shape {tuple(a.shape[1:])}, "
                    f"model expects {shape}")
        n = xs[0].shape[0]
        if any(a.shape[0] != n for a in xs):
            raise ValueError("multi-input arrays must share the batch dim")
        # one params snapshot per request: a concurrent hot swap never gives
        # a chunked request mixed versions — every chunk runs the same tree
        params = self._snapshot_params()
        if n == 0:
            probe = self._run(tuple(a[:0] for a in xs), 0, params,
                              probe_rows=1)
            return probe[:0]
        with self._stats_lock:
            self._requests += 1
            self._rows += n
        if n > self.max_batch:
            outs = [self._run(tuple(a[i:i + self.max_batch] for a in xs),
                              min(self.max_batch, n - i), params)
                    for i in range(0, n, self.max_batch)]
            return np.concatenate(outs, axis=0)
        return self._run(xs, n, params)

    def _run(self, xs, n: int, params, probe_rows: int = 0) -> np.ndarray:
        have = max(n, probe_rows)
        bucket = self._bucket_for(have)
        if have < bucket:
            xs = tuple(np.concatenate(
                [a, np.zeros((bucket - a.shape[0],) + a.shape[1:], a.dtype)])
                for a in xs)
        elif probe_rows and xs[0].shape[0] == 0:
            xs = tuple(np.zeros((bucket,) + a.shape[1:], a.dtype) for a in xs)
        exe = self._executable(bucket)
        self.metrics.observe("serving/engine_batch_rows", n)
        self.metrics.observe("serving/padding_waste",
                             (bucket - n) / bucket if bucket else 0.0)
        # span + annotate: the host span routes to whatever tracer is
        # active on this thread (the batcher worker's, usually), and the
        # same named range still shows in JAX profiler captures
        with obs_span("serving/engine_apply", args={"bucket": bucket},
                      jax_annotation=True):
            out = exe(params, xs if self._multi else xs[0])
        return np.asarray(out)[:n]

    # -- live weight hot-swap ------------------------------------------------

    def weights_template(self):
        """Shape/dtype template (``ShapeDtypeStruct`` tree, standard layout)
        of the ctor weights — what a published tree must match leaf-for-leaf
        for :meth:`swap_params` to accept it."""
        return self._weights_template

    def swap_params(self, weights, *, version: Optional[int] = None) -> bool:
        """Hot-swap the serving weights without a restart. ``weights`` is
        anything the ctor accepts, in the model's STANDARD layout, with every
        leaf's shape/dtype identical to the ctor tree (enforced — the AOT
        bucket executables are reused as-is, so the swap causes zero
        retraces). Double-buffered: the new tree is quantized/sharded/placed
        on device while the old one keeps serving, then swapped in a single
        reference assignment; in-flight predicts hold their snapshot, so no
        request ever observes mixed versions. Returns True (swaps apply
        immediately on this engine)."""
        faults.fire("engine.swap")  # chaos hook; no-op unless armed
        params = self._load_params(weights)
        flat, treedef = jax.tree.flatten(params)
        want, want_def = jax.tree.flatten(self._weights_template)
        if treedef != want_def:
            raise ValueError("swapped weights have a different tree "
                             "structure than the ctor weights")
        for i, (got, w) in enumerate(zip(flat, want)):
            gshape = tuple(np.shape(got))
            gdtype = (np.dtype(got.dtype) if hasattr(got, "dtype")
                      else np.asarray(got).dtype)
            if gshape != tuple(w.shape) or gdtype != np.dtype(w.dtype):
                raise ValueError(
                    f"swapped weights leaf {i} is {gshape}/{gdtype}, "
                    f"expected {tuple(w.shape)}/{np.dtype(w.dtype)}: hot "
                    f"swap requires unchanged shapes")
        placed = self._place_params(params)  # old tree still serving
        with self._stats_lock:
            self._params = placed  # the swap: one reference assignment
            v = (int(version) if version is not None
                 else self._serving_version + 1)
            self._serving_version = v
            self._swaps += 1
        self.metrics.gauge("serving/version", float(v))
        return True

    def serving_version(self) -> int:
        """Version of the weights currently serving (0 = ctor weights)."""
        with self._stats_lock:
            return self._serving_version

    def maybe_swap(self) -> bool:
        """Swaps apply immediately on this engine; nothing is deferred."""
        return True

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            requests, rows = self._requests, self._rows
            serving_version, swaps = self._serving_version, self._swaps
            params = self._params
        return {"buckets": list(self.buckets),
                "serving_version": serving_version,
                "swaps": swaps,
                "sharding": self.sharding.describe(),
                "aot_compiles": self.aot_compiles,
                "fallback_compiles": self.fallback_compiles,
                "traces": self.recompile_guard.traces,
                "steady_traces": self.recompile_guard.steady_traces,
                "requests": requests,
                "rows": rows,
                "compile_cache": (
                    None if self.compile_cache_dir is None else
                    {"dir": self.compile_cache_dir,
                     "hits": self.compile_cache_hits,
                     "misses": self.compile_cache_misses}),
                "cold_start": (
                    None if self.exec_store is None else
                    {"dir": self.exec_store.directory,
                     "serialized_loads": self.serialized_loads,
                     "serialized_saves": self.serialized_saves}),
                "quantize": self.quantize,
                "mesh": (dict(self.mesh.shape) if self.mesh is not None
                         else None),
                "tp": (self.sharding.tp_size(self.mesh)
                       if self.mesh is not None else 1),
                "ep": (self.sharding.ep_size(self.mesh)
                       if self.mesh is not None else 1),
                "param_bytes_per_device": sum(
                    per_device_bytes(leaf)
                    for leaf in jax.tree.leaves(params))}
