"""``build_graph`` + optimizer-config builders (reference: ``sparkflow/graph_utils.py``).

``build_graph(model_fn)`` runs the user's model function inside a fresh graph scope
and returns the JSON-serialized graph spec — the model wire format that travels as a
plain string Param through the Estimator, exactly like the reference's
``MessageToJson(export_meta_graph())`` string (``sparkflow/graph_utils.py:6-15``) but
a compact declarative spec instead of a TF1 protobuf dump.

The ``build_*_config`` helpers keep the reference's exact signatures
(``sparkflow/graph_utils.py:18-47``) so optimizer hyperparameter JSON round-trips
unchanged; ``use_locking`` is accepted for compatibility and ignored (synchronous
all-reduce training has no lock to take — see ``sparkflow_tpu/optimizers.py``).
"""

from __future__ import annotations

import json
from typing import Callable

from . import nn
from .graphdef import GraphDef


def build_graph(func: Callable) -> str:
    """Run ``func`` (a model-definition function using :mod:`sparkflow_tpu.nn`)
    in a fresh graph scope and return the JSON graph spec."""
    with nn.graph_scope() as g:
        func()
    if not g.nodes:
        raise ValueError("model function built an empty graph — use sparkflow_tpu.nn "
                         "ops (nn.placeholder, nn.dense, ...) inside it")
    return g.to_json()


def generate_config(**kwargs) -> str:
    return json.dumps(kwargs)


def build_adam_config(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                      use_locking=False) -> str:
    return generate_config(learning_rate=learning_rate, beta1=beta1,
                           beta2=beta2, epsilon=epsilon, use_locking=use_locking)


def build_rmsprop_config(learning_rate=0.001, decay=0.9, momentum=0.0, epsilon=1e-10,
                         use_locking=False, centered=False) -> str:
    return generate_config(learning_rate=learning_rate, decay=decay, momentum=momentum,
                           epsilon=epsilon, use_locking=use_locking, centered=centered)


def build_momentum_config(learning_rate=0.001, momentum=0.9, use_locking=False,
                          use_nesterov=False) -> str:
    return generate_config(learning_rate=learning_rate, momentum=momentum,
                           use_locking=use_locking, use_nesterov=use_nesterov)


def build_adadelta_config(learning_rate=0.001, rho=0.95, epsilon=1e-8,
                          use_locking=False) -> str:
    return generate_config(learning_rate=learning_rate, rho=rho, epsilon=epsilon,
                           use_locking=use_locking)


def build_adagrad_config(learning_rate=0.001, initial_accumulator=0.1,
                         use_locking=False) -> str:
    return generate_config(learning_rate=learning_rate,
                           initial_accumulator=initial_accumulator,
                           use_locking=use_locking)


def build_gradient_descent(learning_rate=0.001, use_locking=False) -> str:
    return generate_config(learning_rate=learning_rate, use_locking=use_locking)


def build_ftrl_config(learning_rate=0.001, learning_rate_power=-0.5,
                      initial_accumulator_value=0.1,
                      l1_regularization_strength=0.0,
                      l2_regularization_strength=0.0, use_locking=False) -> str:
    return generate_config(learning_rate=learning_rate,
                           learning_rate_power=learning_rate_power,
                           initial_accumulator_value=initial_accumulator_value,
                           l1_regularization_strength=l1_regularization_strength,
                           l2_regularization_strength=l2_regularization_strength,
                           use_locking=use_locking)
