"""Registry models: transformer (clf + LM), ResNet, presets, TP/SP steps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkflow_tpu.models import (build_registry_spec, model_from_json, presets)
from sparkflow_tpu.optimizers import build_optimizer
from sparkflow_tpu.parallel.mesh import make_mesh
from sparkflow_tpu.parallel.sp import make_sp_train_step
from sparkflow_tpu.parallel.tp import (fsdp_pspecs, make_sharded_train_step,
                                       shard_params)
from sparkflow_tpu.trainer import Trainer


TINY_CLF = dict(vocab_size=64, num_classes=3, hidden=32, num_layers=2,
                num_heads=4, mlp_dim=64, max_len=16)


def test_registry_spec_roundtrip():
    spec = build_registry_spec("transformer_classifier", **TINY_CLF)
    m = model_from_json(spec)
    assert m.model_name == "transformer_classifier"
    with pytest.raises(KeyError):
        build_registry_spec("not_a_model")


def test_transformer_classifier_trains():
    spec = build_registry_spec("transformer_classifier", **TINY_CLF)
    rs = np.random.RandomState(0)
    # learnable: class = first token id % 3
    ids = rs.randint(0, 64, (128, 16)).astype(np.float32)
    labels = (ids[:, 0] % 3).astype(int)
    y = np.eye(3)[labels].astype(np.float32)
    tr = Trainer(spec, "input_ids:0", "y:0", iters=30, mini_batch_size=32,
                 learning_rate=3e-3)
    res = tr.fit(ids, y)
    assert res.losses[-1] < res.losses[0]


def test_transformer_lm_loss_decreases():
    spec = build_registry_spec("transformer_lm", vocab_size=32, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64, max_len=16)
    m = model_from_json(spec)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(np.tile(np.arange(16), (8, 1)), jnp.int32)  # predictable
    params = m.init(jax.random.PRNGKey(0))
    opt = build_optimizer("adam", 1e-2, None)
    state = opt.init(params)
    import optax

    @jax.jit
    def step(params, state):
        def lf(p):
            return m.loss_vector(p, {"input_ids": ids},
                                 rng=jax.random.PRNGKey(1)).mean()
        loss, g = jax.value_and_grad(lf)(params)
        u, state2 = opt.update(g, state, params)
        return optax.apply_updates(params, u), state2, loss

    losses = []
    for _ in range(20):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_tp_sharded_step(dp_mesh):
    mesh = make_mesh({"dp": 2, "tp": 4})
    spec = build_registry_spec("transformer_classifier", **TINY_CLF)
    m = model_from_json(spec)
    params = shard_params(m.init(jax.random.PRNGKey(0)), mesh, m.param_pspecs())
    opt = build_optimizer("adam", 1e-3, None)
    state = opt.init(params)
    step = make_sharded_train_step(m, opt, mesh, "input_ids", "y")
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (4, 16)), jnp.float32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 4)], jnp.float32)
    mask = jnp.ones((4,), jnp.float32)
    p2, s2, loss = step(params, state, ids, y, mask, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    # param shardings survived the update
    qkv = p2["block_0"]["qkv_kernel"]
    assert "tp" in str(qkv.sharding.spec)


def test_sp_ring_step_matches_single_device_loss():
    mesh = make_mesh({"dp": 2, "sp": 4})
    spec = build_registry_spec("transformer_lm", vocab_size=50, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=32, dropout=0.0)
    lm = model_from_json(spec)
    params = lm.init(jax.random.PRNGKey(0))
    opt = build_optimizer("adam", 1e-3, None)
    step = make_sp_train_step(lm, opt, mesh)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 50, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.float32)
    _, _, loss = step(jax.tree.map(jnp.copy, params), opt.init(params), ids,
                      mask, jax.random.PRNGKey(3))
    single = model_from_json(spec)
    ref = single.loss_vector(params, {"input_ids": ids, "attention_mask": mask},
                             train=False).mean()
    # shard-boundary targets are excluded under sp, so tolerances are loose
    assert abs(float(loss) - float(ref)) < 0.1


def test_sp_forward_matches_single_device_logits():
    """Regression: under sp, shard i must use GLOBAL positions i*S_local..;
    amplified pos table + trained-scale comparison catches local-offset bugs."""
    import copy
    from sparkflow_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"sp": 8})
    spec = build_registry_spec("transformer_lm", vocab_size=50, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=32, dropout=0.0)
    lm = model_from_json(spec)
    params = lm.init(jax.random.PRNGKey(0))
    params["embed"]["pos"] = params["embed"]["pos"] * 5.0  # amplify position signal

    lm_sp = copy.copy(lm)
    lm_sp.sp_axis = "sp"
    fwd = shard_map(
        lambda p, ids: lm_sp.apply(p, {"input_ids": ids}, ["logits"])["logits"],
        mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 50, (2, 32)), jnp.int32)
    sp_logits = jax.jit(fwd)(params, ids)
    ref_logits = lm.apply(params, {"input_ids": ids}, ["logits"])["logits"]
    np.testing.assert_allclose(np.asarray(sp_logits), np.asarray(ref_logits),
                               atol=1e-3)


def test_ring_attention_respects_kv_mask():
    from sparkflow_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from sparkflow_tpu.ops import attention_reference, ring_attention
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    rs = np.random.RandomState(0)
    B, H, S, D = 2, 2, 64, 16
    q = jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
    mask = jnp.asarray((rs.rand(B, S) > 0.3).astype(np.float32))

    ring = shard_map(
        lambda q, k, v, m: ring_attention(q, k, v, "sp", kv_mask=m),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None), check_vma=False)
    out = jax.jit(ring)(q, q, q, mask)
    # reference with additive key mask
    s = jnp.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(D)
    s = jnp.where(mask[:, None, None, :] > 0, s, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_sp_step_does_not_mutate_model():
    spec = build_registry_spec("transformer_lm", vocab_size=20, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32, max_len=16)
    lm = model_from_json(spec)
    mesh = make_mesh({"dp": 2, "sp": 4})
    make_sp_train_step(lm, build_optimizer("adam", 1e-3, None), mesh)
    assert lm.sp_axis is None  # caller's model untouched
    # and still usable outside shard_map
    p = lm.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.randint(0, 20, (2, 16)), jnp.int32)
    assert np.isfinite(float(lm.loss_vector(p, {"input_ids": ids}).mean()))


def test_fsdp_pspecs_shard_large_only():
    spec = build_registry_spec("transformer_classifier", **TINY_CLF)
    m = model_from_json(spec)
    specs = fsdp_pspecs(m.param_specs(), min_size=32 * 96)
    assert "fsdp" in str(specs["block_0"]["qkv_kernel"])
    assert str(specs["block_0"]["ln1_scale"]) == "PartitionSpec()"


def test_resnet_variants():
    for depth, np_expect in ((18, None), (50, None)):
        m = model_from_json(build_registry_spec("resnet", num_classes=10,
                                                depth=depth, image_size=32))
        p = m.init(jax.random.PRNGKey(0))
        x = np.random.rand(2, 32, 32, 3).astype(np.float32)
        out = m.apply(p, {"x": x}, ["logits:0", "pred:0"])
        assert out["logits:0"].shape == (2, 10)
    total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(p))
    assert 20e6 < total < 30e6  # ResNet-50 ~23.5M params


@pytest.mark.slow  # ~80s: full resnet-18 Trainer fit; run by path when
# touching models/resnet or conv lowering
def test_resnet_trains_via_trainer():
    spec = build_registry_spec("resnet", num_classes=2, depth=18, image_size=8)
    rs = np.random.RandomState(0)
    x = rs.rand(32, 8, 8, 3).astype(np.float32)
    labels = (x.mean(axis=(1, 2, 3)) > 0.5).astype(int)
    y = np.eye(2)[labels].astype(np.float32)
    tr = Trainer(spec, "x:0", "y:0", iters=5, mini_batch_size=16,
                 learning_rate=0.01)
    res = tr.fit(x.reshape(32, -1).reshape(32, 8, 8, 3), y)
    assert np.isfinite(res.losses[-1])


def test_presets_build():
    for spec in (presets.mlp(20, 3), presets.cnn(28, 1, 10),
                 presets.autoencoder(50, (16, 4, 16))):
        m = model_from_json(spec)
        p = m.init(jax.random.PRNGKey(0))
        assert p


def test_tp_sharded_step_with_pallas_eligible_shapes():
    """Seq/head shapes that satisfy the pallas tiling constraints must still
    compile + run under a tp x dp sharded jit: the trace guard forces the
    GSPMD-partitionable blockwise attention path (ADVICE r1, tp.py:77)."""
    mesh = make_mesh({"dp": 4, "tp": 2})
    spec = build_registry_spec("transformer_classifier", vocab_size=64,
                               num_classes=3, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=128,
                               dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    sharded = shard_params(jax.tree.map(jnp.copy, params), mesh, m.param_pspecs())
    opt = build_optimizer("adam", 1e-3, None)
    step = make_sharded_train_step(m, opt, mesh, "input_ids", "y")
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (8, 128)), jnp.float32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)
    mask = jnp.ones((8,), jnp.float32)
    _, _, loss = step(sharded, opt.init(sharded), ids, y, mask,
                      jax.random.PRNGKey(1))
    ref = m.loss_vector(params, {"input_ids": ids, "y": y},
                        train=False).mean()
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4, atol=1e-4)


def test_dp_shardmap_step_matches_gspmd_and_runs_pallas():
    """shard_map DP step: same numerics as the GSPMD step, and the pallas
    flash-attention kernel actually executes (operands are device-local, so
    no GSPMD partitioning rule is needed — the multi-chip kernel path)."""
    from sparkflow_tpu.core import make_loss_fn, make_train_step
    from sparkflow_tpu.ops import attention as A
    from sparkflow_tpu.parallel.dp import make_dp_shardmap_train_step

    mesh = make_mesh({"dp": 8})
    spec = build_registry_spec("transformer_classifier", vocab_size=32,
                               num_classes=3, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=128,
                               dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    opt = build_optimizer("gradient_descent", 0.1, None)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 32, (8, 128)), jnp.float32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)
    mask = jnp.ones((8,), jnp.float32)

    calls = []
    orig = A._flash_pallas_forward

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    A._flash_pallas_forward = spy
    try:
        step = make_dp_shardmap_train_step(m, opt, mesh, "input_ids", "y")
        p1, _, l1 = step(jax.tree.map(jnp.copy, params), opt.init(params),
                         ids, y, mask, jax.random.PRNGKey(1))
    finally:
        A._flash_pallas_forward = orig
    assert calls, "pallas kernel was not reached under shard_map"

    gstep = make_train_step(make_loss_fn(m, "input_ids", "y"), opt, mesh)
    p2, _, l2 = gstep(jax.tree.map(jnp.copy, params), opt.init(params),
                      ids, y, mask, jax.random.PRNGKey(1))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_sp_step_gradients_exact_vs_masked_reference():
    """Pin sp gradients exactly: a single-device reference computing the SAME
    loss (per-shard next-token NLL, shard-boundary targets excluded) must
    produce the same loss and the same SGD update as the sp step."""
    import optax
    n_sp = 4
    S = 32
    Sl = S // n_sp
    mesh = make_mesh({"dp": 2, "sp": n_sp})
    spec = build_registry_spec("transformer_lm", vocab_size=50, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=S, dropout=0.0)
    lm = model_from_json(spec)
    params = lm.init(jax.random.PRNGKey(0))
    opt = build_optimizer("gradient_descent", 0.1, None)
    step = make_sp_train_step(lm, opt, mesh)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 50, (4, S)), jnp.int32)
    mask = jnp.ones((4, S), jnp.float32)
    p2, _, loss = step(jax.tree.map(jnp.copy, params), opt.init(params), ids,
                       mask, jax.random.PRNGKey(3))

    def ref_loss(p):
        # full-attention logits (ring attention is exact), but the TOKEN loss
        # counts only each shard's local targets 1..Sl-1 (boundary targets
        # between shards excluded, exactly the sp semantics)
        logits = lm.apply(p, {"input_ids": ids, "attention_mask": mask},
                          ["logits"], train=False)["logits"]
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(logp, ids[:, 1:, None], axis=-1)[..., 0]
        w = np.ones((4, S - 1), np.float32)
        for i in range(1, n_sp):
            w[:, i * Sl - 1] = 0.0  # target at a shard boundary
        w = jnp.asarray(w)
        return jnp.sum(nll * w) / jnp.sum(w)

    np.testing.assert_allclose(float(loss), float(ref_loss(params)),
                               rtol=1e-5)
    g = jax.grad(ref_loss)(params)
    sgd = optax.apply_updates(params, jax.tree.map(lambda x: -0.1 * x, g))
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(sgd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_fsdp_training_matches_replicated():
    """ZeRO-style parameter sharding end-to-end: a GSPMD step with
    fsdp-sharded params matches the replicated step's loss and update."""
    import optax
    mesh = make_mesh({"dp": 1, "fsdp": 8})
    spec = build_registry_spec("transformer_classifier", vocab_size=64,
                               num_classes=3, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=16,
                               dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    fspecs = fsdp_pspecs(m.param_specs(), min_size=32 * 64)
    sharded = shard_params(jax.tree.map(jnp.copy, params), mesh, fspecs)
    assert any("fsdp" in str(l.sharding.spec)
               for l in jax.tree.leaves(sharded))
    opt = build_optimizer("gradient_descent", 0.1, None)
    step = make_sharded_train_step(m, opt, mesh, "input_ids", "y")
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (8, 16)), jnp.float32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)
    mask = jnp.ones((8,), jnp.float32)
    p2, _, loss = step(sharded, opt.init(sharded), ids, y, mask,
                       jax.random.PRNGKey(1))

    def ref_loss(p):
        return m.loss_vector(p, {"input_ids": ids, "y": y},
                             train=False).mean()

    np.testing.assert_allclose(float(loss), float(ref_loss(params)),
                               rtol=1e-5)
    g = jax.grad(ref_loss)(params)
    sgd = optax.apply_updates(params, jax.tree.map(lambda x: -0.1 * x, g))
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(sgd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)
    # updated params keep their fsdp placement
    assert any("fsdp" in str(l.sharding.spec) for l in jax.tree.leaves(p2))


def test_tp_training_update_exact_vs_single_device():
    """Megatron TP via GSPMD: one tp(4)xdp(2) step equals single-device SGD
    leaf for leaf (the strictest pin, matching the pp/sp/fsdp tests)."""
    import optax
    mesh = make_mesh({"dp": 2, "tp": 4})
    spec = build_registry_spec("transformer_classifier", vocab_size=64,
                               num_classes=3, hidden=32, num_layers=2,
                               num_heads=4, mlp_dim=64, max_len=16,
                               dropout=0.0)
    m = model_from_json(spec)
    params = m.init(jax.random.PRNGKey(0))
    sharded = shard_params(jax.tree.map(jnp.copy, params), mesh,
                           m.param_pspecs())
    opt = build_optimizer("gradient_descent", 0.1, None)
    step = make_sharded_train_step(m, opt, mesh, "input_ids", "y")
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, 64, (8, 16)), jnp.float32)
    y = jnp.asarray(np.eye(3)[rs.randint(0, 3, 8)], jnp.float32)
    mask = jnp.ones((8,), jnp.float32)
    p2, _, loss = step(sharded, opt.init(sharded), ids, y, mask,
                       jax.random.PRNGKey(1))

    def ref_loss(p):
        return m.loss_vector(p, {"input_ids": ids, "y": y},
                             train=False).mean()

    np.testing.assert_allclose(float(loss), float(ref_loss(params)), rtol=1e-5)
    g = jax.grad(ref_loss)(params)
    sgd = optax.apply_updates(params, jax.tree.map(lambda x: -0.1 * x, g))
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(sgd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_remat_modes_identical_numerics():
    """remat=False / True (full) / 'dots' (save matmul outputs) must give
    identical losses and gradients — remat trades memory for recompute,
    never numerics. Bad mode fails loudly."""
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 50, (4, 16)), jnp.int32)

    results = {}
    for mode in (False, True, "dots"):
        m = model_from_json(build_registry_spec(
            "transformer_lm", vocab_size=50, hidden=32, num_layers=2,
            num_heads=4, mlp_dim=64, max_len=16, dropout=0.0, remat=mode))
        params = m.init(jax.random.PRNGKey(0))

        def loss(p):
            return m.loss_vector(p, {"input_ids": ids}, train=False).mean()

        l, g = jax.value_and_grad(loss)(params)
        results[mode] = (float(l), g)

    l0, g0 = results[False]
    for mode in (True, "dots"):
        l, g = results[mode]
        assert abs(l - l0) < 1e-6, (mode, l, l0)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="remat"):
        model_from_json(build_registry_spec(
            "transformer_lm", vocab_size=50, hidden=32, num_layers=1,
            num_heads=4, mlp_dim=64, max_len=16, remat="everything"))
