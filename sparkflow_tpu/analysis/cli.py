"""graftcheck CLI: ``python -m sparkflow_tpu.analysis [paths...]``.

Runs the static passes (ast_lint + per-class lock coverage + the
whole-package lock-order/blocking graph) over every ``.py`` file under the
given paths, plus — unless ``--no-trace`` — the jaxpr self-check over the
repo's model presets and optimizer registry. Exit status is the finding
count clamped to 1, so CI can gate on it; ``--format json`` emits one
finding object per line (JSONL) for tooling.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import List, Optional, Sequence

from . import ast_lint, lifecycle, lockgraph, locks, policy_lint, tracelint
from .findings import RULES, Finding, format_findings

__all__ = ["main", "run_static", "run_all", "load_baseline",
           "apply_baseline"]


def run_static(paths: Sequence[str]) -> List[Finding]:
    """ast_lint + per-class lock coverage + the whole-package lock graph
    (deadlock/blocking-under-lock) + pure-policy purity + resource
    lifecycles + trace-propagation over every .py under ``paths``."""
    return (ast_lint.lint_paths(paths) + locks.lint_paths(paths)
            + lockgraph.lint_paths(paths) + policy_lint.lint_paths(paths)
            + lifecycle.lint_paths(paths) + tracelint.lint_paths(paths))


def _baseline_key(d: dict) -> tuple:
    # line numbers shift on every edit; (rule, path, message) is what makes
    # a finding "the same one we already accepted" — and messages that
    # quote a line themselves ("acquire() at line 13 ...") get that
    # reference masked so an unrelated edit above doesn't unaccept them
    msg = re.sub(r"\bline \d+", "line ?", str(d.get("message", "")))
    return (d.get("rule"), d.get("path"), msg)


def load_baseline(path: str) -> set:
    """Accepted-finding keys from a JSONL baseline written by
    ``--write-baseline`` (or any ``--format json`` capture)."""
    keys = set()
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                keys.add(_baseline_key(json.loads(line)))
    return keys


def apply_baseline(findings: Sequence[Finding], path: str) -> List[Finding]:
    """Drop findings whose (rule, path, message) already appear in the
    baseline file — known-accepted debt stays out of the exit status while
    anything new still fails the gate."""
    keys = load_baseline(path)
    return [f for f in findings if _baseline_key(f.to_dict()) not in keys]


def run_all(paths: Sequence[str], trace: bool = True,
            ignore: Sequence[str] = ()) -> List[Finding]:
    """The full graftcheck pass: static rules over ``paths`` and, with
    ``trace``, the jaxpr repo self-check (model presets x optimizers)."""
    ignore = set(ignore)
    findings = [f for f in run_static(paths) if f.rule not in ignore]
    if trace:
        from . import jaxpr_lint
        findings.extend(jaxpr_lint.repo_self_check(ignore=ignore))
    return findings


def _list_rules() -> str:
    lines = ["graftcheck rule catalog (docs/analysis.md has the long form):"]
    for rule_id in sorted(RULES):
        name, desc = RULES[rule_id]
        lines.append(f"  {rule_id}  {name:<24} {desc}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparkflow_tpu.analysis",
        description="graftcheck: sharding / tracing / concurrency lint "
                    "for sparkflow-tpu code")
    parser.add_argument("paths", nargs="*", default=["sparkflow_tpu"],
                        help="files or directories to lint "
                             "(default: sparkflow_tpu)")
    parser.add_argument("--no-trace", action="store_true",
                        help="skip the jaxpr self-check over the repo's "
                             "model presets and optimizer registry")
    parser.add_argument("--ignore", default="",
                        help="comma-separated rule ids to drop "
                             "(e.g. GC-A203,GC-L302)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="JSONL of known-accepted findings (from "
                             "--write-baseline): exact matches are "
                             "suppressed, new findings still fail")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write the current findings to FILE as JSONL "
                             "and exit 0 — the accepted-debt snapshot a "
                             "later --baseline run diffs against")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    ignore = [r.strip() for r in args.ignore.split(",") if r.strip()]
    findings = run_all(args.paths, trace=not args.no_trace, ignore=ignore)

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            for f in findings:
                fh.write(json.dumps(f.to_dict(), sort_keys=True) + "\n")
        print(f"graftcheck: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0
    if args.baseline:
        findings = apply_baseline(findings, args.baseline)

    if args.format == "json":
        # JSONL: one finding object per line, so editors/CI can stream-parse
        # (and `grep GC-L304 | head -1 | jq` just works); clean run = no output
        for f in findings:
            print(json.dumps(f.to_dict(), sort_keys=True))
    elif findings:
        print(format_findings(findings))
        print(f"\ngraftcheck: {len(findings)} finding(s)")
    else:
        print("graftcheck: clean")
    return 1 if findings else 0
