"""GC-S501 impure-policy: purity lint for marked policy modules.

The policy/transport split (``serving/policies.py``) only holds if policy
code stays a pure function of its inputs: the fleet simulator replays
those decisions deterministically in virtual time, so a stray
``time.monotonic()`` or ``random.random()`` inside a policy silently
forks sim behavior from production behavior — the worst kind of model
error, because every parity test still passes on the code paths it pins.

This analyzer enforces the contract mechanically. A module opts in with a
marker comment in its first ten lines::

    # graftcheck: pure-policy

and every opted-in module is then denied, anywhere in the file:

- **imports** of impure modules (``time``, ``random``, ``secrets``,
  ``socket``, ``select``, ``threading``, ``subprocess``, ``asyncio``,
  ``http``, ``urllib``, ``os``, ``datetime``) — time must arrive as a
  ``now`` argument, randomness pre-drawn by the caller;
- **calls** into those modules however aliased (``import time as t`` /
  ``from time import monotonic`` are caught at the import), plus bare
  ``open``/``input``/``print``/``eval``/``exec`` and any ``*.sleep(...)``
  — no files, no terminals, no blocking.

Suppression follows the standard graftcheck syntax (trailing
``# graftcheck: disable=GC-S501`` / file-level ``disable-file=``), and
``tests/test_analysis.py`` gates the repo: the real policy module must
lint clean, and planted defects in both directions (an impurity that must
be flagged, clean code that must not be) pin the analyzer itself.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .ast_lint import iter_py_files
from .findings import Finding, filter_suppressed

__all__ = ["PURE_POLICY_MARKER", "lint_source", "lint_file", "lint_paths"]

PURE_POLICY_MARKER = "graftcheck: pure-policy"

#: modules whose very import means wall-clock, randomness, blocking, or
#: I/O is reachable from policy code
IMPURE_MODULES: Set[str] = {
    "time", "random", "secrets", "socket", "select", "threading",
    "subprocess", "asyncio", "http", "urllib", "os", "datetime",
}

#: bare builtins that do I/O or execute dynamic code
IMPURE_BUILTINS: Set[str] = {"open", "input", "print", "eval", "exec"}


def _is_marked(source: str) -> bool:
    head = source.splitlines()[:10]
    return any(PURE_POLICY_MARKER in line for line in head)


class _PurityVisitor(ast.NodeVisitor):
    def __init__(self, path: Optional[str]):
        self.path = path
        self.findings: List[Finding] = []
        # names bound (by import) to impure modules or their members,
        # so aliased calls are caught too
        self.tainted: Set[str] = set()

    def _hit(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "GC-S501", f"{what} in a pure-policy module — policies take "
            f"time as a `now` argument and pre-drawn randomness, never "
            f"the impure source itself", path=self.path,
            line=getattr(node, "lineno", None), source="policy_lint"))

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            root = alias.name.split(".")[0]
            if root in IMPURE_MODULES:
                self._hit(node, f"import of impure module "
                                f"'{alias.name}'")
                self.tainted.add(alias.asname or root)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        root = (node.module or "").split(".")[0]
        if node.level == 0 and root in IMPURE_MODULES:
            names = ", ".join(a.name for a in node.names)
            self._hit(node, f"import from impure module '{node.module}' "
                            f"({names})")
            for a in node.names:
                self.tainted.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id in IMPURE_BUILTINS:
                self._hit(node, f"call to '{fn.id}()'")
            elif fn.id in self.tainted:
                self._hit(node, f"call to '{fn.id}()' (imported from an "
                                f"impure module)")
        elif isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name) and (base.id in IMPURE_MODULES
                                               or base.id in self.tainted):
                self._hit(node, f"call to '{base.id}.{fn.attr}()'")
            elif fn.attr == "sleep":
                self._hit(node, "call to a '.sleep()' method")
        self.generic_visit(node)


def lint_source(source: str, path: Optional[str] = None) -> List[Finding]:
    """Lint one module's source; returns [] unless it carries the
    pure-policy marker."""
    if not _is_marked(source):
        return []
    try:
        tree = ast.parse(source, filename=path or "<policy>")
    except SyntaxError:
        return []   # the interpreter's problem, not this lint's
    visitor = _PurityVisitor(path)
    visitor.visit(tree)
    visitor.findings.sort(key=lambda f: (f.line or 0, f.message))
    return filter_suppressed(visitor.findings, source)


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f))
    return findings
