"""Attention kernels: pallas flash attention + ring attention (sequence parallel).

Nothing like this exists in the reference (it has no attention or sequence code
at all — SURVEY.md §5 "Long-context"); these ops are the long-context foundation
of the framework's transformer models.

Layout convention: ``[batch, heads, seq, head_dim]``.

- :func:`flash_attention`: single-device fused attention. The pallas kernel
  tiles Q into ``block_q`` rows and streams K/V in ``block_k`` columns with the
  online-softmax recurrence, so the S x S score matrix never hits HBM; scores
  accumulate in f32 on the MXU regardless of input dtype. Falls back to a pure
  jnp implementation off-TPU (CPU tests) and for tiny shapes where tiling
  constraints don't hold.

- :func:`ring_attention`: attention over a sequence-sharded mesh axis (``sp``).
  Each device holds S/n of Q/K/V; K/V shards rotate around the ring via
  ``ppermute`` (ICI neighbor exchange) for n steps while each device folds the
  visiting block into its running (max, sum, acc) softmax state. Communication
  overlaps compute and per-device memory stays O(S/n) — the standard TPU
  long-context recipe (Liu et al., Ring Attention; jax-ml scaling-book §sharding).
"""

from __future__ import annotations

import contextlib
import functools
import math
from contextvars import ContextVar
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..jax_compat import axis_size

try:  # pallas TPU backend (absent in some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30

# Trace-time switch: pallas_call lowers to a custom call that GSPMD has no
# partitioning rule for, so under a sharded jit the kernel's operands may be
# sharded and the compiled program would replicate them (all-gather) or fail
# outright. Sharded train-step builders trace under sharded_attention()
# (below), which keeps the kernel by nesting a shard_map; this explicit
# override forces the GSPMD-partitionable blockwise path unconditionally —
# for tests and for callers that need the partitioner to own attention.
_FORCE_XLA: ContextVar[bool] = ContextVar("sparkflow_force_xla_attention",
                                          default=False)


@contextlib.contextmanager
def force_xla_attention():
    """Within this context (including jit *tracing* started inside it),
    :func:`flash_attention` routes to the XLA blockwise/reference path instead
    of the pallas kernel. See the note on ``_FORCE_XLA`` above."""
    tok = _FORCE_XLA.set(True)
    try:
        yield
    finally:
        _FORCE_XLA.reset(tok)


# Sharded-jit attention: GSPMD cannot partition the pallas custom call, but
# attention is embarrassingly parallel over batch and heads — so instead of
# forfeiting the kernel on every >1-device mesh (the old blanket
# force_xla_attention), sharded traces set this context and flash_attention
# wraps ITSELF in a nested shard_map over (batch x heads), running the
# pallas kernel per shard with zero communication. Falls back to the
# blockwise path when the dims don't divide the mesh axes.
_SHARD_ATTN: ContextVar = ContextVar("sparkflow_shard_attention",
                                     default=None)


@contextlib.contextmanager
def sharded_attention(mesh, batch_axis: str = "dp", head_axis: str = "tp"):
    """Within this context (including jit tracing started inside it),
    :func:`flash_attention` runs the pallas kernel per (batch, heads) shard
    via shard_map over ``mesh`` instead of degrading to XLA blockwise."""
    tok = _SHARD_ATTN.set((mesh, batch_axis, head_axis))
    try:
        yield
    finally:
        _SHARD_ATTN.reset(tok)


@contextlib.contextmanager
def unsharded_attention():
    """Within this context (including jit tracing started inside it),
    :func:`flash_attention` ignores any enclosing :func:`sharded_attention`
    — for step builders that manage their OWN shard_map (pp/sp): their
    bodies run per-shard already, and re-wrapping the kernel in a nested
    shard_map over the same mesh axes would be invalid."""
    tok = _SHARD_ATTN.set(None)
    try:
        yield
    finally:
        _SHARD_ATTN.reset(tok)


def _try_shardmap_flash(q, k, v, kv_mask, causal, scale, interpret,
                        block_q=None, block_k=None):
    """shard_map-wrapped flash for sharded-jit traces, or None when the
    context is unset / the shapes don't divide the mesh axes."""
    ctx = _SHARD_ATTN.get()
    if ctx is None:
        return None
    mesh, ba, ha = ctx
    bsz = int(mesh.shape.get(ba, 1))
    hsz = int(mesh.shape.get(ha, 1))
    b, h = q.shape[0], q.shape[1]
    if bsz * hsz <= 1 or b % bsz or h % hsz:
        return None
    from ..jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    bspec = ba if bsz > 1 else None
    hspec = ha if hsz > 1 else None
    qkv_spec = P(bspec, hspec)

    def inner(q, k, v, *m):
        # the body must not recurse into the wrapper, and per-shard
        # divisibility/tiling decisions are flash_attention's own;
        # explicitly pinned tile sizes stay pinned per shard (the
        # documented contract)
        tok = _SHARD_ATTN.set(None)
        try:
            return flash_attention(q, k, v, causal=causal, sm_scale=scale,
                                   interpret=interpret,
                                   block_q=block_q, block_k=block_k,
                                   kv_mask=m[0] if m else None)
        finally:
            _SHARD_ATTN.reset(tok)

    in_specs = (qkv_spec, qkv_spec, qkv_spec)
    args = (q, k, v)
    if kv_mask is not None:
        in_specs += (P(bspec),)
        args += (kv_mask,)
    return shard_map(inner, mesh=mesh, in_specs=in_specs,
                     out_specs=qkv_spec, check_vma=False)(*args)


# Which path the most recent flash_attention TRACE took ('pallas',
# 'blockwise', or 'reference'). Benchmarks assert this is 'pallas' after
# compiling their TPU step: a kernel edit that breaks the tile rules would
# otherwise fall back silently and the suite would stay green while the
# perf path quietly degraded (the round-2 (8,128)-tile regression).
# A ContextVar (like _FORCE_XLA/_SHARD_ATTN) so an interleaved trace in
# another thread cannot clobber the value between a caller's compile and
# its last_attention_path() check.
_LAST_PATH: ContextVar = ContextVar("sparkflow_last_attention_path",
                                    default=None)


def last_attention_path():
    """Path taken by the most recent :func:`flash_attention` call (at trace
    time for jitted callers) in this thread/context: 'pallas' | 'blockwise'
    | 'reference' | None."""
    return _LAST_PATH.get()


# ---------------------------------------------------------------------------
# Reference (jnp) implementation — ground truth for tests + CPU fallback
# ---------------------------------------------------------------------------


def attention_reference(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        q_offset: int = 0, k_offset: int = 0, kv_mask=None):
    """Plain softmax attention, f32 accumulation. Shapes [B,H,S,D];
    ``kv_mask`` [B,S_k] masks padded keys (1 = attend)."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0) + q_offset
        ki = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1) + k_offset
        s = jnp.where(qi >= ki, s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU)
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, *rest, sm_scale: float, causal: bool,
                  block_q: int, block_k: int, has_mask: bool):
    if has_mask:
        mask_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0]                               # [block_q, d] input dtype
        k = k_ref[0]                               # [block_k, d]
        v = v_ref[0]                               # [block_k, d]
        # native-dtype operands on the MXU, f32 accumulation
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_k
            s = jnp.where(rows >= cols, s, NEG_INF)
        if mask_ref is not None:  # [1, block_k] key-padding mask for this batch row
            s = jnp.where(mask_ref[0] > 0, s, NEG_INF)

        m_prev = m_ref[:]                          # [block_q, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [block_q, block_k] f32
        alpha = jnp.exp(m_prev - m_new)            # [block_q, 1]
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # blocks entirely above the diagonal contribute nothing — skip them
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
        # logsumexp per q row — the backward kernels recompute p from it.
        # Kept [block_q, 1]: a trailing unit dim makes the block legal under
        # the TPU (8, 128) tile rule (a [1, block_q] block is not)
        lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _flash_pallas_forward(q, k, v, kv_mask, causal, scale, block_q, block_k,
                          interpret, with_lse=False):
    b, h, s, d = q.shape
    sk = k.shape[2]
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    has_mask = kv_mask is not None

    kernel = functools.partial(_flash_kernel, sm_scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    args = [qf, kf, vf]
    if has_mask:
        # per-batch key mask as [B, 1, Sk]; block row selected by bh // h
        # (the unit middle dim keeps the [1, 1, block_k] block tile-legal)
        in_specs.append(pl.BlockSpec((1, 1, block_k),
                                     lambda bh, qi, ki, _h=h: (bh // _h, 0, ki)))
        args.append(kv_mask.astype(jnp.float32)[:, None, :])
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q, sk // block_k),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
                   pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0))),
        out_shape=(jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    out = out.reshape(b, h, s, d)
    if with_lse:
        return out, lse.reshape(b, h, s)
    return out


# Tile-legal [1, block, 1] block over a [bh, s, 1] row-statistics array —
# shared by the lse/delta operands of the forward and backward kernels
def _row_stat_spec(block, order="qk"):
    if order == "qk":   # grid (bh, qi, ki)
        return pl.BlockSpec((1, block, 1), lambda bh_, qi, ki: (bh_, qi, 0))
    return pl.BlockSpec((1, block, 1), lambda bh_, ki, qi: (bh_, qi, 0))


def _blockwise_attention(q, k, v, kv_mask, causal, scale, block_k=512):
    """Differentiable blockwise attention in pure jnp: lax.scan over K/V
    blocks with the online-softmax fold, each block rematerialized — O(S*block)
    live memory instead of O(S^2). This is the autodiff path behind the pallas
    kernel's custom_vjp (gradients recompute flash-style; the S x S score
    matrix never materializes in either direction)."""
    b, h, s, d = q.shape
    sk = k.shape[2]
    block_k = min(block_k, sk)
    if sk % block_k:
        # can't tile: the dense reference path, mask honored
        return attention_reference(q, k, v, causal, scale, kv_mask=kv_mask)
    nblk = sk // block_k
    kb = k.reshape(b, h, nblk, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, block_k, d).transpose(2, 0, 1, 3, 4)
    if kv_mask is not None:
        mb = kv_mask.reshape(b, nblk, block_k).transpose(1, 0, 2)
    else:
        mb = jnp.ones((nblk, b, 1), jnp.float32)  # dummy, unused

    @jax.checkpoint
    def fold(carry, blk):
        acc, m, l = carry
        kc, vc, mc, idx = blk
        a2, m2, l2 = _block_stats(q, kc, vc, scale, causal, 0, idx * block_k,
                                  mc if kv_mask is not None else None)
        return _merge_stats(acc, m, l, a2, m2, l2), None

    init = (jnp.zeros((b, h, s, d), jnp.float32),
            jnp.full((b, h, s, 1), NEG_INF, jnp.float32),
            jnp.zeros((b, h, s, 1), jnp.float32))
    (acc, m, l), _ = jax.lax.scan(fold, init, (kb, vb, mb, jnp.arange(nblk)))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash attention backward (dq and dk/dv kernels, flash-style recompute)
#
# Standard recurrence (Dao, FlashAttention-2): with row stats L = logsumexp
# saved by the forward and D_i = rowsum(dO_i * O_i),
#   P   = exp(S - L);  dV = P^T dO;  dP = dO V^T
#   dS  = P * (dP - D);  dQ = scale * dS K;  dK = scale * dS^T Q
# The S x S matrices exist only block-by-block in VMEM, same as the forward.
# ---------------------------------------------------------------------------


def _bwd_p_block(q, k, lse, sm_scale, causal, qi0, ki0, mask_blk):
    """Recompute the normalized probability block P = exp(S - L) [bq, bk];
    masked/causal-excluded entries are exactly 0 (no exp of NEG_INF deltas).
    ``lse`` is [bq, 1]; ``mask_blk`` is [1, bk] (both broadcast over S)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi0
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki0
        s = jnp.where(rows >= cols, s, NEG_INF)
    if mask_blk is not None:
        s = jnp.where(mask_blk > 0, s, NEG_INF)
    # rows with every key masked have lse ~ NEG_INF; gate on s to keep p = 0
    return jnp.where(s > 0.5 * NEG_INF, jnp.exp(s - lse), 0.0)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, sm_scale, causal, block_q, block_k, has_mask):
    if has_mask:
        mask_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        p = _bwd_p_block(q, k, lse_ref[0], sm_scale, causal,
                         qi * block_q, ki * block_k,
                         mask_ref[0] if mask_ref is not None else None)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dq_acc[:] += sm_scale * jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *rest, sm_scale, causal, block_q, block_k, has_mask):
    if has_mask:
        mask_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        mask_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        p = _bwd_p_block(q, k, lse_ref[0], sm_scale, causal,
                         qi * block_q, ki * block_k,
                         mask_ref[0] if mask_ref is not None else None)
        # dV_j += P^T dO ; dK_j += scale * dS^T Q
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += sm_scale * jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q blocks entirely above this k block's diagonal see p = 0
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_prep(q, out, lse, g):
    """Flatten the q-side operands and compute D = rowsum(dO * O) — all
    independent of the k/v side, so ring backward hoists this out of the
    per-visit loop. Row statistics travel as [bh, s, 1]: tile-legal
    [1, block_q, 1] blocks (the layout the forward emits lse in)."""
    b, h, s, d = q.shape
    bh = b * h
    qf = q.reshape(bh, s, d)
    gf = g.reshape(bh, s, d)
    lsef = lse.reshape(bh, s, 1)
    # D_i = rowsum(dO * O): tiny elementwise reduce, XLA fuses it fine
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, s, 1)
    return qf, gf, lsef, delta


def _flash_pallas_backward(q, k, v, kv_mask, out, lse, g, causal, scale,
                           block_q, block_k, interpret):
    qf, gf, lsef, delta = _flash_bwd_prep(q, out, lse, g)
    b, h, _, d = q.shape
    kf = k.reshape(b * h, -1, d)
    vf = v.reshape(b * h, -1, d)
    maskf = (kv_mask.astype(jnp.float32)[:, None, :]
             if kv_mask is not None else None)
    dq, dk, dv = _flash_pallas_backward_flat(
        qf, kf, vf, gf, lsef, delta, maskf, h, causal, scale,
        block_q, block_k, interpret)
    s, sk = q.shape[2], k.shape[2]
    return (dq.reshape(b, h, s, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _flash_pallas_backward_flat(qf, kf, vf, gf, lsef, delta, maskf, h,
                                causal, scale, block_q, block_k, interpret):
    bh, s, d = qf.shape
    sk = kf.shape[1]
    has_mask = maskf is not None

    common = dict(sm_scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, has_mask=has_mask)
    qspec = pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0))
    row_q = _row_stat_spec(block_q, "qk")

    in_specs_dq = [
        qspec,
        pl.BlockSpec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, 0)),
        qspec, row_q, row_q,
    ]
    args_dq = [qf, kf, vf, gf, lsef, delta]
    if has_mask:
        in_specs_dq.append(pl.BlockSpec(
            (1, 1, block_k), lambda bh_, qi, ki, _h=h: (bh_ // _h, 0, ki)))
        args_dq.append(maskf)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(bh, s // block_q, sk // block_k),
        in_specs=in_specs_dq,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qf.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args_dq)

    kspec = pl.BlockSpec((1, block_k, d), lambda bh_, ki, qi: (bh_, ki, 0))
    in_specs_kv = [
        pl.BlockSpec((1, block_q, d), lambda bh_, ki, qi: (bh_, qi, 0)),
        kspec, kspec,
        pl.BlockSpec((1, block_q, d), lambda bh_, ki, qi: (bh_, qi, 0)),
        _row_stat_spec(block_q, "kq"),
        _row_stat_spec(block_q, "kq"),
    ]
    args_kv = [qf, kf, vf, gf, lsef, delta]
    if has_mask:
        in_specs_kv.append(pl.BlockSpec(
            (1, 1, block_k), lambda bh_, ki, qi, _h=h: (bh_ // _h, 0, ki)))
        args_kv.append(maskf)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(bh, sk // block_k, s // block_q),
        in_specs=in_specs_kv,
        out_specs=(kspec, kspec),
        out_shape=(jax.ShapeDtypeStruct((bh, sk, d), kf.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), vf.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args_kv)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, kv_mask, causal, scale, block_q, block_k,
           bwd_block_q, bwd_block_k, interpret):
    return _flash_pallas_forward(q, k, v, kv_mask, causal, scale, block_q,
                                 block_k, interpret)


def _flash_fwd(q, k, v, kv_mask, causal, scale, block_q, block_k,
               bwd_block_q, bwd_block_k, interpret):
    out, lse = _flash_pallas_forward(q, k, v, kv_mask, causal, scale, block_q,
                                     block_k, interpret, with_lse=True)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, bwd_block_q, bwd_block_k,
               interpret, res, g):
    q, k, v, kv_mask, out, lse = res
    dq, dk, dv = _flash_pallas_backward(q, k, v, kv_mask, out, lse, g, causal,
                                        scale, bwd_block_q, bwd_block_k,
                                        interpret)
    return dq, dk, dv, None  # mask carries no gradient


_flash.defvjp(_flash_fwd, _flash_bwd)


def _auto_block(n: int, cap: int) -> int:
    """Largest power-of-two block <= cap that divides n (from 128 up).
    Sequences shorter than 128 get the sequence itself (the old
    ``min(128, s)`` clamp) so short-q cross-attention keeps the kernel."""
    if n < 128:
        return n
    b = 128
    while b * 2 <= cap and n % (b * 2) == 0:
        b *= 2
    return b


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    kv_mask=None):
    """Fused attention; [B,H,S,D] -> [B,H,S,D]. ``kv_mask`` is an optional
    [B, S_k] key-padding mask (1 = attend).

    Forward runs the pallas kernel on TPU when the sequence tiles cleanly
    (otherwise the jnp reference path — numerics match to fp tolerance).
    Backward goes through a custom VJP with its own pallas dq/dk/dv kernels.

    ``block_q``/``block_k`` default to an auto choice PER DIMENSION AND PATH:
    the forward kernel prefers the largest tiles that divide the sequence
    (up to 1024 — measured ~2x faster than 512x512 at seq 4096 on v5e),
    while the backward kernels prefer 512 (the dq and dkv grids re-stream
    more operands per tile, so bigger tiles lose). An explicitly passed
    value pins that dimension on BOTH paths; the other stays auto.
    """
    b, h, s, d = q.shape
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    _user_block_q, _user_block_k = block_q, block_k  # pre-auto-derivation

    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    # p-tile is block_q*block_k f32: cap the product at 2^20 (4 MB VMEM)
    cap = 1024 if d <= 128 else 512
    bwd_block_q = min(block_q, s) if block_q is not None else _auto_block(s, 512)
    bwd_block_k = min(block_k, sk) if block_k is not None else _auto_block(sk, 512)
    block_q = min(block_q, s) if block_q is not None else _auto_block(s, cap)
    block_k = min(block_k, sk) if block_k is not None else _auto_block(sk, cap)
    # the XLA blockwise path materializes [B,H,S,block_k] f32 score blocks
    # in HBM — the pallas-tuned (VMEM-sized) auto block would inflate that
    # up to 8x, so the fallbacks cap at the scan's own tuned default
    xla_block_k = min(block_k, 512)
    if _FORCE_XLA.get():
        # explicit override (tests, callers that need the GSPMD-partitionable
        # form): blockwise unconditionally
        _LAST_PATH.set("blockwise")
        return _blockwise_attention(q, k, v, kv_mask, causal, scale,
                                    block_k=xla_block_k)
    wrapped = _try_shardmap_flash(q, k, v, kv_mask, causal, scale, interpret,
                                  block_q=_user_block_q, block_k=_user_block_k)
    if wrapped is not None:
        return wrapped
    if _SHARD_ATTN.get() is not None:
        # sharded-jit trace but the shapes don't divide the mesh's
        # batch/heads axes (or the mesh has neither): the plain pallas call
        # would hand GSPMD an unpartitionable custom call — blockwise is the
        # partitionable form
        _LAST_PATH.set("blockwise")
        return _blockwise_attention(q, k, v, kv_mask, causal, scale,
                                    block_k=xla_block_k)
    # TPU tiling: q-rows multiple of 8 (sublanes), k-cols multiple of 128
    # (lanes); sequences must tile exactly (pad upstream otherwise)
    tiles_ok = (pltpu is not None
                and s % block_q == 0 and sk % block_k == 0
                and s % bwd_block_q == 0 and sk % bwd_block_k == 0
                and block_q % 8 == 0 and block_k % 128 == 0
                and bwd_block_q % 8 == 0 and bwd_block_k % 128 == 0
                and d % 8 == 0)
    if not tiles_ok:
        if kv_mask is None:
            _LAST_PATH.set("reference")
            return attention_reference(q, k, v, causal, scale)
        # blockwise keeps memory bounded when it tiles; its own fallback is
        # the dense reference path with the mask honored
        _LAST_PATH.set("blockwise")
        return _blockwise_attention(q, k, v, kv_mask, causal, scale,
                                    block_k=xla_block_k)
    _LAST_PATH.set("pallas")
    return _flash(q, k, v, kv_mask, causal, scale, block_q, block_k,
                  bwd_block_q, bwd_block_k, interpret)


# ---------------------------------------------------------------------------
# Paged attention (single-token decode over a page-table-indirected KV pool)
# ---------------------------------------------------------------------------


def _gather_dequant(pages, page_table, scales):
    """Gather pool pages per slot and (when quantized) apply the
    per-page-per-head scales: ``[num_pages, page, H, D]`` x ``[B, maxp]``
    -> ``[B, maxp*page, H, D]`` f32. The dequant convert runs on the
    GATHERED pages only — converting the whole pool is the GC-J108
    defect (it silently doubles peak pool memory)."""
    b, maxp = page_table.shape
    page, h, d = pages.shape[1:]
    g = pages[page_table].astype(jnp.float32)   # [B, maxp, page, H, D]
    if scales is not None:
        g = g * scales[page_table][:, :, None, :, None]
    return g.reshape(b, maxp * page, h, d)


def paged_attention_reference(q, k_pages, v_pages, page_table, lengths,
                              sm_scale: Optional[float] = None,
                              k_scales=None, v_scales=None):
    """Ground-truth decode attention over a paged KV pool, pure jnp.

    One query token per slot attends over that slot's cached keys/values,
    which live scattered across fixed-size pages of a shared pool:

    - ``q``: ``[B, H, D]`` — the current token's query per slot;
    - ``k_pages`` / ``v_pages``: ``[num_pages, page_size, H, D]`` pool;
    - ``page_table``: ``[B, max_pages]`` int32 — slot b's cache lives in
      pages ``page_table[b, :ceil(lengths[b]/page_size)]``, in order
      (entries past that count must still be valid pool indices — the
      manager points them at its scratch page);
    - ``lengths``: ``[B]`` int32 — valid tokens per slot; global position
      ``p * page_size + t < lengths[b]`` attends, everything else is
      masked. A slot with ``lengths == 0`` returns exact zeros.
    - ``k_scales`` / ``v_scales``: optional ``[num_pages, H]`` f32
      per-page-per-head dequantization scales for an int8/fp8 pool
      (``row = stored * scale``); pass both or neither.
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    b, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    # gather (and dequantize) the slot's whole logical cache
    k = _gather_dequant(k_pages, page_table, k_scales)
    v = _gather_dequant(v_pages, page_table, v_scales)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), k,
                   preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(k.shape[1], dtype=jnp.int32)
    valid = pos[None, :] < lengths[:, None]               # [B, K]
    s = jnp.where(valid[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, v)
    # all-masked rows softmax to uniform garbage; empty slots must be zeros
    out = jnp.where((lengths > 0)[:, None, None], out, 0.0)
    return out.astype(q.dtype)


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size: int, sm_scale: float):
    """Grid ``(B, max_pages)``; scalar-prefetched page table drives the
    K/V BlockSpec index maps, so program ``(b, p)`` sees slot b's p-th
    logical page already staged in VMEM. Online-softmax state (m, l, acc)
    folds across the slot's pages; pages at or past ``lengths[b]`` are
    skipped outright (no flops, state untouched)."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    np_ = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                  # [H, D]
        k = k_ref[0].astype(jnp.float32)                  # [page, H, D]
        v = v_ref[0].astype(jnp.float32)
        # s[h, t] = q[h, :] . k[t, h, :]  (batch over H, contract D)
        s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                                preferred_element_type=jnp.float32) * sm_scale
        tpos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(tpos < length, s, NEG_INF)          # ragged last page
        m_prev = m_ref[:]                                 # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pexp = jnp.exp(s - m_new)                         # [H, page]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(pexp, axis=1, keepdims=True)
        # acc[h, d] += sum_t pexp[h, t] * v[t, h, d]
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(p == np_ - 1)
    def _finalize():
        # empty slot: init state (acc 0, l 0) divides to exact zeros
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def _paged_kernel_quant(table_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                        vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                        page_size: int, sm_scale: float):
    """:func:`_paged_kernel` over an int8/fp8 pool: the page's K/V block
    arrives quantized and its ``[H]`` per-page-per-head scales ride the
    same scalar-prefetched index map. Dequantization happens INSIDE the
    accumulations in f32 — the K scale folds into the QK^T scores and the
    V scale into the PV update — so no full-precision page is ever
    materialized beyond the one block in VMEM."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    np_ = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                  # [H, D]
        k = k_ref[0].astype(jnp.float32)                  # [page, H, D] quant
        v = v_ref[0].astype(jnp.float32)
        ks = ks_ref[0]                                    # [H] f32
        vs = vs_ref[0]
        # s[h, t] = (q[h, :] . k_q[t, h, :]) * k_scale[h] * sm_scale
        s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                                preferred_element_type=jnp.float32
                                ) * (ks[:, None] * sm_scale)
        tpos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(tpos < length, s, NEG_INF)          # ragged last page
        m_prev = m_ref[:]                                 # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pexp = jnp.exp(s - m_new)                         # [H, page]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(pexp, axis=1, keepdims=True)
        # acc[h, d] += (sum_t pexp[h, t] * v_q[t, h, d]) * v_scale[h]
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * vs[:, None]
        m_ref[:] = m_new

    @pl.when(p == np_ - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, page_table, lengths,
                    sm_scale: Optional[float] = None,
                    interpret: Optional[bool] = None,
                    k_scales=None, v_scales=None):
    """Decode attention kernel: one query token per slot against a
    page-table-indirected K/V pool. Same operands/semantics as
    :func:`paged_attention_reference` (which is its parity ground truth).

    The pallas grid is ``(B, max_pages)`` with the page table and lengths
    scalar-prefetched (``PrefetchScalarGridSpec``): the BlockSpec index map
    reads ``page_table[b, p]``, so the gather over scattered pages happens
    in the pipeline's DMA stage, not as a materialized ``[B, maxp*page]``
    cache copy the way the reference does it. Pages wholly past a slot's
    length cost no flops. Falls back to the reference (with the same
    ``last_attention_path`` reporting) when the head layout violates the
    TPU tile rules.

    With ``k_scales``/``v_scales`` (``[num_pages, H]`` f32) the pool is
    int8/fp8 and the kernel dequantizes inside the gather: the scale
    blocks ride the same scalar-prefetched page-table index map and fold
    into the QK^T / PV accumulations in f32 — the full-precision pool is
    never materialized.
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    quantized = k_scales is not None
    b, h, d = q.shape
    page = k_pages.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    # compiled blocks are [page, H, D]: sublane dim H, lane dim D % 128.
    # The sublane tile depends on the pool dtype — 8 for f32/bf16, 32 for
    # int8/fp8. (interpret mode has no tile constraint — CPU parity tests
    # run any shape)
    sub = 32 if quantized else 8
    tiles_ok = (pltpu is not None
                and (interpret or (h % sub == 0 and d % 128 == 0)))
    if not tiles_ok or _FORCE_XLA.get():
        _LAST_PATH.set("reference")
        return paged_attention_reference(q, k_pages, v_pages, page_table,
                                         lengths, sm_scale=scale,
                                         k_scales=k_scales,
                                         v_scales=v_scales)
    _LAST_PATH.set("pallas")
    maxp = page_table.shape[1]
    page_spec = pl.BlockSpec((1, page, h, d),
                             lambda bb, p, t, l: (t[bb, p], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h, d), lambda bb, p, t, l: (bb, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        kernel = functools.partial(_paged_kernel_quant, page_size=page,
                                   sm_scale=scale)
        scale_spec = pl.BlockSpec((1, h), lambda bb, p, t, l: (t[bb, p], 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
    else:
        kernel = functools.partial(_paged_kernel, page_size=page,
                                   sm_scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda bb, p, t, l: (bb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),   # acc
            pltpu.VMEM((h, 1), jnp.float32),   # running max
            pltpu.VMEM((h, 1), jnp.float32),   # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        # the page axis folds one slot's online-softmax state — sequential
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)


# ---------------------------------------------------------------------------
# Paged verify attention (multi-query-position decode for speculative steps)
# ---------------------------------------------------------------------------


def paged_attention_verify_reference(q, k_pages, v_pages, page_table, start,
                                     sm_scale: Optional[float] = None,
                                     k_scales=None, v_scales=None):
    """Ground-truth multi-position decode attention over a paged KV pool.

    The speculative verify step scores ``S = k + 1`` consecutive positions
    per slot in one call: slot b's query ``s`` sits at absolute position
    ``start[b] + s`` and attends causally over everything at or before it.

    - ``q``: ``[B, H, S, D]`` — S consecutive query tokens per slot;
    - ``k_pages`` / ``v_pages``: ``[num_pages, page_size, H, D]`` pool, with
      the K/V for all S positions already written (the engine's attend
      scatters them before calling);
    - ``page_table``: ``[B, max_pages]`` int32, scratch-padded like
      :func:`paged_attention_reference`;
    - ``start``: ``[B]`` int32 — tokens committed *before* this chunk; query
      ``s`` attends positions ``<= start[b] + s``, so ``S == 1`` degenerates
      to :func:`paged_attention_reference` with ``lengths = start + 1``.
    - ``k_scales`` / ``v_scales``: optional ``[num_pages, H]`` f32
      per-page-per-head dequantization scales for an int8/fp8 pool.

    Every query attends at least itself, so there is no empty-slot case.
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    b, h, s, d = q.shape
    page = k_pages.shape[1]
    maxp = page_table.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    k = _gather_dequant(k_pages, page_table, k_scales)
    v = _gather_dequant(v_pages, page_table, v_scales)
    att = jnp.einsum("bhsd,bkhd->bhsk", q.astype(jnp.float32), k,
                     preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(maxp * page, dtype=jnp.int32)
    qpos = start[:, None] + jnp.arange(s, dtype=jnp.int32)       # [B, S]
    valid = pos[None, None, :] <= qpos[:, :, None]               # [B, S, K]
    att = jnp.where(valid[:, None, :, :], att, NEG_INF)
    p = jax.nn.softmax(att, axis=-1)
    out = jnp.einsum("bhsk,bkhd->bhsd", p, v)
    return out.astype(q.dtype)


def _paged_verify_kernel(table_ref, start_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, page_size: int,
                         num_q: int, sm_scale: float):
    """Grid ``(B, max_pages)`` exactly like :func:`_paged_kernel`, but the
    online-softmax state carries ``num_q`` query rows per head and the
    validity mask is per-query causal (``tpos <= start[b] + s``)."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    np_ = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]

    @pl.when(p * page_size < start + num_q)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                  # [H, S, D]
        k = k_ref[0].astype(jnp.float32)                  # [page, H, D]
        v = v_ref[0].astype(jnp.float32)
        # att[h, s, t] = q[h, s, :] . k[t, h, :] (batch H, contract D)
        att = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (1,))),
                                  preferred_element_type=jnp.float32
                                  ) * sm_scale             # [H, S, page]
        tpos = p * page_size + jax.lax.broadcasted_iota(jnp.int32,
                                                        att.shape, 2)
        qpos = start + jax.lax.broadcasted_iota(jnp.int32, att.shape, 1)
        att = jnp.where(tpos <= qpos, att, NEG_INF)
        m_prev = m_ref[:]                                 # [H, S, 1]
        m_new = jnp.maximum(m_prev, jnp.max(att, axis=2, keepdims=True))
        pexp = jnp.exp(att - m_new)                       # [H, S, page]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(pexp, axis=2, keepdims=True)
        # acc[h, s, d] += sum_t pexp[h, s, t] * v[t, h, d]
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            pexp, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(p == np_ - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def _paged_verify_kernel_quant(table_ref, start_ref, q_ref, k_ref, v_ref,
                               ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref,
                               *, page_size: int, num_q: int, sm_scale: float):
    """:func:`_paged_verify_kernel` over an int8/fp8 pool: like
    :func:`_paged_kernel_quant`, the page's ``[H]`` scales ride the
    scalar-prefetched index map and fold into the QK^T / PV accumulations
    in f32 (broadcast over the S query rows)."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    np_ = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]

    @pl.when(p * page_size < start + num_q)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                  # [H, S, D]
        k = k_ref[0].astype(jnp.float32)                  # [page, H, D] quant
        v = v_ref[0].astype(jnp.float32)
        ks = ks_ref[0]                                    # [H] f32
        vs = vs_ref[0]
        # att[h, s, t] = (q[h, s, :] . k_q[t, h, :]) * k_scale[h] * sm_scale
        att = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (1,))),
                                  preferred_element_type=jnp.float32
                                  ) * (ks[:, None, None] * sm_scale)
        tpos = p * page_size + jax.lax.broadcasted_iota(jnp.int32,
                                                        att.shape, 2)
        qpos = start + jax.lax.broadcasted_iota(jnp.int32, att.shape, 1)
        att = jnp.where(tpos <= qpos, att, NEG_INF)
        m_prev = m_ref[:]                                 # [H, S, 1]
        m_new = jnp.maximum(m_prev, jnp.max(att, axis=2, keepdims=True))
        pexp = jnp.exp(att - m_new)                       # [H, S, page]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(pexp, axis=2, keepdims=True)
        # acc[h, s, d] += (sum_t pexp[h, s, t] * v_q[t, h, d]) * v_scale[h]
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            pexp, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * vs[:, None, None]
        m_ref[:] = m_new

    @pl.when(p == np_ - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def paged_attention_verify(q, k_pages, v_pages, page_table, start,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           k_scales=None, v_scales=None):
    """Speculative-verify attention kernel: ``S`` consecutive query positions
    per slot against the page-table-indirected K/V pool, per-query causal.
    Same operands/semantics as :func:`paged_attention_verify_reference`
    (its parity ground truth); same scalar-prefetch page-gather structure as
    :func:`paged_attention` — the grid just carries S query rows of
    online-softmax state instead of one. Pages wholly past ``start[b] + S``
    cost no flops. Falls back to the reference (reported via
    ``last_attention_path``) when the tile rules are violated.

    ``k_scales``/``v_scales`` (``[num_pages, H]`` f32) select the
    dequant-on-read kernel for an int8/fp8 pool, exactly like
    :func:`paged_attention`.
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    quantized = k_scales is not None
    b, h, s, d = q.shape
    page = k_pages.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    # compiled q/acc blocks are [H, S, D]: sublane dim S % 8, lane D % 128;
    # k/v blocks [page, H, D] need H % 8 like the single-query kernel —
    # % 32 when the pool is int8/fp8 (dtype-dependent sublane tile)
    sub = 32 if quantized else 8
    tiles_ok = (pltpu is not None
                and (interpret or (h % sub == 0 and d % 128 == 0
                                   and s % 8 == 0)))
    if not tiles_ok or _FORCE_XLA.get():
        _LAST_PATH.set("reference")
        return paged_attention_verify_reference(q, k_pages, v_pages,
                                                page_table, start,
                                                sm_scale=scale,
                                                k_scales=k_scales,
                                                v_scales=v_scales)
    _LAST_PATH.set("pallas")
    maxp = page_table.shape[1]
    page_spec = pl.BlockSpec((1, page, h, d),
                             lambda bb, p, t, st: (t[bb, p], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h, s, d), lambda bb, p, t, st: (bb, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        kernel = functools.partial(_paged_verify_kernel_quant,
                                   page_size=page, num_q=s, sm_scale=scale)
        scale_spec = pl.BlockSpec((1, h), lambda bb, p, t, st: (t[bb, p], 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
    else:
        kernel = functools.partial(_paged_verify_kernel, page_size=page,
                                   num_q=s, sm_scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, s, d),
                               lambda bb, p, t, st: (bb, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, s, d), jnp.float32),   # acc
            pltpu.VMEM((h, s, 1), jnp.float32),   # running max
            pltpu.VMEM((h, s, 1), jnp.float32),   # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), start.astype(jnp.int32),
      *operands)


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over a mesh axis)
# ---------------------------------------------------------------------------


def _block_stats(q, k, v, scale, causal, q_offset, k_offset, kv_mask=None):
    """One blockwise attention step -> (acc, m, l) in f32. [B,H,Sq,D]x[B,H,Sk,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0) + q_offset
        ki = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1) + k_offset
        s = jnp.where(qi >= ki, s, NEG_INF)
    if kv_mask is not None:  # [B, Sk] key padding mask
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                        # [B,H,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _merge_stats(acc, m, l, a2, m2, l2):
    """Fold one blockwise (acc, max, sum) triple into the running online
    -softmax state — shared by ring attention and the flash backward."""
    m_new = jnp.maximum(m, m2)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m2 - m_new)
    return acc * alpha + a2 * beta, m_new, l * alpha + l2 * beta


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None, kv_mask=None):
    """Attention where q/k/v are sequence-sharded over ``axis_name``.

    Must run inside ``shard_map`` (or pjit-of-shard_map) with q/k/v carrying
    the local sequence shard ``[B,H,S_local,D]``. K/V (and the optional
    ``kv_mask`` [B,S_local] key-padding mask) rotate around the ring;
    online-softmax stats merge per visit. Returns the local output shard.
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_offset = idx * s_local

    perm = [(i, (i + 1) % n) for i in range(n)]
    have_mask = kv_mask is not None

    @jax.checkpoint
    def fold(acc, m, l, kc, vc, mc, k_offset):
        # remat per visit: backward recomputes the [S_local, S_local] block
        # instead of saving one per visit (which would rebuild the full
        # S_local x S_global score matrix ring attention exists to avoid)
        a2, m2, l2 = _block_stats(q, kc, vc, scale, causal, q_offset, k_offset,
                                  mc if have_mask else None)
        return _merge_stats(acc, m, l, a2, m2, l2)

    def body(step, carry):
        acc, m, l, kc, vc, mc = carry
        # the k/v block currently resident came from device (idx - step) % n
        src = (idx - step) % n
        acc, m_new, l = fold(acc, m, l, kc, vc, mc, src * s_local)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if have_mask:
            mc = jax.lax.ppermute(mc, axis_name, perm)
        return acc, m_new, l, kc, vc, mc

    b, h, sl, _ = q.shape
    init = (jnp.zeros((b, h, sl, d), jnp.float32),
            jnp.full((b, h, sl, 1), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sl, 1), jnp.float32),
            k, v,
            kv_mask if have_mask else jnp.zeros((b, sl), jnp.float32))
    acc, m, l, _, _, _ = jax.lax.fori_loop(0, n, body, init)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_flash_attention(q, k, v, axis_name: str, causal: bool = False,
                         sm_scale: Optional[float] = None, kv_mask=None,
                         block_q: int = 128, block_k: int = 128,
                         interpret: Optional[bool] = None):
    """Ring attention whose per-visit block compute is the PALLAS flash
    kernel (inside shard_map operands are device-local, so the kernel needs
    no partitioning rule — same principle as
    :func:`~sparkflow_tpu.parallel.dp.make_dp_shardmap_train_step`).

    The kernel's saved logsumexp makes cross-visit merging exact: visiting
    blocks combine as ``o = sum_i o_i * exp(lse_i - lse_total)`` with
    ``lse_total = logaddexp_i lse_i``. Causality with equal sequence shards
    reduces to three whole-block cases per visit — source shard strictly
    behind (full attention), same shard (locally-causal kernel, since the
    local diagonal IS the global diagonal), or strictly ahead (zero
    contribution) — so the kernel never needs global offsets.

    Falls back to :func:`ring_attention` when shapes don't satisfy the
    kernel's tiling constraints. The backward is ALSO a pallas ring: per
    visit the dq/dk/dv kernels recompute P from the forward's merged global
    logsumexp, and the dk/dv accumulators rotate with their k/v shard (see
    :func:`_ring_flash_backward`) — the kernel win covers training, not just
    the forward.
    """
    b, h, sl, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = not on_tpu
    bq = min(block_q, sl)
    bk = min(block_k, sl)
    tiles_ok = (pltpu is not None and sl % bq == 0 and sl % bk == 0
                and bq % 8 == 0 and bk % 128 == 0 and d % 8 == 0)
    if not tiles_ok:
        return ring_attention(q, k, v, axis_name, causal=causal,
                              sm_scale=sm_scale, kv_mask=kv_mask)

    return _ring_flash(q, k, v, kv_mask, axis_name, causal, scale, bq, bk,
                       interpret)


def _ring_flash_forward(q, k, v, kv_mask, axis_name, causal, scale, bq, bk,
                        interpret, with_lse=False):
    b, h, sl, d = q.shape
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    have_mask = kv_mask is not None

    def visit(kc, vc, mc, local_causal):
        out, lse = _flash_pallas_forward(
            q, kc, vc, mc if have_mask else None, local_causal, scale,
            bq, bk, interpret, with_lse=True)
        return out.astype(jnp.float32), lse

    def body(step, carry):
        o, lse, kc, vc, mc = carry
        src = (idx - step) % n
        if causal:
            # three whole-block cases per visit (equal shards make the local
            # diagonal the global one): strictly-behind source -> full
            # attention; same shard -> locally-causal kernel; strictly-ahead
            # -> SKIPPED entirely (no kernel launch, zero contribution)
            branch = jnp.where(src == idx, 1, jnp.where(src > idx, 2, 0))
            o2, lse2 = jax.lax.switch(branch, [
                lambda: visit(kc, vc, mc, False),
                lambda: visit(kc, vc, mc, True),
                lambda: (jnp.zeros((b, h, sl, d), jnp.float32),
                         jnp.full((b, h, sl), NEG_INF, jnp.float32)),
            ])
        else:
            o2, lse2 = visit(kc, vc, mc, False)
        # exact merge via logsumexp weights
        lse_new = jnp.logaddexp(lse, lse2)                    # [B,H,S]
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o2 * jnp.exp(lse2 - lse_new)[..., None])
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if have_mask:
            mc = jax.lax.ppermute(mc, axis_name, perm)
        return o, lse_new, kc, vc, mc

    init = (jnp.zeros((b, h, sl, d), jnp.float32),
            jnp.full((b, h, sl), NEG_INF, jnp.float32),
            k, v,
            kv_mask if have_mask else jnp.zeros((b, sl), jnp.float32))
    o, lse, _, _, _ = jax.lax.fori_loop(0, n, body, init)
    if with_lse:
        return o.astype(q.dtype), lse
    return o.astype(q.dtype)


def _ring_flash_backward(q, k, v, kv_mask, out, lse, g, axis_name, causal,
                         scale, bq, bk, interpret):
    """Ring backward running the PALLAS dq/dk/dv kernels per visit.

    The forward's merged ``lse`` is the GLOBAL logsumexp for every local q row,
    so per-visit kernel calls with it recompute globally-normalized P blocks
    directly — each visit's dq/dk/dv contribution is exact, and contributions
    just sum. dk/dv accumulators ROTATE WITH their k/v shard: after n
    ppermutes they arrive home having collected every device's contribution.
    Same three-case causal structure as the forward (strictly-ahead sources
    contribute zero and skip the kernels entirely)."""
    b, h, sl, d = q.shape
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    have_mask = kv_mask is not None

    # q-side quantities (flat views + D = rowsum(dO*O)) never change across
    # visits — computed ONCE outside the ring loop
    qf, gf, lsef, delta = _flash_bwd_prep(q, out, lse, g)

    def visit(kc, vc, mc, local_causal):
        dq2, dk2, dv2 = _flash_pallas_backward_flat(
            qf, kc.reshape(b * h, sl, d), vc.reshape(b * h, sl, d), gf, lsef,
            delta, mc.astype(jnp.float32)[:, None, :] if have_mask else None,
            h, local_causal, scale, bq, bk, interpret)
        return (dq2.reshape(b, h, sl, d).astype(jnp.float32),
                dk2.reshape(b, h, sl, d).astype(jnp.float32),
                dv2.reshape(b, h, sl, d).astype(jnp.float32))

    def body(step, carry):
        dq, kc, vc, mc, dk, dv = carry
        src = (idx - step) % n
        if causal:
            branch = jnp.where(src == idx, 1, jnp.where(src > idx, 2, 0))
            dq2, dk2, dv2 = jax.lax.switch(branch, [
                lambda: visit(kc, vc, mc, False),
                lambda: visit(kc, vc, mc, True),
                lambda: (jnp.zeros((b, h, sl, d), jnp.float32),) * 3,
            ])
        else:
            dq2, dk2, dv2 = visit(kc, vc, mc, False)
        dq = dq + dq2
        dk = dk + dk2
        dv = dv + dv2
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dk = jax.lax.ppermute(dk, axis_name, perm)
        dv = jax.lax.ppermute(dv, axis_name, perm)
        if have_mask:
            mc = jax.lax.ppermute(mc, axis_name, perm)
        return dq, kc, vc, mc, dk, dv

    zeros = jnp.zeros((b, h, sl, d), jnp.float32)
    init = (zeros, k, v,
            kv_mask if have_mask else jnp.zeros((b, sl), jnp.float32),
            zeros, zeros)
    dq, _, _, _, dk, dv = jax.lax.fori_loop(0, n, body, init)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, kv_mask, axis_name, causal, scale, bq, bk, interpret):
    return _ring_flash_forward(q, k, v, kv_mask, axis_name, causal, scale,
                               bq, bk, interpret)


def _ring_flash_fwd(q, k, v, kv_mask, axis_name, causal, scale, bq, bk,
                    interpret):
    out, lse = _ring_flash_forward(q, k, v, kv_mask, axis_name, causal, scale,
                                   bq, bk, interpret, with_lse=True)
    return out, (q, k, v, kv_mask, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, bq, bk, interpret, res, g):
    # pallas dq/dk/dv kernels per ring visit (see _ring_flash_backward) — the
    # kernel win now covers the training path, not just the forward; memory
    # stays O(S/n) per device (lse + out residuals, per-visit recompute of P)
    q, k, v, kv_mask, out, lse = res
    dq, dk, dv = _ring_flash_backward(q, k, v, kv_mask, out, lse, g,
                                      axis_name, causal, scale, bq, bk,
                                      interpret)
    return dq, dk, dv, None  # mask carries no gradient


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)
