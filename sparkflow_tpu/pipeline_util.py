"""Pipeline persistence: ``PysparkReaderWriter`` / ``PysparkPipelineWrapper``.

Reference contract (``sparkflow/pipeline_util.py``): custom Python stages must
survive Spark's native ``Pipeline.save`` / ``PipelineModel.load``. The reference
smuggles a dill-pickled, zlib-compressed Python object through a Java
``StopWordsRemover``'s stopwords list, marked with a GUID, and ``unwrap`` swaps
the real stage back in after load (``pipeline_util.py:109-127, 56-74``).

Here the same two public names exist with the same call shapes:

- with **pyspark** present, the carrier trick is reproduced (it is
  model-framework-agnostic: any Params-only Python stage round-trips);
- with **localml**, stages are dill-serialized directly by the localml
  writer — no carrier needed — and ``unwrap`` is a structural no-op that still
  recurses for API compatibility.
"""

from __future__ import annotations

import zlib
from typing import Any, List

import dill

from .compat import USING_PYSPARK

# GUID marking carrier stages (ours, not the reference's — saves are not
# wire-compatible across frameworks, only API-compatible).
GUID = "7a3f9c2e51b44de2a0c8sparkflowtpu".replace("sparkflowtpu", "9d17e3b4")


def _to_bytes_string(obj: Any) -> str:
    raw = zlib.compress(dill.dumps(obj))
    return ",".join(str(b) for b in raw)


def _from_bytes_string(s: str) -> Any:
    raw = bytes(int(tok) for tok in s.split(","))
    return dill.loads(zlib.decompress(raw))


if USING_PYSPARK:  # covered by the pyspark CI job (make test-pyspark)

    from pyspark.ml.feature import StopWordsRemover
    from pyspark.ml.pipeline import Pipeline, PipelineModel
    from pyspark.ml.util import JavaMLReader, JavaMLWriter

    class PysparkObjId:
        """Carrier constants (reference ``pipeline_util.py:16-31``)."""

        _getCarrierClass = staticmethod(lambda: StopWordsRemover)
        GUID = GUID

    def _unwrap_carrier(words: List[str], what: str = "stage") -> Any:
        """Single decode path for every carrier consumer (reader, _from_java,
        pipeline unwrap): validate the GUID sentinel, then dill-load."""
        words = list(words)
        if len(words) < 2 or words[-1] != GUID:
            raise ValueError(f"{what} is not a sparkflow-tpu carrier")
        return _from_bytes_string(words[0])

    class _CarrierReader:
        """Loads a saved carrier StopWordsRemover and unwraps the Python
        stage (reference ``pipeline_util.py:89-98``: the reader is for the
        CARRIER class — a Python-only class has no Java loader)."""

        def load(self, path: str):
            carrier = JavaMLReader(StopWordsRemover).load(path)
            return _unwrap_carrier(carrier.getStopWords(), what=path)

    class PysparkReaderWriter:
        """Mixin giving a Python stage Spark-native save/load via the
        StopWordsRemover carrier (reference ``pipeline_util.py:77-127``)."""

        def write(self):
            return JavaMLWriter(self)

        def save(self, path: str):
            self.write().save(path)

        @classmethod
        def read(cls):
            return _CarrierReader()

        @classmethod
        def load(cls, path: str):
            return cls.read().load(path)

        def _to_java(self):
            payload = _to_bytes_string(self)
            carrier = StopWordsRemover()
            carrier._resetUid(self.uid)  # keep stage identity in metadata
            carrier.setStopWords([payload, GUID])
            return carrier._to_java()

        @classmethod
        def _from_java(cls, java_stage):
            return _unwrap_carrier(java_stage.getStopWords())

    class PysparkPipelineWrapper:
        """Recursively swap carrier stages back into real Python objects after
        ``PipelineModel.load`` (reference ``pipeline_util.py:56-74``)."""

        @staticmethod
        def unwrap(pipeline):
            if isinstance(pipeline, (Pipeline, PipelineModel)):
                stages = (pipeline.getStages() if isinstance(pipeline, Pipeline)
                          else pipeline.stages)
                for i, stage in enumerate(stages):
                    if isinstance(stage, (Pipeline, PipelineModel)):
                        stages[i] = PysparkPipelineWrapper.unwrap(stage)
                    elif (isinstance(stage, StopWordsRemover)
                          and stage.getStopWords()
                          and stage.getStopWords()[-1] == GUID):
                        stages[i] = _unwrap_carrier(stage.getStopWords())
                if isinstance(pipeline, Pipeline):
                    pipeline.setStages(stages)
                else:
                    pipeline.stages = stages
            return pipeline

else:

    from .localml.pipeline import Pipeline, PipelineModel

    class PysparkObjId:
        GUID = GUID

    class PysparkReaderWriter:
        """With localml the base writer already dill-serializes the full stage
        (``sparkflow_tpu/localml/base.py``); nothing extra to mix in."""

    class PysparkPipelineWrapper:
        @staticmethod
        def unwrap(pipeline):
            # localml loads real Python objects directly; recurse only to keep
            # the call shape of the reference API.
            if isinstance(pipeline, (Pipeline, PipelineModel)):
                stages = (pipeline.getStages() if isinstance(pipeline, Pipeline)
                          else pipeline.stages)
                for i, stage in enumerate(stages):
                    if isinstance(stage, (Pipeline, PipelineModel)):
                        stages[i] = PysparkPipelineWrapper.unwrap(stage)
            return pipeline
