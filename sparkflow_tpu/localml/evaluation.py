"""Evaluators: the subset the reference examples use
(``MulticlassClassificationEvaluator`` with accuracy,
``examples/simple_dnn.py:71-74``)."""

from __future__ import annotations

import numpy as np

from .param import Param, Params, TypeConverters, keyword_only, HasLabelCol, HasPredictionCol


class MulticlassClassificationEvaluator(HasLabelCol, HasPredictionCol):
    metricName = Param(Params._dummy(), "metricName", "metric name",
                       typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol="label", predictionCol="prediction",
                 metricName="f1"):
        super().__init__()
        self._setDefault(labelCol="label", predictionCol="prediction", metricName="f1")
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def evaluate(self, dataset) -> float:
        label_col = self.getOrDefault(self.labelCol)
        pred_col = self.getOrDefault(self.predictionCol)
        metric = self.getOrDefault(self.metricName)
        y = np.array([float(r[label_col]) for r in dataset.collect()])
        p = np.array([float(r[pred_col]) for r in dataset.collect()])
        if metric == "accuracy":
            return float((y == p).mean()) if len(y) else 0.0
        if metric == "f1":  # weighted f1
            classes = np.unique(np.concatenate([y, p]))
            f1s, weights = [], []
            for c in classes:
                tp = float(((p == c) & (y == c)).sum())
                fp = float(((p == c) & (y != c)).sum())
                fn = float(((p != c) & (y == c)).sum())
                prec = tp / (tp + fp) if tp + fp else 0.0
                rec = tp / (tp + fn) if tp + fn else 0.0
                f1s.append(2 * prec * rec / (prec + rec) if prec + rec else 0.0)
                weights.append(float((y == c).sum()))
            return float(np.average(f1s, weights=weights)) if weights else 0.0
        raise ValueError(f"unsupported metric {metric!r}")
