"""Serving lifecycle: a small thread-safe state machine with in-flight
request accounting.

States flow one way — ``STARTING -> SERVING -> DRAINING -> STOPPED`` (any
state may jump straight to ``STOPPED``). ``DRAINING`` is the graceful-drain
window: in-flight requests run to completion while new ones are refused
(the HTTP front maps the refusal to ``503`` + ``Retry-After``, so a load
balancer retries against another replica instead of surfacing an error).
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Optional

__all__ = ["ServerState", "Lifecycle"]


class ServerState(enum.Enum):
    STARTING = "starting"
    SERVING = "serving"
    DRAINING = "draining"
    STOPPED = "stopped"


_ALLOWED = {
    ServerState.STARTING: {ServerState.SERVING, ServerState.STOPPED},
    ServerState.SERVING: {ServerState.DRAINING, ServerState.STOPPED},
    ServerState.DRAINING: {ServerState.STOPPED},
    ServerState.STOPPED: set(),
}


class Lifecycle:
    """State + in-flight counter, safe to poke from handler threads, the
    drain thread, and signal handlers alike."""

    def __init__(self):
        self._cond = threading.Condition()
        self._state = ServerState.STARTING
        self._inflight = 0

    @property
    def state(self) -> ServerState:
        with self._cond:
            return self._state

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def transition(self, new: ServerState) -> bool:
        """Move to ``new`` if the edge is legal; returns whether the state
        changed (repeat/illegal transitions are refused, not raised — a
        second SIGTERM during a drain must be harmless)."""
        with self._cond:
            if new is self._state or new not in _ALLOWED[self._state]:
                return False
            self._state = new
            self._cond.notify_all()
            return True

    def try_begin_request(self) -> bool:
        """Admit one request iff SERVING (counted until
        :meth:`end_request`)."""
        with self._cond:
            if self._state is not ServerState.SERVING:
                return False
            self._inflight += 1
            return True

    def end_request(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._cond.notify_all()

    def wait_idle(self, timeout: Optional[float] = 10.0) -> bool:
        """Block until no requests are in flight (the drain barrier).
        Returns False if ``timeout`` expired first."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._inflight > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True
