"""Row / RDD / DataFrame / session: the ``pyspark.sql`` subset sparkflow touches.

The reference drives training through ``df.rdd.map``, ``coalesce``,
``foreachPartition`` and inference through ``rdd.mapPartitions(...).toDF()``
(``sparkflow/tensorflow_async.py:90-99,290-291``; ``HogwildSparkModel.py:259``).
This local engine keeps those exact call shapes over in-process lists, with
logical partitions standing in for Spark executors — the multi-device mesh is
the real parallelism substrate underneath.
"""

from __future__ import annotations

import csv as _csv
import json as _json
import os as _os
import random as _random
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np


class Row:
    """Named-field record, pyspark-Row-compatible (attr + item access, asDict)."""

    __slots__ = ("__fields__", "__values__")

    def __init__(self, **kwargs):
        object.__setattr__(self, "__fields__", list(kwargs.keys()))
        object.__setattr__(self, "__values__", list(kwargs.values()))

    def asDict(self) -> Dict[str, Any]:
        return dict(zip(self.__fields__, self.__values__))

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.__values__[key]
        try:
            return self.__values__[self.__fields__.index(key)]
        except ValueError:
            raise KeyError(key)

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            return self.__values__[self.__fields__.index(name)]
        except ValueError:
            raise AttributeError(name)

    def __contains__(self, key):
        return key in self.__fields__

    def __len__(self):
        return len(self.__values__)

    def __iter__(self):
        return iter(self.__values__)

    def __eq__(self, other):
        return isinstance(other, Row) and self.asDict() == other.asDict()

    def __repr__(self):
        kv = ", ".join(f"{f}={v!r}" for f, v in zip(self.__fields__, self.__values__))
        return f"Row({kv})"


def _slice(items: List[Any], n: int) -> List[List[Any]]:
    n = max(1, min(n, len(items)) if items else 1)
    base, extra = divmod(len(items), n)
    out, i = [], 0
    for k in range(n):
        size = base + (1 if k < extra else 0)
        out.append(items[i:i + size])
        i += size
    return out


class RDD:
    """A list with logical partitions; mirrors the RDD methods sparkflow uses."""

    def __init__(self, items: List[Any], num_partitions: int = 1):
        self.items = list(items)
        self.num_partitions = max(1, num_partitions)

    # -- transforms ---------------------------------------------------------

    def map(self, f: Callable) -> "RDD":
        return RDD([f(x) for x in self.items], self.num_partitions)

    def mapPartitions(self, f: Callable) -> "RDD":
        out: List[Any] = []
        for part in _slice(self.items, self.num_partitions):
            out.extend(f(iter(part)))
        return RDD(out, self.num_partitions)

    def foreachPartition(self, f: Callable) -> None:
        for part in _slice(self.items, self.num_partitions):
            f(iter(part))

    def coalesce(self, n: int) -> "RDD":
        return RDD(self.items, min(self.num_partitions, max(1, n)))

    def persist(self, *_a) -> "RDD":
        return self  # local lists are always materialized

    def unpersist(self, *_a) -> "RDD":
        return self

    def repartition(self, n: int) -> "RDD":
        items = list(self.items)
        _random.Random(17).shuffle(items)
        return RDD(items, max(1, n))

    # -- actions ------------------------------------------------------------

    def collect(self) -> List[Any]:
        return list(self.items)

    def toLocalIterator(self) -> Iterator[Any]:
        """Partition-by-partition generator (pyspark's streaming action: the
        driver holds one partition at a time, never the whole dataset)."""
        for part in _slice(self.items, self.num_partitions):
            for x in part:
                yield x

    def count(self) -> int:
        return len(self.items)

    def getNumPartitions(self) -> int:
        return self.num_partitions

    def toDF(self, schema: Optional[Sequence[str]] = None) -> "DataFrame":
        if not self.items:
            return DataFrame([], list(schema) if schema else [])
        rows = [x if isinstance(x, Row) else Row(**x) if isinstance(x, dict)
                else Row(**{c: v for c, v in zip(schema, x)}) for x in self.items]
        return DataFrame(rows, rows[0].__fields__, self.num_partitions)


class _RandOrder:
    """Sentinel returned by functions.rand() for orderBy-shuffles."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = seed


class functions:
    @staticmethod
    def rand(seed: Optional[int] = None) -> _RandOrder:
        return _RandOrder(seed)


class DataFrame:
    """Immutable list-of-Rows table with logical partitions."""

    def __init__(self, rows: List[Row], columns: List[str], num_partitions: int = 4):
        self._rows = rows
        self.columns = list(columns)
        self.num_partitions = max(1, num_partitions)

    @property
    def rdd(self) -> RDD:
        return RDD(self._rows, self.num_partitions)

    def select(self, *cols) -> "DataFrame":
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        rows = [Row(**{c: r[c] for c in cols}) for r in self._rows]
        return DataFrame(rows, list(cols), self.num_partitions)

    def withColumn(self, name: str, values: Sequence[Any]) -> "DataFrame":
        """localml extension: attach a computed column (no Column expressions)."""
        rows = [Row(**{**r.asDict(), name: v}) for r, v in zip(self._rows, values)]
        cols = self.columns + ([name] if name not in self.columns else [])
        return DataFrame(rows, cols, self.num_partitions)

    def orderBy(self, *exprs) -> "DataFrame":
        rows = list(self._rows)
        if exprs and isinstance(exprs[0], _RandOrder):
            _random.Random(exprs[0].seed).shuffle(rows)
        elif exprs:
            rows.sort(key=lambda r: tuple(r[c] for c in exprs))
        return DataFrame(rows, self.columns, self.num_partitions)

    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(self._rows, self.columns, max(1, n))

    def coalesce(self, n: int) -> "DataFrame":
        return DataFrame(self._rows, self.columns,
                         min(self.num_partitions, max(1, n)))

    def collect(self) -> List[Row]:
        return list(self._rows)

    def take(self, n: int) -> List[Row]:
        return self._rows[:n]

    def first(self) -> Optional[Row]:
        return self._rows[0] if self._rows else None

    def count(self) -> int:
        return len(self._rows)

    def show(self, n: int = 20) -> None:
        print(" | ".join(self.columns))
        for r in self._rows[:n]:
            print(" | ".join(str(r[c]) for c in self.columns))

    def filter(self, pred) -> "DataFrame":
        """localml: ``pred`` is a callable Row -> bool (no Column exprs)."""
        if not callable(pred):
            raise TypeError("localml filter() takes a callable Row -> bool")
        rows = [r for r in self._rows if pred(r)]
        return DataFrame(rows, self.columns, self.num_partitions)

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._rows[:n], self.columns, self.num_partitions)

    def union(self, other: "DataFrame") -> "DataFrame":
        if list(other.columns) != list(self.columns):
            raise ValueError(f"union: column mismatch {self.columns} vs "
                             f"{other.columns}")
        return DataFrame(self._rows + other.collect(), self.columns,
                         self.num_partitions)

    def sample(self, withReplacement=None, fraction=None, seed=None
               ) -> "DataFrame":
        # pyspark also allows sample(fraction) / sample(fraction, seed)
        if isinstance(withReplacement, float):
            withReplacement, fraction, seed = False, withReplacement, fraction
        rng = _random.Random(seed)
        if withReplacement:
            k = int(round(len(self._rows) * float(fraction)))
            rows = [rng.choice(self._rows) for _ in range(k)] if self._rows else []
        else:
            rows = [r for r in self._rows if rng.random() < float(fraction)]
        return DataFrame(rows, self.columns, self.num_partitions)

    def randomSplit(self, weights, seed=None) -> List["DataFrame"]:
        total = float(sum(weights))
        rng = _random.Random(seed)
        rows = list(self._rows)
        rng.shuffle(rows)
        out, start = [], 0
        bounds = []
        acc = 0.0
        for w in weights[:-1]:
            acc += w / total
            bounds.append(int(round(acc * len(rows))))
        bounds.append(len(rows))
        for b in bounds:
            out.append(DataFrame(rows[start:b], self.columns,
                                 self.num_partitions))
            start = b
        return out

    def dropna(self, how: str = "any", thresh=None, subset=None
               ) -> "DataFrame":
        """pyspark signature: how='any'|'all', thresh = min non-null count
        (overrides how), subset = columns to consider."""
        if how not in ("any", "all"):
            raise ValueError(f"how must be 'any' or 'all', got {how!r}")
        if isinstance(subset, str):
            subset = [subset]
        cols = subset or self.columns

        def is_null(v):
            return v is None or (isinstance(v, float) and v != v)

        def ok(r):
            non_null = sum(0 if is_null(r[c]) else 1 for c in cols)
            if thresh is not None:
                return non_null >= thresh
            return non_null == len(cols) if how == "any" else non_null > 0

        return DataFrame([r for r in self._rows if ok(r)], self.columns,
                         self.num_partitions)

    def fillna(self, value, subset=None) -> "DataFrame":
        if isinstance(subset, str):
            subset = [subset]
        cols = subset or self.columns
        # pyspark only fills SCALAR columns whose type matches the value:
        # numbers fill numeric columns, strings fill string columns; vector
        # or other object columns are never touched
        want_str = isinstance(value, str)

        def col_matches(c):
            for r in self._rows:
                v = r[c]
                if v is None or (isinstance(v, float) and v != v):
                    continue
                if want_str:
                    return isinstance(v, str)
                return isinstance(v, (int, float, bool)) \
                    and not isinstance(v, str)
            return True  # all-null column: fill it

        cols = [c for c in cols if col_matches(c)]

        def fix(r):
            d = r.asDict()
            for c in cols:
                v = d.get(c)
                if v is None or (isinstance(v, float) and v != v):
                    d[c] = value
            return Row(**d)

        return DataFrame([fix(r) for r in self._rows], self.columns,
                         self.num_partitions)

    def cache(self) -> "DataFrame":
        return self  # everything is already in memory

    def persist(self, *_a) -> "DataFrame":
        return self

    def unpersist(self, *_a) -> "DataFrame":
        return self

    def toPandas(self):
        import pandas as pd
        return pd.DataFrame([r.asDict() for r in self._rows],
                            columns=self.columns)

    @property
    def write(self) -> "_Writer":
        return _Writer(self)

    def __repr__(self):
        return f"DataFrame[{', '.join(self.columns)}] ({len(self._rows)} rows)"


def _vector_to_plain(v):
    """DenseVector/SparseVector -> list[float] for columnar formats (the
    JVM VectorUDT has no pyarrow analog; densified on purpose)."""
    if hasattr(v, "toArray"):
        return [float(x) for x in v.toArray()]
    return v


def _plain_to_vector(v):
    """list-of-numbers -> DenseVector on read (the inverse convention)."""
    if (isinstance(v, list) and v
            and all(isinstance(x, (int, float)) for x in v)):
        from .linalg import Vectors
        return Vectors.dense([float(x) for x in v])
    return v


class _Writer:
    """``df.write.mode("overwrite").parquet(path)`` / ``.json(path)`` /
    ``.csv(path)`` — single-file writers for the standalone engine."""

    def __init__(self, df: "DataFrame"):
        self._df = df
        self._mode = "error"

    def mode(self, m: str) -> "_Writer":
        if m not in ("error", "errorifexists", "overwrite", "ignore"):
            raise ValueError(f"unsupported write mode {m!r} (supported: "
                             f"error, overwrite, ignore)")
        self._mode = m
        return self

    def _should_write(self, path: str) -> bool:
        if _os.path.exists(path):
            if self._mode == "overwrite":
                return True
            if self._mode == "ignore":
                return False
            raise IOError(f"path {path} already exists "
                          f"(mode={self._mode!r})")
        return True

    def parquet(self, path: str) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq
        if not self._should_write(path):
            return
        rows = self._df.collect()
        cols = {c: [_vector_to_plain(r[c]) for r in rows]
                for c in self._df.columns}
        pq.write_table(pa.table(cols), path)

    def json(self, path: str) -> None:
        if not self._should_write(path):
            return
        with open(path, "w") as f:
            for r in self._df.collect():
                d = {c: _vector_to_plain(r[c]) for c in self._df.columns}
                f.write(_json.dumps(d) + "\n")

    def csv(self, path: str) -> None:
        if not self._should_write(path):
            return
        with open(path, "w", newline="") as f:
            w = _csv.writer(f)
            w.writerow(self._df.columns)
            for r in self._df.collect():
                w.writerow([_vector_to_plain(r[c])
                            for c in self._df.columns])


class _CsvReader:
    def __init__(self, session):
        self._session = session
        self._options: Dict[str, Any] = {}

    def option(self, key: str, value) -> "_CsvReader":
        self._options[str(key).lower()] = value
        return self

    def csv(self, path: str) -> DataFrame:
        infer = str(self._options.get("inferschema", "false")).lower() == "true"
        header = str(self._options.get("header", "false")).lower() == "true"
        rows: List[Row] = []
        with open(path, newline="") as f:
            reader = _csv.reader(f)
            cols: Optional[List[str]] = None
            for rec in reader:
                if cols is None:
                    cols = rec if header else [f"_c{i}" for i in range(len(rec))]
                    if header:
                        continue
                vals = [_parse(v) if infer else v for v in rec]
                rows.append(Row(**dict(zip(cols, vals))))
        return DataFrame(rows, cols or [], self._session._default_parallelism)

    def parquet(self, path: str) -> DataFrame:
        import pyarrow.parquet as pq
        table = pq.read_table(path)
        cols = table.column_names
        data = {c: table.column(c).to_pylist() for c in cols}
        n = table.num_rows
        rows = [Row(**{c: _plain_to_vector(data[c][i]) for c in cols})
                for i in range(n)]
        return DataFrame(rows, cols, self._session._default_parallelism)

    def json(self, path: str) -> DataFrame:
        """JSON Lines (one object per line), like spark.read.json. Missing
        keys on a line become None (pyspark fills null)."""
        dicts, cols = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = {k: _plain_to_vector(v)
                     for k, v in _json.loads(line).items()}
                for k in d:
                    if k not in cols:
                        cols.append(k)
                dicts.append(d)
        rows = [Row(**{c: d.get(c) for c in cols}) for d in dicts]
        return DataFrame(rows, cols, self._session._default_parallelism)


def _parse(s: str):
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


class _SessionBuilder:
    def __init__(self):
        self._conf: Dict[str, Any] = {}
        self._master = "local[1]"

    def appName(self, name: str) -> "_SessionBuilder":
        self._conf["app.name"] = name
        return self

    def master(self, m: str) -> "_SessionBuilder":
        self._master = m
        return self

    def config(self, key: str, value) -> "_SessionBuilder":
        self._conf[key] = value
        return self

    def getOrCreate(self) -> "LocalSession":
        par = 1
        if self._master.startswith("local["):
            spec = self._master[6:-1]
            par = 4 if spec == "*" else int(spec)
        return LocalSession(self._conf, par)


class LocalSession:
    """Stands in for SparkSession: createDataFrame + read.csv."""

    builder = None  # set below (class property pattern like SparkSession.builder)

    def __init__(self, conf: Optional[Dict[str, Any]] = None, parallelism: int = 4):
        self.conf = conf or {}
        self._default_parallelism = parallelism

    @property
    def read(self) -> _CsvReader:
        return _CsvReader(self)

    def createDataFrame(self, data, schema: Optional[Sequence[str]] = None) -> DataFrame:
        rows: List[Row] = []
        for item in data:
            if isinstance(item, Row):
                rows.append(item)
            elif isinstance(item, dict):
                rows.append(Row(**item))
            else:  # tuple/list + schema
                if schema is None:
                    raise ValueError("schema required for tuple data")
                rows.append(Row(**dict(zip(schema, item))))
        cols = list(schema) if schema else (rows[0].__fields__ if rows else [])
        return DataFrame(rows, cols, self._default_parallelism)

    def stop(self):
        pass


class _BuilderAccessor:
    def __get__(self, obj, objtype=None):
        return _SessionBuilder()


LocalSession.builder = _BuilderAccessor()
