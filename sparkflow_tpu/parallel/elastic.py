"""Bounded-staleness elastic async data parallelism — the Hogwild heritage,
modernized.

The reference's identity is asynchronous parameter-server training
(``sparkflow/HogwildSparkModel.py``: every Spark partition pushes gradients
to a Flask server whenever it finishes a mini-batch, lock-free). The sync
paths in this repo (``core``, ``parallel/dp.py``) replaced that with
all-reduce — faster per step, but one slow or preempted replica stalls
EVERY step. This module restores the async shape with modern bounds, per
DeepSpark (arXiv:1602.08191) and SSP-style staleness control:

- :class:`ElasticParamStore` — a versioned in-process parameter store. Each
  accepted gradient push bumps a monotonic weight version. A push carries
  the version its gradient was computed against (its *basis*); the gap to
  the current version is its **staleness**. Pushes within ``max_staleness``
  are accepted with a **dampening** scale (default ``1/(1+staleness)``);
  beyond the bound they are rejected and the replica must refresh — a
  straggler therefore *delays its own contribution*, never the fleet.
- **Elastic membership** — replicas join/leave via heartbeat + lease
  (the ``Lifecycle`` idea from ``resilience``, applied per replica): every
  pull/push renews the lease; a replica that goes quiet past
  ``lease_ttl_s`` is evicted and must re-join before its pushes count.
  The effective dp width shrinks and grows without restarting training.
- **Dense vs sparse aggregation split** (Parallax, arXiv:1808.02621) —
  gradients route per-parameter by *density*: dense tensors travel whole
  (on a device mesh they would ride the all-reduce path in
  ``parallel/dp.py``); embedding-class tensors whose gradient touches only
  a few rows travel as :class:`SparseRows` (row indices + values) through
  the versioned store, the PS-style sparse exchange.
- **Deterministic chaos** — workers reach the store through an injectable
  transport; ``resilience.faults`` points ``"elastic.push"`` /
  ``"elastic.pull"`` inject delays and drops, and the virtual-time engine
  (:meth:`ElasticDPEngine.run_virtual`) replays stragglers and mid-step
  preemptions on a simulated clock, so the chaos suite asserts with no
  sleeps (``tests/test_elastic.py``, ``make elastic-smoke``).

Observability: ``elastic/staleness`` histogram, ``elastic/replicas`` gauge,
``elastic/push_{accepted,rejected}`` / ``elastic/evicted`` counters,
``elastic/sparse_bytes_saved``, and a span per push — all through the
standard registry, so ``prometheus_text`` exports them for free.

Entry points: ``Trainer(strategy="elastic_dp", elastic={...})`` and
``HogwildTrainer`` (which now trains through this engine — the reference's
constructor, the reference's async semantics, bounded).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..analysis import racecheck
from ..resilience import faults

logger = logging.getLogger("sparkflow_tpu")

__all__ = [
    "SparseRows", "encode_grads", "decode_grads",
    "PushResult", "ReplicaView", "ElasticParamStore", "InProcessTransport",
    "ReplicaSpec", "ElasticResult", "ElasticDPEngine",
    "sync_baseline_examples_per_sec",
]


# ---------------------------------------------------------------------------
# dense/sparse gradient codec (the Parallax split)
# ---------------------------------------------------------------------------

class SparseRows:
    """Row-sparse gradient wire format: ``values[i]`` is the gradient of row
    ``indices[i]`` of a ``shape``-shaped dense tensor; untouched rows are
    zero. Deliberately NOT a pytree node — it must stay a leaf so encoded
    gradient trees keep the dense tree's structure."""

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices: np.ndarray, values: np.ndarray,
                 shape: Tuple[int, ...]):
        self.indices = np.asarray(indices, dtype=np.int32)
        self.values = np.asarray(values)
        self.shape = tuple(shape)

    @property
    def nbytes(self) -> int:
        return self.indices.nbytes + self.values.nbytes

    def densify(self) -> np.ndarray:
        out = np.zeros(self.shape, dtype=self.values.dtype)
        if self.indices.size:
            out[self.indices] = self.values
        return out

    def __repr__(self):  # pragma: no cover - debugging nicety
        return (f"SparseRows({self.indices.size}/{self.shape[0]} rows, "
                f"shape={self.shape})")


def _is_sparse(leaf) -> bool:
    return isinstance(leaf, SparseRows)


def encode_grads(grads, density_threshold: Optional[float] = 0.25):
    """Split a gradient pytree by row density: leaves of rank >= 2 whose
    nonzero-row fraction is <= ``density_threshold`` become
    :class:`SparseRows` (embedding-class params — a sparse batch touches
    few vocabulary rows); everything else stays dense, the all-reduce
    class. Returns ``(encoded_tree, dense_bytes, wire_bytes)`` so callers
    can account the traffic the split saved. ``density_threshold=None``
    disables the split (everything dense)."""
    dense_bytes = 0
    wire_bytes = 0

    def leaf(g):
        nonlocal dense_bytes, wire_bytes
        a = np.asarray(g)
        dense_bytes += a.nbytes
        if (density_threshold is None or a.ndim < 2 or a.shape[0] == 0):
            wire_bytes += a.nbytes
            return a
        touched = np.flatnonzero(
            np.any(a.reshape(a.shape[0], -1) != 0, axis=1))
        density = touched.size / a.shape[0]
        if density > density_threshold:
            wire_bytes += a.nbytes
            return a
        sp = SparseRows(touched, a[touched], a.shape)
        wire_bytes += sp.nbytes
        return sp

    return jax.tree.map(leaf, grads), dense_bytes, wire_bytes


def decode_grads(encoded):
    """Inverse of :func:`encode_grads`: densify every SparseRows leaf."""
    return jax.tree.map(
        lambda l: l.densify() if _is_sparse(l) else l,
        encoded, is_leaf=_is_sparse)


# ---------------------------------------------------------------------------
# the versioned parameter store
# ---------------------------------------------------------------------------

@dataclass
class PushResult:
    """Outcome of one gradient push. On acceptance the store piggybacks the
    post-update weights (``params`` at ``version``) so the replica starts
    its next step fresh without a second round-trip; on rejection it
    piggybacks the CURRENT weights — the forced refresh."""
    accepted: bool
    staleness: int
    version: int
    params: Any
    scale: float = 1.0
    reason: str = ""


@dataclass
class ReplicaView:
    """Membership snapshot for one replica (read-only copy)."""
    replica_id: str
    joined_at: float
    last_heartbeat: float
    pushes: int = 0
    rejected: int = 0
    last_staleness: int = 0


class _Lease:
    __slots__ = ("joined_at", "last_beat", "pushes", "rejected",
                 "last_staleness")

    def __init__(self, now: float):
        self.joined_at = now
        self.last_beat = now
        self.pushes = 0
        self.rejected = 0
        self.last_staleness = 0


def _resolve_dampening(dampening) -> Callable[[int], float]:
    if dampening is None or dampening == "none":
        return lambda s: 1.0
    if dampening == "inverse":
        return lambda s: 1.0 / (1.0 + s)
    if callable(dampening):
        return dampening
    raise ValueError(
        f"dampening must be 'inverse', 'none'/None, or a callable "
        f"staleness -> scale; got {dampening!r}")


class ElasticParamStore:
    """Versioned in-process parameter store with bounded-staleness updates
    and lease-based elastic membership.

    The asynchronous replacement for the all-reduce: replicas pull
    ``(version, params)``, compute a gradient, and push it back tagged with
    that basis version. The store serializes updates under one lock (the
    reference's ``acquireLock=True`` path — SURVEY.md notes the lock-free
    races were a misfeature), applies the optax update scaled by the
    dampening rule, and bumps the version. Unlike the sync step, nobody
    *waits* for anybody: a slow replica only makes its OWN gradient stale.

    ``clock`` is injectable (the virtual-time engine drives leases on
    simulated seconds); ``fault_sleep`` is the sleep used by injected fault
    delays, swapped for a virtual-time advance in simulation.
    """

    def __init__(self, params, optimizer: optax.GradientTransformation, *,
                 max_staleness: int = 4,
                 dampening="inverse",
                 lease_ttl_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None,
                 publish_to=None,
                 publish_every: int = 0):
        if max_staleness < 0:
            raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")
        if metrics is None:
            from ..utils.metrics import default_metrics
            metrics = default_metrics
        self.metrics = metrics
        self.optimizer = optimizer
        self.max_staleness = int(max_staleness)
        self.lease_ttl_s = float(lease_ttl_s)
        self.clock = clock
        self.fault_sleep = time.sleep
        self._damp = _resolve_dampening(dampening)
        self._lock = threading.Lock()
        self._params = jax.tree.map(jnp.asarray, params)
        self._opt_state = optimizer.init(self._params)
        self._version = 0
        self._replicas: Dict[str, _Lease] = {}
        self._evictions = 0
        # live publication (train→serve): every publish_every ACCEPTED
        # pushes, the current weights go to a serving WeightStore — the
        # pull side of the same versioned-weights idea this store implements
        self.publish_every = int(publish_every)
        if isinstance(publish_to, str):
            from ..serving.weightstore import WeightStore
            publish_to = WeightStore(publish_to)
        self._publish_store = publish_to

        def _apply(params, opt_state, grads, scale):
            updates, opt_state = optimizer.update(grads, opt_state, params)
            # dampening scales the UPDATE, not the raw gradient: adaptive
            # optimizers (adam's second-moment normalization) would cancel
            # a gradient-side scale, leaving stale pushes undampened
            updates = jax.tree.map(lambda u: u * scale, updates)
            return optax.apply_updates(params, updates), opt_state

        self._apply = jax.jit(_apply)

    # -- membership ---------------------------------------------------------

    def _expire_locked(self, now: float) -> None:
        dead = [rid for rid, l in self._replicas.items()
                if now - l.last_beat > self.lease_ttl_s]
        for rid in dead:
            del self._replicas[rid]
            self._evictions += 1
            logger.warning("elastic: replica %r lease expired (> %.1fs "
                           "without a heartbeat) — evicted", rid,
                           self.lease_ttl_s)
        if dead:
            self.metrics.incr("elastic/evicted", len(dead))
            self.metrics.gauge("elastic/replicas", len(self._replicas))

    def join(self, replica_id: str):
        """Register (or re-register after eviction/preemption) a replica and
        hand it the current weights. Returns ``(version, params)``."""
        now = self.clock()
        with self._lock:
            self._expire_locked(now)
            rejoin = replica_id in self._replicas
            self._replicas[replica_id] = _Lease(now)
            self.metrics.incr("elastic/join")
            self.metrics.gauge("elastic/replicas", len(self._replicas))
            if not rejoin:
                logger.info("elastic: replica %r joined (now %d alive)",
                            replica_id, len(self._replicas))
            return self._version, self._params

    def leave(self, replica_id: str) -> None:
        """Graceful exit: drop the lease immediately (no ttl wait)."""
        with self._lock:
            if self._replicas.pop(replica_id, None) is not None:
                self.metrics.gauge("elastic/replicas", len(self._replicas))

    def heartbeat(self, replica_id: str) -> bool:
        """Renew a lease. False means the lease already expired (or never
        existed) — the replica must :meth:`join` again."""
        now = self.clock()
        with self._lock:
            self._expire_locked(now)
            lease = self._replicas.get(replica_id)
            if lease is None:
                return False
            lease.last_beat = now
            return True

    def alive_count(self) -> int:
        with self._lock:
            self._expire_locked(self.clock())
            return len(self._replicas)

    def membership(self) -> Dict[str, ReplicaView]:
        with self._lock:
            return {rid: ReplicaView(rid, l.joined_at, l.last_beat,
                                     l.pushes, l.rejected, l.last_staleness)
                    for rid, l in self._replicas.items()}

    # -- weight/gradient exchange ------------------------------------------

    def pull(self, replica_id: str):
        """Fetch ``(version, params)``; renews the replica's lease when it
        holds one (a pull does NOT implicitly re-join — eviction must be
        answered by an explicit :meth:`join`)."""
        faults.fire("elastic.pull", sleep=self.fault_sleep)
        now = self.clock()
        with self._lock:
            self._expire_locked(now)
            lease = self._replicas.get(replica_id)
            if lease is not None:
                lease.last_beat = now
            return self._version, self._params

    def push(self, replica_id: str, grads, basis_version: int) -> PushResult:
        """Offer one gradient computed against ``basis_version``.

        Acceptance rule (the bounded-staleness contract):

        - no live lease (expired mid-compute / never joined) -> rejected,
          ``reason='lease_expired'`` — re-join first;
        - ``staleness = version - basis_version > max_staleness`` ->
          rejected, ``reason='stale'`` — refresh (the result carries the
          current weights) and recompute;
        - otherwise the update applies, scaled by ``dampening(staleness)``,
          and the version increments.

        SparseRows leaves are densified here — the store is where the
        PS-style sparse exchange lands.
        """
        faults.fire("elastic.push", sleep=self.fault_sleep)
        now = self.clock()
        from ..obs import span
        with span("elastic/push", args={"replica": replica_id}):
            with self._lock:
                self._expire_locked(now)
                lease = self._replicas.get(replica_id)
                if lease is None:
                    self.metrics.incr("elastic/push_rejected")
                    return PushResult(False, 0, self._version, self._params,
                                      0.0, "lease_expired")
                lease.last_beat = now
                staleness = self._version - int(basis_version)
                lease.last_staleness = staleness
                self.metrics.observe("elastic/staleness", float(staleness))
                if staleness > self.max_staleness:
                    lease.rejected += 1
                    self.metrics.incr("elastic/push_rejected")
                    return PushResult(False, staleness, self._version,
                                      self._params, 0.0, "stale")
                scale = float(self._damp(staleness))
                dense = jax.tree.map(jnp.asarray, decode_grads(grads))
                self._params, self._opt_state = self._apply(
                    self._params, self._opt_state, dense, np.float32(scale))
                self._version += 1
                lease.pushes += 1
                self.metrics.incr("elastic/push_accepted")
                result = PushResult(True, staleness, self._version,
                                    self._params, scale)
                do_publish = (self._publish_store is not None
                              and self.publish_every > 0
                              and self._version % self.publish_every == 0)
            # disk IO happens after the lock releases: a slow publication
            # must never stall concurrent pulls/pushes from other replicas
            if do_publish:
                self._publish(result.params)
            return result

    def _publish(self, params) -> None:
        """Best-effort live publication; a failed publish is logged and
        counted but never fails the training push that triggered it (the
        serving side keeps last-good weights either way)."""
        try:
            v = self._publish_store.publish(params)
            self.metrics.gauge("elastic/published_version", float(v))
        except Exception:
            self.metrics.incr("elastic/publish_failed")
            logger.exception("elastic: live weight publication failed")

    def snapshot(self):
        """``(version, params, opt_state)`` under the lock — checkpoint /
        end-of-training read."""
        with self._lock:
            return self._version, self._params, self._opt_state

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def evictions(self) -> int:
        with self._lock:
            return self._evictions


class InProcessTransport:
    """Default transport: direct store calls. Workers only ever talk to a
    transport, so tests (and future multi-host backends) swap in their own —
    the fault points in the store fire for every implementation that
    delegates here."""

    def __init__(self, store: ElasticParamStore):
        self.store = store

    def join(self, rid: str):
        return self.store.join(rid)

    def leave(self, rid: str) -> None:
        self.store.leave(rid)

    def heartbeat(self, rid: str) -> bool:
        return self.store.heartbeat(rid)

    def pull(self, rid: str):
        return self.store.pull(rid)

    def push(self, rid: str, grads, basis_version: int) -> PushResult:
        return self.store.push(rid, grads, basis_version)


# ---------------------------------------------------------------------------
# replica runner: one replica's sequential pull/compute/push state machine
# ---------------------------------------------------------------------------

class _ReplicaRunner:
    """Drives one replica over its data shard. Pure sequential logic — the
    threaded engine gives each runner its own thread, the virtual-time
    engine interleaves runners on a simulated clock; both call the same
    three methods (``join`` / ``compute`` / ``push``)."""

    def __init__(self, rid: str, index: int, transport, grad_fn,
                 x: np.ndarray, y: np.ndarray, batch: int, epochs: int,
                 seed: int, density_threshold: Optional[float],
                 max_stale_retries: int = 1,
                 loss_callback: Optional[Callable] = None):
        self.rid = rid
        self.index = index
        self.transport = transport
        self.grad_fn = grad_fn
        self.x, self.y = x, y
        n = x.shape[0]
        self.batch = max(1, min(batch, n))
        self.steps_per_epoch = max(1, n // self.batch)
        self.epochs = epochs
        self.total_steps = epochs * self.steps_per_epoch
        self.density_threshold = density_threshold
        self.max_stale_retries = max_stale_retries
        self.loss_callback = loss_callback
        self._rs = np.random.RandomState(seed)
        self._key = jax.random.PRNGKey(seed)
        self._perm = None
        self._perm_epoch = -1
        self.step = 0
        self.retries_this_batch = 0
        self.version = -1
        self.params = None
        # outcome accounting (read by the engine after the run)
        self.losses: List[Tuple[int, float]] = []  # (epoch, loss) accepted
        self.examples_applied = 0
        self.pushes = 0
        self.accepted = 0
        self.rejected_stale = 0
        self.rejected_lease = 0
        self.dropped_stale = 0
        self.dropped_lease = 0
        self.dropped_fault = 0
        self.dense_bytes = 0
        self.wire_bytes = 0

    def join(self) -> None:
        self.version, self.params = self.transport.join(self.rid)

    @property
    def done(self) -> bool:
        return self.step >= self.total_steps

    def _batch_indices(self) -> np.ndarray:
        e = self.step // self.steps_per_epoch
        if e != self._perm_epoch:
            self._perm = self._rs.permutation(self.x.shape[0])
            self._perm_epoch = e
        i = self.step % self.steps_per_epoch
        return self._perm[i * self.batch:(i + 1) * self.batch]

    def compute(self) -> Optional[dict]:
        """One local gradient on the current basis weights, encoded for the
        wire. None when this replica's work is complete."""
        if self.done:
            return None
        idx = self._batch_indices()
        xb = self.x[idx]
        yb = self.y[idx] if self.y is not None else np.zeros(
            (idx.size, 1), np.float32)
        mask = np.ones((idx.size,), np.float32)
        key = jax.random.fold_in(self._key, self.step * 131071 +
                                 self.retries_this_batch)
        loss, grads = self.grad_fn(self.params, xb, yb, mask, key)
        encoded, db, wb = encode_grads(grads, self.density_threshold)
        self.dense_bytes += db
        self.wire_bytes += wb
        return {"grads": encoded, "basis": self.version,
                "loss": float(loss), "epoch": self.step // self.steps_per_epoch,
                "examples": int(idx.size)}

    def push(self, payload: dict) -> Optional[PushResult]:
        """Push one payload; adopt the piggybacked weights either way.
        Returns None when the push was dropped by an injected fault (the
        gradient is lost; the runner resyncs and moves on — the reference's
        drop-the-update behavior, now counted instead of printed)."""
        self.pushes += 1
        try:
            res = self.transport.push(self.rid, payload["grads"],
                                      payload["basis"])
        except faults.InjectedFault:
            self.dropped_fault += 1
            try:
                self.version, self.params = self.transport.pull(self.rid)
            except faults.InjectedFault:
                pass  # resync on the next successful exchange
            self._advance()
            return None
        self.version, self.params = res.version, res.params
        if res.accepted:
            self.accepted += 1
            self.examples_applied += payload["examples"]
            self.losses.append((payload["epoch"], payload["loss"]))
            if self.loss_callback is not None:
                self.loss_callback(payload["loss"], self.step, self.index)
            self._advance()
        elif res.reason == "lease_expired":
            self.rejected_lease += 1
            self.join()  # re-register (fresh lease + weights) either way
            if self.retries_this_batch >= self.max_stale_retries:
                # a transport delay far beyond the lease TTL re-expires
                # every retry's fresh lease — without a bound the replica
                # re-joins and recomputes forever. Same rule as stale:
                # drop this batch's contribution and move on.
                self.dropped_lease += 1
                self._advance()
            else:
                self.retries_this_batch += 1
        else:  # stale beyond the bound: refresh happened via piggyback
            self.rejected_stale += 1
            if self.retries_this_batch >= self.max_stale_retries:
                # a persistent straggler would livelock recomputing forever
                # (every recompute ages past the bound again) — drop this
                # batch's contribution and move on, like DeepSpark's lagging
                # workers that simply skip ahead
                self.dropped_stale += 1
                self._advance()
            else:
                self.retries_this_batch += 1
        return res

    def _advance(self) -> None:
        self.step += 1
        self.retries_this_batch = 0

    def run_one(self) -> bool:
        """compute+push for the threaded engine; False when done."""
        payload = self.compute()
        if payload is None:
            return False
        self.push(payload)
        return True


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

@dataclass
class ReplicaSpec:
    """Virtual-time behavior of one replica: per-step compute cost in
    simulated seconds, when it joins, and an optional mid-run preemption
    window (``preempt_at`` .. ``rejoin_at``; ``rejoin_at=None`` means it
    never comes back)."""
    cost_s: float = 1.0
    join_at: float = 0.0
    preempt_at: Optional[float] = None
    rejoin_at: Optional[float] = None


@dataclass
class ElasticResult:
    """Outcome of an elastic run. ``losses`` is the per-epoch mean over
    accepted pushes (epochs a replica never completed contribute what was
    accepted); ``stats`` carries the push/membership accounting the tests
    and bench pin."""
    params: Any
    opt_state: Any
    losses: List[float]
    examples: int
    wall_s: float
    examples_per_sec: float
    version: int
    stats: Dict[str, Any] = field(default_factory=dict)


def _aggregate_losses(runners: Sequence[_ReplicaRunner]) -> List[float]:
    by_epoch: Dict[int, List[float]] = {}
    for r in runners:
        for e, l in r.losses:
            by_epoch.setdefault(e, []).append(l)
    return [float(np.mean(by_epoch[e])) for e in sorted(by_epoch)]


def _collect_stats(runners: Sequence[_ReplicaRunner],
                   store: ElasticParamStore) -> Dict[str, Any]:
    s = {
        "pushes": sum(r.pushes for r in runners),
        "accepted": sum(r.accepted for r in runners),
        "rejected_stale": sum(r.rejected_stale for r in runners),
        "rejected_lease": sum(r.rejected_lease for r in runners),
        "dropped_stale": sum(r.dropped_stale for r in runners),
        "dropped_lease": sum(r.dropped_lease for r in runners),
        "dropped_fault": sum(r.dropped_fault for r in runners),
        "dense_bytes": sum(r.dense_bytes for r in runners),
        "wire_bytes": sum(r.wire_bytes for r in runners),
        "evictions": store.evictions,
        "final_version": store.version,
        "per_replica_accepted": {r.rid: r.accepted for r in runners},
    }
    s["sparse_bytes_saved"] = s["dense_bytes"] - s["wire_bytes"]
    return s


class ElasticDPEngine:
    """Elastic bounded-staleness data-parallel training over an
    :class:`ElasticParamStore`.

    Two drivers over the same replica state machine:

    - :meth:`run_threads` — one OS thread per replica, real clock. The
      production-shaped path (``Trainer(strategy='elastic_dp')`` /
      ``HogwildTrainer``).
    - :meth:`run_virtual` — a deterministic event-driven simulation on a
      virtual clock: per-replica step costs, joins, mid-step preemptions
      and lease expiries all replay identically every run, with zero
      sleeping. The chaos tests and the straggler bench run here.
    """

    def __init__(self, loss_fn: Callable,
                 optimizer: optax.GradientTransformation, init_params, *,
                 max_staleness: int = 4, dampening="inverse",
                 density_threshold: Optional[float] = 0.25,
                 lease_ttl_s: float = 10.0,
                 metrics=None, transport=None,
                 loss_callback: Optional[Callable] = None,
                 publish_to=None, publish_every: int = 0):
        self.optimizer = optimizer
        self.density_threshold = density_threshold
        self.loss_callback = loss_callback
        self.store = ElasticParamStore(
            init_params, optimizer, max_staleness=max_staleness,
            dampening=dampening, lease_ttl_s=lease_ttl_s, metrics=metrics,
            publish_to=publish_to, publish_every=publish_every)
        self.transport = (transport if transport is not None
                          else InProcessTransport(self.store))

        def _value_and_grad(params, x, y, mask, rng):
            return jax.value_and_grad(loss_fn)(params, x, y, mask, rng)

        self.grad_fn = jax.jit(_value_and_grad)
        self.membership_trace: List[Tuple[float, int]] = []

    # -- shared setup -------------------------------------------------------

    def _make_runners(self, shards, batch: int, epochs: int, seed: int,
                      max_stale_retries: int = 1) -> List[_ReplicaRunner]:
        runners = []
        for i, (x, y) in enumerate(shards):
            runners.append(_ReplicaRunner(
                f"replica-{i}", i, self.transport, self.grad_fn, x, y,
                batch, epochs, seed + 1000003 * i, self.density_threshold,
                max_stale_retries=max_stale_retries,
                loss_callback=self.loss_callback))
        return runners

    def _warmup(self, runners: List[_ReplicaRunner]) -> None:
        """Compile the gradient program before concurrency starts (one trace
        per distinct batch shape) so threads never race a trace."""
        for r in runners:
            idx = np.arange(r.batch)
            xb = r.x[idx]
            yb = (r.y[idx] if r.y is not None
                  else np.zeros((idx.size, 1), np.float32))
            _v, params = self.transport.join(r.rid)  # also primes membership
            self.transport.leave(r.rid)
            out = self.grad_fn(params, xb, yb,
                               np.ones((idx.size,), np.float32),
                               jax.random.PRNGKey(0))
            jax.block_until_ready(out[0])

    def _result(self, runners, wall_s: float) -> ElasticResult:
        version, params, opt_state = self.store.snapshot()
        examples = sum(r.examples_applied for r in runners)
        stats = _collect_stats(runners, self.store)
        stats["membership_trace"] = list(self.membership_trace)
        return ElasticResult(
            params=params, opt_state=opt_state,
            losses=_aggregate_losses(runners), examples=examples,
            wall_s=wall_s,
            examples_per_sec=examples / max(wall_s, 1e-9),
            version=version, stats=stats)

    # -- threaded driver ----------------------------------------------------

    def run_threads(self, shards: Sequence[Tuple[np.ndarray,
                                                 Optional[np.ndarray]]],
                    *, epochs: int, batch_size: int,
                    seed: int = 0) -> ElasticResult:
        """Train with one thread per shard. ``shards`` is a list of
        ``(x, y)`` per replica (``y=None`` unsupervised). Returns when every
        replica finished its ``epochs`` over its shard (a replica whose
        pushes keep being dropped still terminates — dropped work is counted,
        not retried forever)."""
        runners = self._make_runners(shards, batch_size, epochs, seed)
        self._warmup(runners)
        # under an active RaceTracker (chaos/test runs), put the store's
        # hot shared state under lockset tracking; no-op (one None check)
        # otherwise
        racecheck.instrument_object(
            self.store,
            fields=("_version", "_params", "_opt_state", "_evictions"))
        errors: List[BaseException] = []

        def worker(r: _ReplicaRunner):
            try:
                r.join()
                while r.run_one():
                    pass
                self.transport.leave(r.rid)
            except BaseException as e:  # surfaced after join() below
                errors.append(e)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(r,),
                                    name=f"elastic-{r.rid}", daemon=True)
                   for r in runners]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return self._result(runners, wall)

    # -- virtual-time driver ------------------------------------------------

    def run_virtual(self, shards, specs: Sequence[ReplicaSpec], *,
                    epochs: int, batch_size: int,
                    seed: int = 0,
                    deadline_s: Optional[float] = None) -> ElasticResult:
        """Deterministic event-driven run on a virtual clock.

        Each replica alternates compute (costing ``spec.cost_s`` virtual
        seconds) and an instantaneous push; the store's lease clock reads
        the same virtual time, so straggling and preemption exercise the
        REAL eviction/rejection paths. A preemption that lands inside a
        compute window discards that in-flight gradient (the mid-step
        preemption case); the replica re-joins at ``rejoin_at`` and
        continues its remaining steps. Injected fault delays
        (``faults.inject(..., delay_ms=...)``) advance virtual time instead
        of sleeping.

        ``deadline_s`` switches from fixed-WORK to fixed-TIME-budget: no
        replica starts a new step at or past the deadline (in-flight steps
        land). This is the sustained-throughput measurement — without it a
        closed step count makes the run's tail "straggler finishing alone",
        which dilutes examples/sec toward the sync barrier number instead
        of measuring what the fleet sustains while elastic."""
        if len(specs) != len(shards):
            raise ValueError(f"{len(shards)} shards but {len(specs)} "
                             f"replica specs")
        runners = self._make_runners(shards, batch_size, epochs, seed,
                                     max_stale_retries=1)
        self._warmup(runners)

        vnow = [0.0]
        self.store.clock = lambda: vnow[0]
        self.store.fault_sleep = lambda s: vnow.__setitem__(0, vnow[0] + s)
        self.membership_trace = []

        # event heap: (time, seq, runner_index, action, payload)
        heap: List[Tuple[float, int, int, str, Any]] = []
        seq = [0]

        def schedule(t: float, i: int, action: str, payload=None):
            heapq.heappush(heap, (t, seq[0], i, action, payload))
            seq[0] += 1

        preempted_done = [False] * len(runners)
        for i, spec in enumerate(specs):
            schedule(max(0.0, spec.join_at), i, "start")

        def preempt_window(i: int, t0: float, t1: float) -> bool:
            """Does replica i's (not yet consumed) preemption land in
            (t0, t1]?"""
            p = specs[i].preempt_at
            return (p is not None and not preempted_done[i]
                    and t0 <= p < t1)

        t_end = 0.0
        while heap:
            t, _s, i, action, payload = heapq.heappop(heap)
            vnow[0] = max(vnow[0], t)
            t = vnow[0]
            t_end = max(t_end, t)
            r, spec = runners[i], specs[i]
            if action == "start":
                r.join()
                self.membership_trace.append((t, self.store.alive_count()))
                schedule(t, i, "compute")
            elif action == "compute":
                out_of_time = (deadline_s is not None
                               and t >= deadline_s - 1e-9)
                if r.done or out_of_time:
                    self.transport.leave(r.rid)
                    self.membership_trace.append(
                        (t, self.store.alive_count()))
                    continue
                payload = r.compute()
                finish = t + spec.cost_s
                if preempt_window(i, t, finish):
                    # preempted MID-STEP: the in-flight gradient dies with
                    # the replica; survivors keep pushing (nothing here
                    # blocks them), the lease expires on its own
                    preempted_done[i] = True
                    if spec.rejoin_at is not None:
                        schedule(max(spec.rejoin_at, finish), i, "start")
                    continue
                schedule(finish, i, "push", payload)
            elif action == "push":
                before = vnow[0]
                r.push(payload)  # may advance vnow via injected delay
                t_end = max(t_end, vnow[0], before)
                self.membership_trace.append(
                    (vnow[0], self.store.alive_count()))
                schedule(vnow[0], i, "compute")

        self.store.clock = time.monotonic
        self.store.fault_sleep = time.sleep
        return self._result(runners, t_end)


def sync_baseline_examples_per_sec(replica_costs: Sequence[float],
                                   batch_size: int) -> float:
    """The synchronous all-reduce throughput bound on the same virtual
    workload: every step waits on the SLOWEST replica (the barrier), so the
    fleet applies ``n * batch`` examples per ``max(cost)`` seconds. This is
    the generous bound for sync — zero collective/dispatch overhead — which
    makes it the conservative denominator for the elastic speedup."""
    costs = list(replica_costs)
    if not costs or min(costs) <= 0:
        raise ValueError("replica_costs must be positive and non-empty")
    return len(costs) * batch_size / max(costs)
