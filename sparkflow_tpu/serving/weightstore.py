"""Live weight publication: a versioned store + the hot-swap watcher.

The reference's whole identity is weights moving over the wire — the
driver-hosted Flask parameter server every executor GETs from and POSTs to
(``sparkflow/HogwildSparkModel.py:156-166``), and both DeepSpark
(arXiv:1602.08191) and SparkNet (arXiv:1511.06051) are periodic
weight-exchange designs. In this repo a deploy was still a process restart.
This module closes the train→serve loop, treating a weight push as what it
is: the single most dangerous mutation a serving fleet accepts.

Two halves:

- :class:`WeightStore` — immutable, monotonically versioned weight sets
  under one directory, published crash-consistently via the
  ``CheckpointManager`` pattern: tmp-dir write, per-file sha256
  ``manifest.json``, atomic ``os.rename``, then a ``latest.json`` pointer
  swapped via tmp + fsync + ``os.replace``. A process killed mid-publish
  leaves a ``_tmp_*`` dir no reader ever sees and an intact previous
  version; a torn or bit-rotted version fails its manifest and readers fall
  back to the newest *verifiable* one. :meth:`WeightStore.rollback`
  quarantines a bad version and repoints the pointer at the last good one —
  the health gate's instant-revert lever.

- :class:`WeightWatcher` — a serving-side daemon thread that polls
  ``latest_version()`` (transient read errors backed off per
  ``resilience.RetryPolicy``), verifies + loads a new version against the
  engine's shape/dtype template, and hands it to each attached engine's
  ``swap_params`` — double-buffered device arrays, applied at a
  batch/token boundary. Shapes are pinned unchanged, so the AOT
  executables are reused as-is: zero retraces, and no in-flight request
  ever observes mixed versions. Any failure (torn file, checksum
  mismatch, shape drift, injected ``engine.swap`` fault) keeps the
  replica on its **last-good** weights and is counted, never raised into
  the serving path.

Chaos surface: :func:`resilience.faults.fire` points
``weights.publish_commit`` (between manifest and rename — the torn-publish
window), ``weights.pull`` (every store read), and ``engine.swap`` (inside
each engine's swap) make the whole path fault-injectable;
``resilience.faults.corrupt_latest_weights`` damages a published version on
disk the way real corruption would. See ``docs/serving.md`` ("Live weight
publication"), ``make swap-smoke``, and ``bench.py --hot-swap``.

Lock order (GC-L304): ``WeightWatcher._lock`` guards only the watcher's own
counters; engine locks are taken via ``swap_params``/``maybe_swap`` calls
made *outside* it, so the watcher→engine edges keep the package lock graph
acyclic.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import time
from typing import (TYPE_CHECKING, Any, Dict, List, Optional, Sequence,
                    Set, Tuple)

import jax
import numpy as np

from ..resilience import faults
from ..resilience.retry import RetryPolicy
from ..utils import metrics as metrics_mod

if TYPE_CHECKING:  # type-only: the store must not pull in the engines
    from .decode import DecodeEngine
    from .engine import InferenceEngine

__all__ = ["WeightStoreError", "WeightStore", "WeightWatcher"]

logger = logging.getLogger("sparkflow_tpu")

MANIFEST_NAME = "manifest.json"
WEIGHTS_NAME = "weights.npz"


class WeightStoreError(RuntimeError):
    """Published versions exist but the requested one (or, with fallback,
    every one) is torn, corrupt, or shape-incompatible."""


def _file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return h.hexdigest()
            h.update(block)


class WeightStore:
    """Immutable, monotonically versioned weight sets under one directory.

    Layout: ``<dir>/v_<n>/weights.npz`` (flat leaves in tree order) +
    per-version ``manifest.json`` (sha256 + byte size per file) +
    ``<dir>/latest.json`` (the atomic pointer, which also carries the
    quarantine list :meth:`rollback` maintains). ``retry`` (a
    :class:`~sparkflow_tpu.resilience.retry.RetryPolicy`) governs transient
    read errors during :meth:`load`; the default retries OSErrors once.

    Publication is crash-consistent: a kill at ANY point leaves either the
    previous state intact or the new version fully in place — never a
    half-written ``v_<n>`` a replica could pull.
    """

    def __init__(self, directory: str, keep: int = 4, retry=None,
                 metrics: Optional[metrics_mod.Metrics] = None):
        self.directory = os.path.abspath(directory)
        self.keep = int(keep)
        self.retry = retry
        self.metrics = metrics if metrics is not None else metrics_mod.Metrics()
        self._lock = threading.Lock()  # in-process publish/rollback serializer
        os.makedirs(self.directory, exist_ok=True)

    def _version_dir(self, version: int) -> str:
        return os.path.join(self.directory, f"v_{version}")

    # -- publish -------------------------------------------------------------

    def _write_manifest(self, tmp: str, version: int, num_leaves: int) -> None:
        files = {}
        for root, _dirs, names in os.walk(tmp):
            for nm in sorted(names):
                full = os.path.join(root, nm)
                rel = os.path.relpath(full, tmp)
                files[rel] = {"sha256": _file_sha256(full),
                              "bytes": os.path.getsize(full)}
        manifest = {"version": int(version), "num_leaves": int(num_leaves),
                    "files": files}
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)

    def _write_latest(self, version: Optional[int],
                      quarantined: Optional[Set[int]] = None) -> None:
        # tmp + fsync + os.replace: the pointer swap is atomic — a kill
        # mid-write can never leave a truncated latest.json behind
        if quarantined is None:
            _, quarantined = self._read_pointer()
        final = os.path.join(self.directory, "latest.json")
        tmp = final + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"latest_version": (int(version)
                                          if version is not None else None),
                       "quarantined": sorted(int(v) for v in quarantined)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    def publish(self, params, *, version: Optional[int] = None) -> int:
        """Publish one immutable weight set; returns its version number.

        ``params`` is any pytree of arrays (device or host) in the model's
        **standard layout** — the same tree a checkpoint stores, before any
        serving-side quantize/shard transform (each replica re-derives its
        own placement on swap). The default version is one past the newest
        published; an explicit ``version`` must still be fresh and higher
        (versions are immutable and monotone — "republish v3" is not a
        thing, and a regressing publisher is a bug this raises on).
        """
        leaves = [np.asarray(x) for x in jax.tree.leaves(params)]
        if not leaves:
            raise ValueError("params has no array leaves to publish")
        with self._lock:
            have = self.all_versions()
            newest = have[-1] if have else 0
            v = int(version) if version is not None else newest + 1
            if v <= newest:
                raise WeightStoreError(
                    f"version {v} is not past the newest published version "
                    f"{newest}: weight versions are immutable and monotone")
            final = self._version_dir(v)
            # the tmp name fails all_versions's int parse, so a crash
            # mid-publish leaves a dir no reader ever mistakes for a version
            tmp = os.path.join(self.directory, f"_tmp_v{v}_{os.getpid()}")
            shutil.rmtree(tmp, ignore_errors=True)
            try:
                os.makedirs(tmp, exist_ok=True)
                np.savez(os.path.join(tmp, WEIGHTS_NAME),
                         **{f"l_{i}": x for i, x in enumerate(leaves)})
                self._write_manifest(tmp, v, len(leaves))
                # the torn-publish window: a crash here leaves the pointer
                # on the previous version and only a _tmp_* dir behind
                faults.fire("weights.publish_commit")
                os.rename(tmp, final)  # atomic on one filesystem
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            self._write_latest(v)
            self._gc()
        self.metrics.incr("weights/publishes")
        self.metrics.gauge("weights/published_version", float(v))
        logger.info("weightstore: published version %d to %s", v,
                    self.directory)
        return v

    def _gc(self) -> None:
        vs = self.all_versions()
        for v in vs[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(self._version_dir(v), ignore_errors=True)

    # -- discovery / verification -------------------------------------------

    def all_versions(self) -> List[int]:
        vs = []
        for name in os.listdir(self.directory):
            if name.startswith("v_"):
                try:
                    vs.append(int(name[2:]))
                except ValueError:
                    pass
        return sorted(vs)

    def _read_pointer(self) -> Tuple[Optional[int], Set[int]]:
        p = os.path.join(self.directory, "latest.json")
        if os.path.exists(p):
            try:
                with open(p) as f:
                    obj = json.load(f)
                v = obj.get("latest_version")
                q = {int(x) for x in obj.get("quarantined", [])}
                return (int(v) if isinstance(v, int) else None), q
            except (ValueError, OSError) as e:
                logger.warning(
                    "weightstore: latest.json in %s is unreadable (%s); "
                    "scanning version dirs instead", self.directory, e)
        return None, set()

    def quarantined(self) -> Set[int]:
        """Versions the health gate rolled back — never served again."""
        return self._read_pointer()[1]

    def latest_version(self) -> Optional[int]:
        """The pointer's version when it names an existing dir; otherwise
        the newest non-quarantined version on disk (pointer torn/missing)."""
        v, q = self._read_pointer()
        if v is not None and os.path.isdir(self._version_dir(v)):
            return v
        vs = [x for x in self.all_versions() if x not in q]
        return vs[-1] if vs else None

    def verify_version(self, version: int) -> bool:
        """True iff every file of ``version`` is present with matching
        size + sha256 and the manifest names this version."""
        path = self._version_dir(version)
        mp = os.path.join(path, MANIFEST_NAME)
        if not os.path.isdir(path) or not os.path.exists(mp):
            return False
        try:
            with open(mp) as f:
                manifest = json.load(f)
            files = manifest["files"]
        except (ValueError, KeyError, OSError):
            return False
        if manifest.get("version") != int(version):
            return False
        for rel, rec in files.items():
            full = os.path.join(path, rel)
            if not os.path.isfile(full):
                return False
            if os.path.getsize(full) != rec.get("bytes"):
                return False
            if _file_sha256(full) != rec.get("sha256"):
                return False
        return True

    # -- load ----------------------------------------------------------------

    def _read(self, version: int, like):
        path = os.path.join(self._version_dir(version), WEIGHTS_NAME)

        def read():
            with np.load(path) as z:
                flat = [z[f"l_{i}"] for i in range(len(z.files))]
            if like is None:
                return flat
            want, treedef = jax.tree.flatten(like)
            if len(flat) != len(want):
                raise WeightStoreError(
                    f"version {version} holds {len(flat)} leaves, the "
                    f"template expects {len(want)}")
            # the shapes-unchanged contract: hot swap reuses the AOT
            # executables, so a published tree that drifts in shape or
            # dtype must be rejected here, not discovered as a retrace
            for i, (got, w) in enumerate(zip(flat, want)):
                wshape = tuple(int(d) for d in w.shape)
                wdtype = np.dtype(w.dtype)
                if got.shape != wshape or got.dtype != wdtype:
                    raise WeightStoreError(
                        f"version {version} leaf {i} is "
                        f"{got.shape}/{got.dtype}, engine expects "
                        f"{wshape}/{wdtype} (shapes must be unchanged "
                        f"across a hot swap)")
            return jax.tree.unflatten(treedef, flat)

        if self.retry is None:
            policy = RetryPolicy(max_attempts=2, base_s=0.05, max_s=0.2,
                                 retry_on=(OSError,), seed=0)
        else:
            policy = self.retry
        return policy.call(read, describe=f"load weights version {version}")

    def load(self, version: Optional[int] = None, like=None,
             verify: bool = True) -> Optional[Tuple[int, Any]]:
        """Load ``(version, params)`` (default: newest loadable).

        ``like`` is a template pytree (arrays or ``ShapeDtypeStruct``
        leaves) supplying the tree structure and pinning shapes/dtypes.
        With ``version=None``, candidates are tried newest-first skipping
        quarantined ones; a version that fails verification or read is
        skipped with a warning — automatic fallback past torn or corrupt
        publishes (the restart-onto-last-good path). Returns None only when
        nothing is published; raises :class:`WeightStoreError` when
        versions exist but none loads. An explicit ``version`` never falls
        back: corruption there raises.
        """
        faults.fire("weights.pull")  # chaos hook; no-op unless armed
        explicit = version is not None
        if explicit:
            candidates = [int(version)]
        else:
            _, q = self._read_pointer()
            candidates = sorted((v for v in self.all_versions()
                                 if v not in q), reverse=True)
            latest = self.latest_version()
            if latest in candidates:  # pointer first (normally the max)
                candidates.remove(latest)
                candidates.insert(0, latest)
        if not candidates:
            return None
        failures = []
        for v in candidates:
            if verify and not self.verify_version(v):
                if explicit:
                    raise WeightStoreError(
                        f"weights version {v} in {self.directory} fails its "
                        f"manifest checksum (torn or corrupt)")
                logger.warning(
                    "weights version %d fails its manifest checksum (torn "
                    "or corrupt); falling back to the next valid version", v)
                failures.append((v, "manifest checksum mismatch"))
                continue
            try:
                params = self._read(v, like)
            except Exception as e:
                if explicit:
                    raise
                logger.warning(
                    "weights version %d is unreadable (%s: %s); falling "
                    "back to the next valid version", v, type(e).__name__, e)
                failures.append((v, f"{type(e).__name__}: {e}"))
                continue
            if failures:
                logger.warning(
                    "loaded weights version %d after skipping corrupt "
                    "version(s) %s", v, [f[0] for f in failures])
            return v, params
        detail = "; ".join(f"v{v}: {why}" for v, why in failures)
        raise WeightStoreError(
            f"no loadable weights in {self.directory} ({detail})")

    # -- rollback ------------------------------------------------------------

    def rollback(self, bad_version: Optional[int] = None,
                 to_version: Optional[int] = None) -> Optional[int]:
        """Quarantine ``bad_version`` (default: the current latest) and
        repoint ``latest.json`` at ``to_version`` (default: the newest
        *verifiable* non-quarantined version). Watchers polling
        ``latest_version()`` then revert every replica; the quarantined
        version is never offered again, even by fallback scans. Returns
        the new latest version (None when nothing good remains — replicas
        simply keep their in-memory last-good weights)."""
        with self._lock:
            ptr, quarantined = self._read_pointer()
            vs = self.all_versions()
            bad = (int(bad_version) if bad_version is not None
                   else (ptr if ptr is not None else (vs[-1] if vs else None)))
            if bad is not None:
                quarantined.add(bad)
            if to_version is None:
                to_version = next(
                    (v for v in sorted(vs, reverse=True)
                     if v not in quarantined and self.verify_version(v)),
                    None)
            self._write_latest(to_version, quarantined)
        self.metrics.incr("weights/rollbacks")
        if to_version is not None:
            self.metrics.gauge("weights/published_version", float(to_version))
        logger.warning(
            "weightstore: rolled back version %s -> %s (quarantined: %s)",
            bad, to_version, sorted(quarantined))
        return to_version

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        ptr, q = self._read_pointer()
        return {"directory": self.directory,
                "versions": self.all_versions(),
                "latest": self.latest_version(),
                "pointer": ptr,
                "quarantined": sorted(q),
                "keep": self.keep}


class WeightWatcher:
    """Poll a :class:`WeightStore` and hot-swap attached engines in place.

    One watcher serves one replica process: attach its engines (any mix of
    :class:`~sparkflow_tpu.serving.engine.InferenceEngine` /
    :class:`~sparkflow_tpu.serving.decode.DecodeEngine`), then
    :meth:`start`. Every ``poll_interval_s`` the daemon thread

    1. nudges engines with a deferred swap pending (``maybe_swap`` — a
       DecodeEngine applies at a drained token boundary, which may arrive
       between polls);
    2. reads ``store.latest_version()`` (errors counted, backed off);
    3. on a version change (up OR down — rollback is just a target below
       the current one), pulls + verifies the tree against the first
       engine's shape/dtype template under a
       :class:`~sparkflow_tpu.resilience.retry.RetryPolicy`, then calls
       each engine's ``swap_params``.

    Any pull/verify failure marks the version failed (retried only when
    the pointer moves) and the replica **keeps serving last-good weights**
    — a corrupt publish is a counter and a log line here, never an error a
    client sees. Pass the watcher to
    ``InferenceServer(weight_watcher=...)`` and ``/healthz`` carries the
    live ``serving_version`` plus the watcher's counters.
    """

    def __init__(self, store: WeightStore,
                 engines: Sequence["DecodeEngine | InferenceEngine"] = (),
                 *, poll_interval_s: float = 0.5, retry=None,
                 metrics: Optional[metrics_mod.Metrics] = None,
                 clock=time.monotonic):
        self.store = store
        self.poll_interval_s = float(poll_interval_s)
        self.retry = (retry if retry is not None
                      else RetryPolicy(max_attempts=3, base_s=0.05,
                                       max_s=0.5, retry_on=(OSError,),
                                       seed=0))
        self.metrics = metrics if metrics is not None else store.metrics
        self.clock = clock
        self._engines: List[Any] = list(engines)
        self._lock = threading.Lock()  # counters/targets only; never held
        #                                across store reads or engine calls
        self._target: Optional[int] = None   # last version handed to engines
        self._failed: Set[int] = set()       # versions that failed pull/verify
        self.polls = 0
        self.swaps = 0
        self.poll_errors = 0
        self.pull_failures = 0
        self.swap_failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def attach(self, engine) -> None:
        """Add an engine (before :meth:`start`); it must expose
        ``swap_params(params, version=)`` and ``weights_template()``."""
        for need in ("swap_params", "weights_template"):
            if not hasattr(engine, need):
                raise TypeError(f"engine has no {need}(); WeightWatcher "
                                f"needs a hot-swappable engine")
        self._engines.append(engine)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "WeightWatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="weight-watcher", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the watcher must never die
                with self._lock:
                    self.poll_errors += 1
                logger.exception("weight watcher poll failed; continuing")

    # -- polling -------------------------------------------------------------

    def poll_once(self) -> bool:
        """One poll tick (also callable synchronously from tests/smokes).
        Returns True when a new version was handed to every engine."""
        with self._lock:
            self.polls += 1
        # a deferred decode swap applies at a drained boundary that may
        # have arrived between polls — nudge before reading the store so an
        # idle engine flips without waiting for its next admission check
        for e in list(self._engines):
            nudge = getattr(e, "maybe_swap", None)
            if nudge is not None:
                nudge()
        try:
            target = self.store.latest_version()
        except OSError as e:
            with self._lock:
                self.poll_errors += 1
            logger.warning("weight watcher: store poll failed (%s)", e)
            return False
        with self._lock:
            if (target is None or target == self._target
                    or target in self._failed):
                return False
        if not self._engines:
            return False
        template = self._engines[0].weights_template()
        try:
            loaded = self.retry.call(
                self.store.load, version=target, like=template,
                describe=f"pull weights version {target}")
        except Exception as e:  # noqa: BLE001 - keep last-good, count it
            with self._lock:
                self._failed.add(target)
                self.pull_failures += 1
            self.metrics.incr("weights/pull_failures")
            logger.warning(
                "weight watcher: version %d failed verification/pull (%s: "
                "%s); keeping last-good weights", target,
                type(e).__name__, e)
            return False
        ver, params = loaded
        all_swapped = True
        for e in list(self._engines):
            try:
                e.swap_params(params, version=ver)
            except Exception as exc:  # noqa: BLE001 - engine keeps last-good
                all_swapped = False
                with self._lock:
                    self.swap_failures += 1
                self.metrics.incr("weights/swap_failures")
                logger.warning(
                    "weight watcher: swap to version %d failed on %s (%s: "
                    "%s); engine keeps last-good weights", ver,
                    type(e).__name__, type(exc).__name__, exc)
        if not all_swapped:
            return False  # retried next poll (target stays unclaimed)
        with self._lock:
            self._target = ver
            self.swaps += 1
        self.metrics.incr("weights/swaps")
        self.metrics.gauge("weights/target_version", float(ver))
        return True

    # -- introspection -------------------------------------------------------

    def serving_version(self) -> int:
        """The version every attached engine is actually serving (the min
        across engines — a deferred decode swap keeps this on the old
        version until it applies at a drained boundary). 0 = unpublished
        ctor weights."""
        versions = []
        for e in list(self._engines):
            sv = getattr(e, "serving_version", None)
            if callable(sv):
                versions.append(int(sv()))
        return min(versions) if versions else 0

    def stats(self) -> Dict[str, Any]:
        serving = self.serving_version()  # engine locks: outside our own
        with self._lock:
            return {"target_version": self._target,
                    "serving_version": serving,
                    "polls": self.polls,
                    "swaps": self.swaps,
                    "poll_errors": self.poll_errors,
                    "pull_failures": self.pull_failures,
                    "swap_failures": self.swap_failures,
                    "failed_versions": sorted(self._failed)}
