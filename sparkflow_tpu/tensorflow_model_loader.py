"""Import-compatibility alias: ``from sparkflow_tpu.tensorflow_model_loader
import load_tensorflow_model`` works exactly like the reference's
``from sparkflow.tensorflow_model_loader import load_tensorflow_model``
(``sparkflow/tensorflow_model_loader.py:8,35``).

The real implementation lives in :mod:`sparkflow_tpu.model_loader` (TF1 Saver
checkpoints are read straight off their shards; graphs rebuild in the DSL)."""

from .model_loader import (attach_pretrained_model_to_pipeline,
                           attach_tensorflow_model_to_pipeline,
                           extract_tensorflow_weights, load_tensorflow_model)

__all__ = ["load_tensorflow_model", "attach_tensorflow_model_to_pipeline",
           "attach_pretrained_model_to_pipeline", "extract_tensorflow_weights"]
