"""Streaming training with the native C++ dataplane.

For datasets beyond device memory: rows stream through the C++ batch-assembly
ring (padding/masking/shuffling on a GIL-free thread) while the device trains —
the big-data ingest path that replaces the reference's per-partition Python
loops. With pyspark, feed ``df.rdd.toLocalIterator()``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.trainer import Trainer


def model():
    x = nn.placeholder([None, 128], name="x")
    y = nn.placeholder([None, 1], name="y")
    h = nn.dense(x, 64, activation="relu")
    nn.sigmoid_cross_entropy(y, nn.dense(h, 1, name="out"))


def row_stream(n_rows=20000, dim=128, seed=0):
    """Simulates an out-of-core source: yields one row at a time."""
    rs = np.random.RandomState(seed)
    w = rs.randn(dim)
    for _ in range(n_rows):
        x = rs.randn(dim).astype(np.float32)
        yield x, float(x @ w > 0)


if __name__ == "__main__":
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    smoke = bool(os.environ.get("SPARKFLOW_TPU_SMOKE"))
    tr = Trainer(build_graph(model), "x:0", "y:0", mini_batch_size=256,
                 learning_rate=0.05)
    res = tr.fit_stream(row_stream(n_rows=2000 if smoke else 20000))
    print(f"steps: {len(res.losses)}  loss {res.losses[0]:.3f} -> "
          f"{res.losses[-1]:.3f}  throughput {int(res.examples_per_sec)} rows/s")
