"""ResNet family (v1.5 bottleneck) — functional JAX, stateless normalization.

Covers BASELINE.md's "ResNet-50 / CIFAR-10" config:
``build_registry_spec('resnet50', num_classes=10, image_size=32)``.

Design notes (TPU-first):
- GroupNorm instead of BatchNorm: batch statistics create cross-device state
  and train/eval divergence; group norm is stateless, pure, and shards cleanly
  over the batch axis (params stay tiny). This is a deliberate deviation — the
  reference has no ResNet at all (new capability, SURVEY.md §6).
- NHWC layout with f32 accumulation conv (bf16 operands under compute_dtype).
- Standard stage layout [3,4,6,3] for ResNet-50; [2,2,2,2] basic blocks for
  ResNet-18 via ``depth=18``.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .base import RegistryModel
from .registry import register_model

_STAGES = {18: ([2, 2, 2, 2], False), 34: ([3, 4, 6, 3], False),
           50: ([3, 4, 6, 3], True), 101: ([3, 4, 23, 3], True)}


def _conv(x, kernel, stride=1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, kernel.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _group_norm(x, scale, bias, groups=32, eps=1e-5):
    b, h, w, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    x32 = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = jnp.mean(x32, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(x32, axis=(1, 2, 4), keepdims=True)
    y = ((x32 - mean) * jax.lax.rsqrt(var + eps)).reshape(b, h, w, c)
    return (y * scale + bias).astype(x.dtype)


@register_model("resnet")
class ResNet(RegistryModel):
    TENSORS = ("x", "y", "logits", "probs", "pred")

    def __init__(self, num_classes: int, depth: int = 50, image_size: int = 32,
                 channels: int = 3, width: int = 64, compute_dtype=None):
        if depth not in _STAGES:
            raise ValueError(f"depth must be one of {sorted(_STAGES)}")
        self.num_classes = num_classes
        self.depth = depth
        self.image_size = image_size
        self.channels = channels
        self.width = width
        self.stages, self.bottleneck = _STAGES[depth]
        super().__init__(compute_dtype)

    # -- specs ----------------------------------------------------------------

    def input_specs(self):
        n = self.image_size
        return {"x": ((None, n, n, self.channels), "float32"),
                "y": ((None, self.num_classes), "float32")}

    def _block_channels(self) -> List[Tuple[str, int, int, int]]:
        """(name, cin, cmid, stride) per block, stage by stage."""
        blocks = []
        expansion = 4 if self.bottleneck else 1
        cin = self.width
        for si, n_blocks in enumerate(self.stages):
            cmid = self.width * (2 ** si)
            for bi in range(n_blocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                blocks.append((f"stage{si}_block{bi}", cin, cmid, stride))
                cin = cmid * expansion
        return blocks

    def param_specs(self):
        k = 3 if self.image_size <= 64 else 7  # CIFAR stem vs ImageNet stem
        specs = {"stem": {"kernel": ((k, k, self.channels, self.width), "he_normal"),
                          "gn_scale": ((self.width,), "ones"),
                          "gn_bias": ((self.width,), "zeros")}}
        expansion = 4 if self.bottleneck else 1
        for name, cin, cmid, stride in self._block_channels():
            cout = cmid * expansion
            if self.bottleneck:
                layer = {
                    "conv1": ((1, 1, cin, cmid), "he_normal"),
                    "gn1_scale": ((cmid,), "ones"), "gn1_bias": ((cmid,), "zeros"),
                    "conv2": ((3, 3, cmid, cmid), "he_normal"),
                    "gn2_scale": ((cmid,), "ones"), "gn2_bias": ((cmid,), "zeros"),
                    "conv3": ((1, 1, cmid, cout), "he_normal"),
                    "gn3_scale": ((cout,), "ones"), "gn3_bias": ((cout,), "zeros"),
                }
            else:
                layer = {
                    "conv1": ((3, 3, cin, cmid), "he_normal"),
                    "gn1_scale": ((cmid,), "ones"), "gn1_bias": ((cmid,), "zeros"),
                    "conv2": ((3, 3, cmid, cout), "he_normal"),
                    "gn2_scale": ((cout,), "ones"), "gn2_bias": ((cout,), "zeros"),
                }
            if stride != 1 or cin != cout:
                layer["proj"] = ((1, 1, cin, cout), "he_normal")
                layer["gnp_scale"] = ((cout,), "ones")
                layer["gnp_bias"] = ((cout,), "zeros")
            specs[name] = layer
        cfinal = self.width * (2 ** (len(self.stages) - 1)) * expansion
        specs["head"] = {"kernel": ((cfinal, self.num_classes), "zeros"),
                         "bias": ((self.num_classes,), "zeros")}
        return specs

    def param_pspecs(self):
        """ResNets replicate cleanly (small params); DP/FSDP shard via optimizer
        state if needed. All-replicated specs keep jit happy on any mesh."""
        return jax.tree.map(lambda _: P(), self.param_specs(),
                            is_leaf=lambda x: isinstance(x, tuple) and len(x) == 2
                            and isinstance(x[1], str))

    # -- forward ---------------------------------------------------------------

    def _bottleneck_block(self, bp, x, stride):
        y = jax.nn.relu(_group_norm(_conv(x, bp["conv1"]), bp["gn1_scale"], bp["gn1_bias"]))
        y = jax.nn.relu(_group_norm(_conv(y, bp["conv2"], stride), bp["gn2_scale"], bp["gn2_bias"]))
        y = _group_norm(_conv(y, bp["conv3"]), bp["gn3_scale"], bp["gn3_bias"])
        if "proj" in bp:
            x = _group_norm(_conv(x, bp["proj"], stride), bp["gnp_scale"], bp["gnp_bias"])
        return jax.nn.relu(x + y)

    def _basic_block(self, bp, x, stride):
        y = jax.nn.relu(_group_norm(_conv(x, bp["conv1"], stride), bp["gn1_scale"], bp["gn1_bias"]))
        y = _group_norm(_conv(y, bp["conv2"]), bp["gn2_scale"], bp["gn2_bias"])
        if "proj" in bp:
            x = _group_norm(_conv(x, bp["proj"], stride), bp["gnp_scale"], bp["gnp_bias"])
        return jax.nn.relu(x + y)

    def _forward(self, params, feeds, train, rng):
        x = self.cast(feeds["x"])
        if x.ndim == 2:  # flattened Spark vector column -> NHWC
            n = self.image_size
            x = x.reshape(x.shape[0], n, n, self.channels)
        sp = params["stem"]
        stride = 1 if self.image_size <= 64 else 2
        x = jax.nn.relu(_group_norm(_conv(x, sp["kernel"], stride),
                                    sp["gn_scale"], sp["gn_bias"]))
        if self.image_size > 64:
            x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                      (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        block = self._bottleneck_block if self.bottleneck else self._basic_block
        for name, _cin, _cmid, stride in self._block_channels():
            x = block(params[name], x, stride)
        pooled = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        logits = jnp.matmul(pooled, params["head"]["kernel"]) + params["head"]["bias"]
        return {"logits": logits,
                "probs": jax.nn.softmax(logits, axis=-1),
                "pred": jnp.argmax(logits, axis=-1).astype(jnp.float32)}

    def _loss(self, params, feeds, train, rng):
        from .base import softmax_xent
        logits = self._forward(params, feeds, train, rng)["logits"]
        return softmax_xent(logits, feeds["y"])


@register_model("resnet50")
class ResNet50(ResNet):
    def __init__(self, num_classes: int, image_size: int = 32, channels: int = 3,
                 compute_dtype=None):
        super().__init__(num_classes, depth=50, image_size=image_size,
                         channels=channels, compute_dtype=compute_dtype)
