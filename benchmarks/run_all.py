"""Extended benchmark suite (BASELINE.md's config ladder).

Prints one JSON line per benchmark. ``python benchmarks/run_all.py [--quick]``.
The headline driver metric stays in ``bench.py``; this file tracks the wider
ladder: MLP / CNN / autoencoder (the reference's three example workloads),
ResNet-50 CIFAR, BERT-base seq-512 step time, and the flash-attention kernel
against XLA's naive attention.
"""

import json
import sys
import time

import numpy as np

QUICK = "--quick" in sys.argv


def _emit(name, value, unit, extra=None):
    rec = {"benchmark": name, "value": round(float(value), 2), "unit": unit}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def _train_eps(graph, input_name, label_name, x, y, batch, epochs, **kw):
    """(examples/sec, mfu-extras dict) for a fused multi-epoch fit.

    FLOPs come from XLA's cost analysis of one train step (these ladder
    models are pure XLA — no pallas custom calls to undercount); MFU keys
    are omitted off-TPU, where a CPU 'peak' would be meaningless."""
    from sparkflow_tpu.trainer import Trainer
    from sparkflow_tpu.utils.flops import (device_peak_flops, mfu,
                                           train_step_flops)

    tr = Trainer(graph, input_name, label_name, optimizer="adam",
                 mini_batch_size=batch, iters=epochs, **kw)
    tr.fit(x, y)  # warmup compiles the same fused multi-epoch program
    res = tr.fit(x, y, init_params=tr.params)
    eps = res.examples_per_sec

    extra = {}
    n = x.shape[0]
    bs = min(batch, n)
    step_fl = train_step_flops(tr.model, input_name, label_name, tr.optimizer,
                               x[:bs], y[:bs] if y is not None else None)
    if step_fl:
        fps = (eps / bs) * step_fl
        extra["tflops_per_sec"] = round(fps / 1e12, 3)
        peak, assumed = device_peak_flops(return_assumed=True)
        u = mfu(fps, peak)
        if u is not None:
            extra["mfu"] = round(u, 4)
            if assumed:
                extra["peak_assumed"] = True
    return eps, extra


def bench_examples_ladder(compute_dtype):
    from sparkflow_tpu.models import presets

    n = 2048 if QUICK else 16384
    rs = np.random.RandomState(0)
    x = rs.rand(n, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n)]
    epochs = 2 if QUICK else 5

    eps, ex = _train_eps(presets.mlp(784, 10), "x:0", "y:0", x, y, 1024,
                         epochs, compute_dtype=compute_dtype)
    _emit("mnist_mlp_train", eps, "examples/sec", ex)
    eps, ex = _train_eps(presets.cnn(), "x:0", "y:0", x, y, 1024, epochs,
                         compute_dtype=compute_dtype)
    _emit("mnist_cnn_train", eps, "examples/sec", ex)
    eps, ex = _train_eps(presets.autoencoder(784), "x:0", None, x, None,
                         1024, epochs, compute_dtype=compute_dtype)
    _emit("mnist_autoencoder_train", eps, "examples/sec", ex)


def bench_resnet(compute_dtype):
    from sparkflow_tpu.models import build_registry_spec

    n = 256 if QUICK else 2048
    rs = np.random.RandomState(0)
    x = rs.rand(n, 32, 32, 3).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n)]
    spec = build_registry_spec("resnet", num_classes=10,
                               depth=18 if QUICK else 50, image_size=32,
                               width=16 if QUICK else 64)
    eps, ex = _train_eps(spec, "x:0", "y:0", x, y, 64 if QUICK else 256, 2,
                         compute_dtype=compute_dtype)
    _emit("resnet_cifar_train", eps, "examples/sec",
          {"depth": 18 if QUICK else 50, **ex})


def bench_bert_step(compute_dtype):
    import jax
    import jax.numpy as jnp
    import optax

    from sparkflow_tpu.models import build_registry_spec, model_from_json
    from sparkflow_tpu.optimizers import build_optimizer

    from sparkflow_tpu.utils.flops import (device_peak_flops, mfu,
                                           transformer_train_step_flops)

    if QUICK:
        cfg = dict(vocab_size=1000, hidden=128, num_layers=2, num_heads=4,
                   mlp_dim=256, max_len=128)
        batches = (8,)
    else:
        cfg = dict(vocab_size=30522, hidden=768, num_layers=12, num_heads=12,
                   mlp_dim=3072, max_len=512)
        # batch is the first MFU lever (BASELINE.md fixes model+seq, not
        # batch; the metric is examples/sec/chip) — scan and keep the best
        batches = (16, 32, 64) if jax.default_backend() == "tpu" else (16,)
    m = model_from_json(build_registry_spec("transformer_classifier",
                                            num_classes=2, dropout=0.1, **cfg),
                        compute_dtype=compute_dtype)
    opt = build_optimizer("adam", 1e-4, None)
    rs = np.random.RandomState(0)

    def measure(B):
        params = m.init(jax.random.PRNGKey(0))
        state = opt.init(params)

        @jax.jit
        def step(params, state, ids, y, rng):
            def lf(p):
                return m.loss_vector(p, {"input_ids": ids, "y": y},
                                     train=True, rng=rng).mean()
            loss, g = jax.value_and_grad(lf)(params)
            u, state = opt.update(g, state, params)
            return optax.apply_updates(params, u), state, loss

        def batch(i):
            return (jnp.asarray(rs.randint(0, cfg["vocab_size"],
                                           (B, cfg["max_len"])), jnp.int32),
                    jnp.asarray(np.eye(2)[rs.randint(0, 2, B)], jnp.float32))

        def key(i):
            # hardware PRNG dropout keys on TPU: threefry mask generation is
            # pure VPU overhead on the step (the mfu_sweep 'rbg' variant
            # measures the delta); the headline entry runs the best config
            if jax.default_backend() == "tpu":
                return jax.random.key(i, impl="rbg")
            return jax.random.PRNGKey(i)

        ids, y = batch(0)
        params, state, loss = step(params, state, ids, y, key(0))
        jax.block_until_ready(params)
        if jax.default_backend() == "tpu":
            # fail LOUDLY if the perf path degraded: a kernel edit that broke
            # the TPU tile rules would otherwise fall back silently and this
            # number would quietly measure XLA attention instead
            from sparkflow_tpu.ops.attention import last_attention_path
            path = last_attention_path()
            assert path == "pallas", (
                f"BERT step attention traced to the {path!r} path, not the "
                f"pallas kernel — the flash tile rules rejected this config")
        t0 = time.perf_counter()
        n_steps = 3 if QUICK else 8
        for i in range(n_steps):
            ids, y = batch(i + 1)
            params, state, loss = step(params, state, ids, y, key(i))
        jax.block_until_ready(params)
        return (time.perf_counter() - t0) / n_steps

    results = {B: measure(B) for B in batches}

    # attention runs in pallas here, which XLA's cost analysis counts as
    # zero flops — use the analytic transformer count instead
    def _entry(B):
        dt = results[B]
        step_fl = transformer_train_step_flops(
            B, cfg["max_len"], cfg["hidden"], cfg["num_layers"],
            cfg["mlp_dim"], num_classes=2)
        peak, assumed = device_peak_flops(return_assumed=True)
        extra = {"ms_per_step": round(dt * 1e3, 1), "batch": B,
                 "seq": cfg["max_len"],
                 "tflops_per_sec": round(step_fl / dt / 1e12, 3)}
        u = mfu(step_fl / dt, peak)
        if u is not None:
            extra["mfu"] = round(u, 4)
            if assumed:
                extra["peak_assumed"] = True
        return extra

    # the headline metric stays at the historical fixed batch (B=16) so
    # cross-round and vs-baseline comparisons compare the same config;
    # the batch scan is reported alongside, best batch as its own metric
    B0 = batches[0]
    extra = _entry(B0)
    if len(results) > 1:
        extra["examples_per_sec_by_batch"] = {
            str(b): round(b / t, 2) for b, t in results.items()}
    _emit("bert_seq512_train_step" if not QUICK else "bert_tiny_train_step",
          B0 / results[B0], "examples/sec", extra)
    if len(results) > 1:
        Bb = max(results, key=lambda b: b / results[b])
        if Bb != B0:
            _emit("bert_seq512_train_step_best_batch", Bb / results[Bb],
                  "examples/sec", _entry(Bb))


def bench_flash_attention():
    import jax
    import jax.numpy as jnp

    from sparkflow_tpu.ops import attention_reference, flash_attention

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        # interpret-mode pallas under jit unrolls the whole grid — the number
        # would measure the interpreter, not the kernel
        _emit("flash_attention_vs_xla", 0, "speedup_x", {"skipped": "not on tpu"})
        return
    S = 1024 if QUICK else 4096
    rs = np.random.RandomState(0)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    # The axon relay has a ~72ms fixed sync round-trip and memoizes identical
    # executions, so per-dispatch timing measures the relay, not the kernel.
    # Amortize: lax.scan the op over ITERS pre-stacked fresh inputs inside ONE
    # jit — a single dispatch+sync covers ITERS kernel invocations.
    ITERS = 4 if QUICK else 16

    def _fresh_stack():
        # a NEW buffer per timed call: the relay memoizes identical
        # (executable, args) executions, so the measured call must use inputs
        # the warm-up call never saw
        return jax.block_until_ready(
            jnp.asarray(rs.randn(ITERS, 2, 8, S, 64), dtype))

    def _timed(op):
        @jax.jit
        def many(xs):
            def body(acc, q):
                return acc + op(q), None
            out, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), xs)
            return out
        float(many(_fresh_stack()))  # compile + warm
        inp = _fresh_stack()
        t0 = time.perf_counter()
        float(many(inp))
        return (time.perf_counter() - t0) / ITERS

    from sparkflow_tpu.utils.flops import attention_flops, device_peak_flops

    peak = device_peak_flops()

    def _kernel_util(flops, secs):
        return ({"kernel_tflops_per_sec": round(flops / secs / 1e12, 2),
                 "kernel_util": round(flops / secs / peak, 4)} if peak else {})

    tf = _timed(lambda q: flash_attention(q, q, q, causal=True).astype(jnp.float32).sum())
    from sparkflow_tpu.ops.attention import last_attention_path
    assert last_attention_path() == "pallas", (
        f"flash bench traced the {last_attention_path()!r} path — the pallas "
        f"kernel was silently rejected for this config")
    tr = _timed(lambda q: attention_reference(q, q, q, causal=True)
                .astype(jnp.float32).sum())
    fwd_fl = attention_flops(2, 8, S, S, 64, causal=True)
    _emit("flash_attention_vs_xla", tr / tf, "speedup_x",
          {"seq": S, "flash_ms": round(tf * 1e3, 2),
           "xla_ms": round(tr * 1e3, 2), **_kernel_util(fwd_fl, tf)})

    # fwd+bwd: the training-path comparison (pallas dq/dk/dv kernels vs
    # XLA autodiff of the dense reference)
    tfg = _timed(lambda q: jax.grad(lambda a: flash_attention(
        a, a, a, causal=True).astype(jnp.float32)
        .sum())(q).astype(jnp.float32).sum())
    trg = _timed(lambda q: jax.grad(lambda a: attention_reference(a, a, a,
        causal=True).astype(jnp.float32).sum())(q).astype(jnp.float32).sum())
    fb_fl = attention_flops(2, 8, S, S, 64, causal=True, with_backward=True)
    _emit("flash_attention_fwd_bwd_vs_xla", trg / tfg, "speedup_x",
          {"seq": S, "flash_ms": round(tfg * 1e3, 2),
           "xla_ms": round(trg * 1e3, 2), **_kernel_util(fb_fl, tfg)})


def bench_flash_long_context():
    """Long-sequence flash entries (8k/16k/32k): the regime the kernel is
    for. XLA comparison uses the blockwise (memory-bounded) attention — the
    dense reference would materialize an [B,H,S,S] score tensor (8 GB at
    32k) and is not a runnable baseline there. TPU-only, amortized timing
    over fresh inputs like bench_flash_attention."""
    import jax
    import jax.numpy as jnp

    from sparkflow_tpu.ops import flash_attention
    from sparkflow_tpu.ops.attention import _blockwise_attention
    from sparkflow_tpu.utils.flops import attention_flops, device_peak_flops

    if jax.default_backend() != "tpu":
        _emit("flash_attention_long_context", 0, "speedup_x",
              {"skipped": "not on tpu"})
        return
    peak = device_peak_flops()
    rs = np.random.RandomState(0)
    seqs = (8192,) if QUICK else (8192, 16384, 32768)
    for S in seqs:
        B, H, D = 1, 8, 64
        ITERS = 4

        def _fresh():
            return jax.block_until_ready(
                jnp.asarray(rs.randn(ITERS, B, H, S, D), jnp.bfloat16))

        def _timed(op):
            @jax.jit
            def many(xs):
                def body(acc, q):
                    return acc + op(q), None
                out, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), xs)
                return out
            float(many(_fresh()))  # compile + warm
            inp = _fresh()
            t0 = time.perf_counter()
            float(many(inp))
            return (time.perf_counter() - t0) / ITERS

        tf = _timed(lambda q: flash_attention(q, q, q, causal=True)
                    .astype(jnp.float32).sum())
        from sparkflow_tpu.ops.attention import last_attention_path
        assert last_attention_path() == "pallas", (
            f"long-context bench at seq {S} traced the "
            f"{last_attention_path()!r} path, not the pallas kernel")
        tb = _timed(lambda q: _blockwise_attention(
            q, q, q, None, True, 1.0 / 8.0, block_k=512)
            .astype(jnp.float32).sum())
        fl = attention_flops(B, H, S, S, D, causal=True)
        extra = {"seq": S, "flash_ms": round(tf * 1e3, 2),
                 "xla_blockwise_ms": round(tb * 1e3, 2),
                 "kernel_tflops_per_sec": round(fl / tf / 1e12, 2)}
        if peak:
            extra["kernel_util"] = round(fl / tf / peak, 4)
        _emit("flash_attention_long_context", tb / tf, "speedup_x", extra)


def bench_ring_flash_long_context():
    """Ring-flash sequence-parallel attention at 8k/16k GLOBAL context: the
    sp training path's attention (K/V shards rotating over the ring, pallas
    kernel per visit — ops/attention.py:ring_flash_attention). On one chip
    the ring is a single hop; on a pod slice the same program spans ICI.
    Emits per-chip tokens/sec so multi-chip runs compare per-chip
    efficiency, not just scale. TPU-only; amortized over fresh inputs."""
    import jax
    import jax.numpy as jnp
    from sparkflow_tpu.jax_compat import shard_map
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from sparkflow_tpu.ops import ring_flash_attention
    from sparkflow_tpu.utils.flops import attention_flops, device_peak_flops

    if jax.default_backend() != "tpu":
        _emit("ring_flash_long_context", 0, "tokens_per_sec_per_chip",
              {"skipped": "not on tpu"})
        return
    peak = device_peak_flops()
    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    rs = np.random.RandomState(0)
    seqs = (8192,) if QUICK else (8192, 16384)
    for S in seqs:
        B, H, D = 1, 8, 64
        ITERS = 4

        def inner(q, k, v):
            o = ring_flash_attention(q, k, v, "sp", causal=True)
            return jax.lax.psum(o.astype(jnp.float32).sum(), "sp")

        ring = shard_map(inner, mesh=mesh,
                         in_specs=(P(None, None, "sp"),) * 3,
                         out_specs=P(), check_vma=False)

        def _fresh():
            return jax.block_until_ready(
                jnp.asarray(rs.randn(ITERS, B, H, S, D), jnp.bfloat16))

        @jax.jit
        def many(xs):
            def body(acc, q):
                return acc + ring(q, q, q), None
            out, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), xs)
            return out

        float(many(_fresh()))  # compile + warm
        inp = _fresh()
        t0 = time.perf_counter()
        float(many(inp))
        t = (time.perf_counter() - t0) / ITERS
        fl = attention_flops(B, H, S, S, D, causal=True)
        extra = {"seq": S, "ring_devices": n,
                 "ring_flash_ms": round(t * 1e3, 2),
                 "tflops_per_sec_per_chip": round(fl / t / n / 1e12, 2)}
        if peak:
            extra["kernel_util"] = round(fl / t / n / peak, 4)
        _emit("ring_flash_long_context", round(B * S / t / n, 1),
              "tokens_per_sec_per_chip", extra)


def bench_stream_vs_collect(compute_dtype):
    """fitMode='stream' vs the collect path on the same CNN workload: the
    native batch ring assembles fixed-shape batches concurrently with device
    compute, so streaming examples/sec should stay within ~10% of the fused
    in-memory fit — if it doesn't, the device is idling on host IO."""
    from sparkflow_tpu.models import presets
    from sparkflow_tpu.trainer import Trainer

    n = 2048 if QUICK else 16384
    rs = np.random.RandomState(0)
    x = rs.rand(n, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n)]
    epochs = 2 if QUICK else 4

    def make_trainer():
        return Trainer(presets.cnn(), "x:0", "y:0", optimizer="adam",
                       mini_batch_size=1024, iters=epochs,
                       compute_dtype=compute_dtype)

    tr = make_trainer()
    tr.fit(x, y)  # compile warmup
    collect_eps = tr.fit(x, y, init_params=tr.params).examples_per_sec

    def rows():
        for i in range(n):
            yield (x[i], y[i])

    ts = make_trainer()
    ts.fit_stream(rows, epochs=1)  # compile warmup (per-step program)
    stream_eps = ts.fit_stream(rows, init_params=ts.params,
                               epochs=epochs).examples_per_sec
    _emit("stream_vs_collect_fit", stream_eps / collect_eps, "ratio",
          {"stream_examples_per_sec": round(stream_eps, 1),
           "collect_examples_per_sec": round(collect_eps, 1)})


def bench_quantized_inference():
    """int8 serving vs f32 on a wide MLP (the shape quantized serving is
    for: weight-HBM-bound batch inference). TPU-only, amortized timing —
    one scan over fresh pre-staged batches per mode."""
    import jax
    import jax.numpy as jnp

    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.graphdef import GraphModel
    import sparkflow_tpu.nn as nn_

    if jax.default_backend() != "tpu":
        _emit("int8_inference_vs_f32", 0, "speedup_x", {"skipped": "not on tpu"})
        return

    def wide_mlp():
        x = nn_.placeholder([None, 1024], name="x")
        h = nn_.dense(x, 4096, activation="relu")
        h = nn_.dense(h, 4096, activation="relu")
        h = nn_.dense(h, 4096, activation="relu")
        nn_.dense(h, 16, name="out")

    model = GraphModel.from_json(build_graph(wide_mlp))
    params = model.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    B, ITERS = 256, 16

    def timed(p):
        @jax.jit
        def many(xs):
            def body(acc, xb):
                out = model.apply(p, {"x": xb}, ["out:0"])["out:0"]
                return acc + out.astype(jnp.float32).sum(), None
            tot, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), xs)
            return tot

        def fresh():
            return jax.block_until_ready(jnp.asarray(
                rs.rand(ITERS, B, 1024), jnp.float32))
        float(many(fresh()))  # compile + warm
        inp = fresh()
        t0 = time.perf_counter()
        float(many(inp))
        return (time.perf_counter() - t0) / ITERS

    t_f32 = timed(params)
    results = {}
    for mode in ("weight_only", "dynamic"):
        qp = model.quantize_for_serving(params, mode=mode)
        try:
            results[mode] = timed(qp)
        finally:
            model.quant_mode = None
    _emit("int8_inference_vs_f32", t_f32 / results["weight_only"], "speedup_x",
          {"batch": B, "f32_ms": round(t_f32 * 1e3, 2),
           "weight_only_ms": round(results["weight_only"] * 1e3, 2),
           "dynamic_ms": round(results["dynamic"] * 1e3, 2),
           "dynamic_speedup_x": round(t_f32 / results["dynamic"], 2)})


def bench_serving_throughput():
    """Micro-batched serving engine vs naive per-request apply: the same
    request stream (mixed sizes 1..8 rows) through (a) one jitted apply call
    per request — the no-batching server, every shape pre-warmed so it pays
    dispatch overhead, not compiles — and (b) the AOT bucket engine behind
    the MicroBatcher, requests coalesced under the deadline. Measurable on
    any backend; the per-call overhead being amortized is host-side."""
    import jax

    import sparkflow_tpu.nn as nn_
    from sparkflow_tpu.graph_utils import build_graph
    from sparkflow_tpu.models import model_from_json
    from sparkflow_tpu.serving import InferenceEngine, MicroBatcher

    def mlp():
        x = nn_.placeholder([None, 256], name="x")
        h = nn_.dense(x, 512, activation="relu")
        h = nn_.dense(h, 512, activation="relu")
        nn_.dense(h, 16, name="out")

    rs = np.random.RandomState(0)
    n_req = 64 if QUICK else 512
    sizes = rs.randint(1, 9, n_req)
    reqs = [rs.rand(s, 256).astype(np.float32) for s in sizes]
    total_rows = int(sizes.sum())

    model = model_from_json(build_graph(mlp))
    params = model.init(jax.random.PRNGKey(0))
    engine = InferenceEngine(model, params, input_name="x:0",
                             output_name="out/BiasAdd:0", max_batch=64)

    naive = jax.jit(lambda p, xb: model.apply(
        p, {"x": xb}, ["out/BiasAdd:0"])["out/BiasAdd:0"])
    for s in sorted(set(sizes.tolist())):
        np.asarray(naive(params, np.zeros((s, 256), np.float32)))
    t0 = time.perf_counter()
    for r in reqs:
        np.asarray(naive(params, r))
    t_naive = time.perf_counter() - t0

    with MicroBatcher(engine, max_delay_ms=1.0, max_queue=8192) as batcher:
        t0 = time.perf_counter()
        futures = [batcher.submit(r) for r in reqs]
        for f in futures:
            f.result()
        t_batched = time.perf_counter() - t0
    _emit("serving_throughput", t_naive / t_batched, "speedup_x",
          {"requests": n_req, "rows": total_rows,
           "batched_rows_per_sec": round(total_rows / t_batched, 1),
           "naive_rows_per_sec": round(total_rows / t_naive, 1),
           "recompiles_after_warmup": engine.fallback_compiles})


def bench_resume_overhead():
    """Crash/resume tax: an uninterrupted checkpointed fit vs the same fit
    crashed mid-run (deterministic fault injection) and restarted through
    ``resilience.run_resilient_fit``. Emits the wall-clock ratio plus a
    bit-identical-params check. Any backend — the tax being measured is
    host-side (checkpoint IO, restore, resume skip-ahead)."""
    import shutil
    import tempfile

    import jax

    from sparkflow_tpu.models import presets
    from sparkflow_tpu.resilience import (RetryPolicy, faults,
                                          run_resilient_fit)
    from sparkflow_tpu.trainer import Trainer

    n = 2048 if QUICK else 8192
    epochs = 6 if QUICK else 12
    rs = np.random.RandomState(0)
    x = rs.rand(n, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n)]

    def make(d, cb):
        # the loss_callback keeps both runs on the per-epoch loop path, so
        # the comparison isolates the resume tax, not loop-vs-fused dispatch
        return Trainer(presets.mlp(784, 10), "x:0", "y:0", optimizer="adam",
                       mini_batch_size=1024, iters=epochs, seed=7,
                       checkpoint_dir=d, checkpoint_every=2,
                       resume_retries=0, loss_callback=cb)

    d0 = tempfile.mkdtemp(prefix="bench_resume_base_")
    d1 = tempfile.mkdtemp(prefix="bench_resume_crash_")
    try:
        t0 = time.perf_counter()
        base = make(d0, lambda *a: None).fit(x, y)
        t_base = time.perf_counter() - t0

        crash = faults.crash_at(epochs // 2)
        pol = RetryPolicy(max_attempts=4, base_s=0.0, jitter=0.0, seed=0,
                          sleep=lambda _s: None)  # measure work, not backoff
        t0 = time.perf_counter()
        res = run_resilient_fit(make(d1, crash), x, y, max_restarts=2,
                                restart_policy=pol)
        t_crash = time.perf_counter() - t0

        identical = all(np.array_equal(a, b) for a, b in zip(
            jax.tree.leaves(jax.tree.map(np.asarray, base.params)),
            jax.tree.leaves(jax.tree.map(np.asarray, res.params))))
        _emit("resume_overhead", t_crash / t_base, "ratio",
              {"uninterrupted_s": round(t_base, 2),
               "crash_resume_s": round(t_crash, 2),
               "crash_epoch": epochs // 2, "epochs": epochs,
               "bit_identical_params": bool(identical)})
    finally:
        shutil.rmtree(d0, ignore_errors=True)
        shutil.rmtree(d1, ignore_errors=True)


def bench_tokenizer():
    """Native C++ WordPiece vs the python fallback — measurable on any host
    (no TPU involved): strings/sec on synthetic text."""
    from sparkflow_tpu.utils.text import WordpieceTokenizer, build_vocab

    rs = np.random.RandomState(0)
    words = ["".join(chr(97 + c) for c in rs.randint(0, 26, rs.randint(2, 10)))
             for _ in range(2000)]
    texts = [" ".join(words[i] for i in rs.randint(0, len(words), 24))
             for _ in range(500 if QUICK else 4000)]
    vocab = build_vocab(texts, max_size=5000)

    results = {}
    for label, use_native in (("native", True), ("python", False)):
        tok = WordpieceTokenizer(vocab, use_native=use_native)
        if label == "native" and tok._native is None:
            results[label] = None
            continue
        t0 = time.perf_counter()
        tok.encode_batch(texts, 64)
        results[label] = len(texts) / (time.perf_counter() - t0)
    if results.get("native"):
        _emit("wordpiece_tokenizer_native_vs_python",
              results["native"] / results["python"], "speedup_x",
              {"native_strings_per_sec": round(results["native"]),
               "python_strings_per_sec": round(results["python"])})
    else:
        _emit("wordpiece_tokenizer_native_vs_python", 0, "speedup_x",
              {"skipped": "no C++ toolchain"})


def bench_dataplane():
    """Native C++ batch-assembly ring vs the python fallback queue — host-side
    streaming throughput (rows/sec), measurable on any machine. The ring is
    what feeds the device in `fitMode='stream'`."""
    import threading

    from sparkflow_tpu.utils import data as D

    n_rows = 20_000 if QUICK else 200_000
    row_dim, bs = 64, 256
    rows = np.random.RandomState(0).rand(n_rows, row_dim).astype(np.float32)
    chunks = [rows[i:i + 1024] for i in range(0, n_rows, 1024)]

    def pump(use_native):
        real_loader = D.load_library
        if not use_native:
            D.load_library = lambda: None
        try:
            q = D.BatchQueue(bs, row_dim, 0, capacity=8, shuffle=True)
        finally:
            D.load_library = real_loader
        if use_native and q._lib is None:
            q.close()
            return None

        def feed():
            for c in chunks:
                q.push(c)
            q.finish()

        t = threading.Thread(target=feed, daemon=True)
        t0 = time.perf_counter()
        t.start()
        seen = 0
        for x, y, mask, n_real in q:
            seen += n_real
        dt = time.perf_counter() - t0
        t.join()
        q.close()
        assert seen == n_rows, (seen, n_rows)
        return n_rows / dt

    native = pump(True)
    python = pump(False)
    if native:
        _emit("dataplane_ring_native_vs_python", native / python, "speedup_x",
              {"native_rows_per_sec": round(native),
               "python_rows_per_sec": round(python)})
    else:
        _emit("dataplane_ring_native_vs_python", 0, "speedup_x",
              {"skipped": "no C++ toolchain"})


def bench_dp_zero1():
    """ZeRO-1 weight-update sharding vs the replicated dp step: step time and
    per-device optimizer-state bytes (expect ~1/dp) on a pure-dp mesh over
    all local devices. One JSON line; skips below 2 devices."""
    import jax
    import jax.numpy as jnp

    from sparkflow_tpu.models import build_registry_spec, model_from_json
    from sparkflow_tpu.optimizers import build_optimizer
    from sparkflow_tpu.optimizers_sharded import (place_zero1_state,
                                                  sharded_update,
                                                  state_bytes_per_device)
    from sparkflow_tpu.parallel.dp import (make_dp_shardmap_train_step,
                                           make_dp_zero1_train_step)
    from sparkflow_tpu.parallel.mesh import make_mesh

    dp = jax.device_count()
    if dp < 2:
        _emit("dp_zero1_vs_replicated", 0, "ratio",
              {"skipped": "needs >= 2 devices"})
        return
    hidden = 128 if QUICK else 512
    layers = 2 if QUICK else 4
    spec = build_registry_spec("transformer_classifier", vocab_size=1000,
                               num_classes=8, hidden=hidden,
                               num_layers=layers, num_heads=8,
                               mlp_dim=4 * hidden, max_len=64, dropout=0.0)
    m = model_from_json(spec)
    opt = build_optimizer("adam", 1e-3, None)
    mesh = make_mesh({"dp": dp})
    B = 8 * dp
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 1000, (B, 64)), jnp.float32)
    y = jnp.asarray(np.eye(8, dtype=np.float32)[rs.randint(0, 8, B)])
    mask = jnp.ones((B,), jnp.float32)
    p0 = m.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    steps = 5 if QUICK else 20

    def timed(step, params, state):
        params, state, _ = step(params, state, ids, y, mask, rng)  # compile
        jax.block_until_ready(params)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, state, _ = step(params, state, ids, y, mask, rng)
        jax.block_until_ready(params)
        return (time.perf_counter() - t0) / steps, state

    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    pR = jax.device_put(jax.tree.map(jnp.array, p0), repl)
    sR = jax.device_put(opt.init(pR), repl)
    tR, sR = timed(make_dp_shardmap_train_step(m, opt, mesh, "input_ids", "y"),
                   pR, sR)
    bytesR = state_bytes_per_device(sR)

    pZ = jax.device_put(jax.tree.map(jnp.array, p0), repl)
    sZ = place_zero1_state(sharded_update(opt, dp, "dp").init(pZ), mesh, dp)
    tZ, sZ = timed(make_dp_zero1_train_step(m, opt, mesh, "input_ids", "y"),
                   pZ, sZ)
    bytesZ = state_bytes_per_device(sZ)

    _emit("dp_zero1_vs_replicated", tR / tZ, "step_time_speedup_x",
          {"dp": dp,
           "replicated_step_ms": round(tR * 1e3, 2),
           "zero1_step_ms": round(tZ * 1e3, 2),
           "replicated_opt_state_bytes_per_device": int(bytesR),
           "zero1_opt_state_bytes_per_device": int(bytesZ),
           "opt_state_reduction_x": round(bytesR / max(bytesZ, 1), 2)})


def main():
    import os
    import sys as _sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from sparkflow_tpu.utils.hw import ensure_live_backend

    fallback = ensure_live_backend()
    import jax

    platform = jax.default_backend()
    if fallback:
        platform += " (fallback: accelerator unreachable)"
    compute_dtype = "bfloat16" if platform == "tpu" else None
    print(json.dumps({"suite": "sparkflow-tpu-benchmarks",
                      "platform": platform, "quick": QUICK}), flush=True)
    bench_examples_ladder(compute_dtype)
    bench_resnet(compute_dtype)
    bench_bert_step(compute_dtype)
    bench_flash_attention()
    bench_flash_long_context()
    bench_ring_flash_long_context()
    bench_stream_vs_collect(compute_dtype)
    bench_dp_zero1()
    bench_quantized_inference()
    bench_serving_throughput()
    bench_resume_overhead()
    bench_tokenizer()
    bench_dataplane()


if __name__ == "__main__":
    main()
