"""Runtime guards (GC-R4xx): catch silent retraces while the program runs.

``jax.jit`` never says when it recompiles — a dtype drift, a ragged batch,
or an unhashed config object just quietly costs seconds per step. The
:class:`RecompileGuard` makes retraces observable: the wrapped function's
Python body runs exactly once per trace, so counting executions counts
compilations, and diffing the argument signature between traces names
*which* argument's shape/dtype/static value changed.

Two ways in:

- ``RecompileGuard(fn)`` — owns the jit: call the guard like the jitted
  function. ``guard.retraces`` / ``guard.report()`` / ``guard.findings()``.
- ``guard.wrap(fn)`` — instrument ``fn`` for an external ``jit`` /
  ``lower().compile()`` pipeline (how the serving engine counts its AOT
  bucket ladder: every bucket compile is an expected trace, anything after
  :meth:`mark_steady` is a regression).

:func:`track_recompiles` is the fit-level hook: inside the context every
``trace_probe``-instrumented build (the core train/epoch steps) reports
traces to the tracker, and the trainer's ``debug_recompiles=True`` wires
it up end to end. Probes are zero-cost when no tracker is active — the
lookup happens at *trace* time, which is already paying a compile.
"""

from __future__ import annotations

import functools
import logging
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from .findings import Finding

__all__ = ["RecompileGuard", "track_recompiles", "trace_probe",
           "describe_signature_diff"]

logger = logging.getLogger("sparkflow_tpu")


def _leaf_sig(leaf) -> Tuple:
    aval = getattr(leaf, "aval", None)
    if aval is not None and hasattr(aval, "shape"):  # a tracer
        return ("array", tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)))
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return ("array", tuple(leaf.shape), str(leaf.dtype),
                bool(getattr(leaf, "weak_type", False)))
    return ("static", repr(leaf))


def _signature(args: Tuple, kwargs: Dict) -> List[Tuple[str, Tuple]]:
    """Flat [(path, leaf signature)] for one call's arguments. Paths are
    jax keystrs (``[0]['params']...``) so diffs name the exact leaf."""
    flat = jax.tree_util.tree_flatten_with_path((args, kwargs))[0]
    return [(jax.tree_util.keystr(path), _leaf_sig(leaf))
            for path, leaf in flat]


def describe_signature_diff(old: List[Tuple[str, Tuple]],
                            new: List[Tuple[str, Tuple]]) -> str:
    """Human-readable first difference between two call signatures."""
    old_d, new_d = dict(old), dict(new)
    if set(old_d) != set(new_d):
        gained = sorted(set(new_d) - set(old_d))[:3]
        lost = sorted(set(old_d) - set(new_d))[:3]
        return (f"pytree structure changed (new leaves: {gained or '[]'}, "
                f"dropped leaves: {lost or '[]'})")
    diffs = []
    for path, sig in new:
        prev = old_d.get(path)
        if prev != sig:
            diffs.append(f"arg{path}: {_render_sig(prev)} -> "
                         f"{_render_sig(sig)}")
    if not diffs:
        return "signatures identical (cache evicted or first trace)"
    shown = "; ".join(diffs[:3])
    more = f" (+{len(diffs) - 3} more)" if len(diffs) > 3 else ""
    return shown + more


def _render_sig(sig: Optional[Tuple]) -> str:
    if sig is None:
        return "<absent>"
    if sig[0] == "array":
        _, shape, dtype, weak = sig
        return f"{dtype}{list(shape)}{' (weak)' if weak else ''}"
    return f"static {sig[1]}"


class RecompileGuard:
    """Count (re)traces of one function and name what caused each.

    Parameters
    ----------
    fn : callable | None
        With a function, the guard jits it (``jit_kwargs`` forwarded) and
        is called in its place. With None, use :meth:`wrap` to instrument
        a function for an external jit/AOT pipeline.
    warn_after : int
        Retrace count beyond which each further trace logs a warning and
        :meth:`findings` reports GC-R401. The first trace is free; a
        bucket-ladder AOT warmup should raise it (or use
        :meth:`mark_steady`).
    """

    def __init__(self, fn: Optional[Callable] = None, *,
                 name: Optional[str] = None, warn_after: int = 1,
                 jit_kwargs: Optional[Dict[str, Any]] = None):
        self.name = name or (getattr(fn, "__name__", "fn") if fn else "fn")
        self.warn_after = int(warn_after)
        self._lock = threading.Lock()
        self._sigs: List[List[Tuple[str, Tuple]]] = []
        self._causes: List[str] = []
        self._steady_at: Optional[int] = None
        self._jitted = (jax.jit(self.wrap(fn), **(jit_kwargs or {}))
                        if fn is not None else None)

    def wrap(self, fn: Callable) -> Callable:
        """Instrument ``fn``: its Python body runs once per trace, so the
        wrapper records one signature per compilation."""

        @functools.wraps(fn)
        def probed(*args, **kwargs):
            self._record(_signature(args, kwargs))
            return fn(*args, **kwargs)

        return probed

    def _record(self, sig: List[Tuple[str, Tuple]]) -> None:
        with self._lock:
            cause = (describe_signature_diff(self._sigs[-1], sig)
                     if self._sigs else "first trace")
            self._sigs.append(sig)
            self._causes.append(cause)
            traces = len(self._sigs)
            steady = self._steady_at
        if steady is not None and traces > steady:
            logger.warning("RecompileGuard[%s]: retrace after steady state "
                           "(#%d): %s", self.name, traces, cause)
        elif traces > self.warn_after:
            logger.warning("RecompileGuard[%s]: retrace #%d: %s",
                           self.name, traces, cause)

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            raise TypeError("RecompileGuard was built without a function; "
                            "use .wrap(fn) and call the wrapped pipeline")
        return self._jitted(*args, **kwargs)

    # -- introspection -------------------------------------------------------

    @property
    def traces(self) -> int:
        with self._lock:
            return len(self._sigs)

    @property
    def retraces(self) -> int:
        return max(0, self.traces - 1)

    @property
    def causes(self) -> List[str]:
        with self._lock:
            return list(self._causes)

    def mark_steady(self) -> None:
        """Declare warmup over: every trace so far was expected, any trace
        after this is a regression (``steady_traces`` counts them)."""
        with self._lock:
            self._steady_at = len(self._sigs)

    @property
    def steady_traces(self) -> int:
        """Traces since :meth:`mark_steady` (0 before it's called)."""
        with self._lock:
            if self._steady_at is None:
                return 0
            return len(self._sigs) - self._steady_at

    def findings(self) -> List[Finding]:
        out = []
        with self._lock:
            traces = len(self._sigs)
            causes = list(self._causes)
            steady = self._steady_at
        excess = (traces - steady if steady is not None
                  else traces - self.warn_after)
        if excess > 0 and traces > 1:
            out.append(Finding(
                "GC-R401",
                f"{self.name} traced {traces}x "
                f"({excess} beyond budget); last cause: {causes[-1]}",
                source="runtime_guard",
                detail={"traces": traces, "causes": causes}))
        return out

    def report(self) -> str:
        with self._lock:
            lines = [f"RecompileGuard[{self.name}]: "
                     f"{len(self._sigs)} trace(s)"]
            lines += [f"  #{i + 1}: {c}"
                      for i, c in enumerate(self._causes)]
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# fit-level tracking: trace probes + an ambient tracker
# ---------------------------------------------------------------------------

_tracker_stack = threading.local()


def _current_tracker() -> Optional["_Tracker"]:
    stack = getattr(_tracker_stack, "stack", None)
    return stack[-1] if stack else None


class _Tracker:
    """Collects per-probe trace signatures inside a track_recompiles()."""

    def __init__(self, warn_after: int = 1):
        self.warn_after = warn_after
        self._lock = threading.Lock()
        self._sigs: Dict[str, List[List[Tuple[str, Tuple]]]] = {}
        self._causes: Dict[str, List[str]] = {}

    def record(self, name: str, sig: List[Tuple[str, Tuple]]) -> None:
        with self._lock:
            sigs = self._sigs.setdefault(name, [])
            causes = self._causes.setdefault(name, [])
            cause = (describe_signature_diff(sigs[-1], sig) if sigs
                     else "first trace")
            sigs.append(sig)
            causes.append(cause)
            count = len(sigs)
        if count > self.warn_after:
            logger.warning("recompile: %s traced #%d: %s", name, count,
                           cause)

    @property
    def traces(self) -> Dict[str, int]:
        with self._lock:
            return {k: len(v) for k, v in self._sigs.items()}

    def findings(self) -> List[Finding]:
        out = []
        with self._lock:
            items = [(k, len(v), self._causes[k][-1])
                     for k, v in self._sigs.items()]
        for name, count, last in items:
            if count > self.warn_after:
                out.append(Finding(
                    "GC-R401",
                    f"{name} traced {count}x inside one fit "
                    f"(budget {self.warn_after}); last cause: {last}",
                    source="runtime_guard",
                    detail={"traces": count}))
        return out

    def report(self) -> str:
        with self._lock:
            if not self._sigs:
                return "no traced builds inside track_recompiles()"
            lines = []
            for name, sigs in self._sigs.items():
                lines.append(f"{name}: {len(sigs)} trace(s)")
                lines += [f"  #{i + 1}: {c}"
                          for i, c in enumerate(self._causes[name])]
        return "\n".join(lines)


@contextmanager
def track_recompiles(warn_after: int = 1):
    """Activate retrace tracking for ``trace_probe``-instrumented builds on
    this thread. Yields the tracker; read ``tracker.traces`` /
    ``tracker.findings()`` / ``tracker.report()`` after the workload."""
    tracker = _Tracker(warn_after=warn_after)
    stack = getattr(_tracker_stack, "stack", None)
    if stack is None:
        stack = _tracker_stack.stack = []
    stack.append(tracker)
    try:
        yield tracker
    finally:
        stack.pop()


def trace_probe(fn: Callable, name: str) -> Callable:
    """Instrument a to-be-jitted function body so an ambient
    :func:`track_recompiles` tracker sees its traces. Free when no tracker
    is active (one thread-local read per *trace*, not per call)."""

    @functools.wraps(fn)
    def probed(*args, **kwargs):
        tracker = _current_tracker()
        if tracker is not None:
            tracker.record(name, _signature(args, kwargs))
        return fn(*args, **kwargs)

    return probed
