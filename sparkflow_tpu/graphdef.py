"""Declarative, JSON-serializable model graphs executed by JAX.

This is the TPU-native replacement for the reference's model wire format: sparkflow
serializes a TF1 ``MetaGraphDef`` protobuf to JSON (``sparkflow/graph_utils.py:6-15``)
and rebuilds a ``tf.Session`` from it on every worker
(``sparkflow/HogwildSparkModel.py:45-54``, ``sparkflow/ml_util.py:54-73``). Here the
wire format is a small dataflow graph of named ops (a ``GraphDef``); the executor
(:class:`GraphModel`) turns it into a pure ``init``/``apply`` pair that is jittable,
differentiable with ``jax.grad``, and shardable with ``pjit`` — no sessions, no
mutable graph state, static shapes only.

Tensor naming is TF1-compatible so user-facing strings like ``'x:0'`` and
``'out/Sigmoid:0'`` (see reference ``examples/autoencoder_example.py:13,38``) keep
working: every node's output tensor is addressable as ``'<name>:0'``, and layers with
a fused activation also register ``'<layer>/<Activation>:0'``.

Losses follow the ``tf.losses`` collection convention the reference relies on
(loss fetched from ``tf.GraphKeys.LOSSES[0]``, ``sparkflow/HogwildSparkModel.py:50``):
loss ops register themselves in ``GraphDef.losses``. Loss ops here compute
*per-example* loss vectors so the trainer can mask padded rows (XLA needs static
batch shapes; the last partial batch is padded and masked, not ragged).
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

FORMAT_NAME = "sparkflow-tpu-graph"
FORMAT_VERSION = 1

# ---------------------------------------------------------------------------
# GraphDef: nodes + name registry
# ---------------------------------------------------------------------------


class Node:
    """One op in the dataflow graph. Serializes to a plain JSON dict."""

    __slots__ = ("id", "op", "name", "inputs", "attrs")

    def __init__(self, id: int, op: str, name: str, inputs: List[int], attrs: Dict[str, Any]):
        self.id = id
        self.op = op
        self.name = name
        self.inputs = inputs
        self.attrs = attrs

    def to_json(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "op": self.op,
            "name": self.name,
            "inputs": list(self.inputs),
            "attrs": self.attrs,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Node":
        return Node(d["id"], d["op"], d["name"], list(d["inputs"]), dict(d["attrs"]))


class GraphDef:
    """A serializable model graph: nodes in topological (creation) order."""

    def __init__(self):
        self.nodes: List[Node] = []
        self.losses: List[int] = []  # node ids registered as losses
        self.aliases: Dict[str, int] = {}  # tensor name -> node id
        self._name_counts: Dict[str, int] = {}
        self._taken: set = set()

    # -- construction -------------------------------------------------------

    def unique_name(self, base: str) -> str:
        n = self._name_counts.get(base, 0)
        while True:
            cand = base if n == 0 else f"{base}_{n}"
            n += 1
            if cand not in self._taken:
                self._name_counts[base] = n
                self._taken.add(cand)
                return cand

    def add_node(self, op: str, name: Optional[str], inputs: Sequence[int],
                 attrs: Dict[str, Any], alias: bool = True) -> Node:
        name = self.unique_name(name or op)
        node = Node(len(self.nodes), op, name, list(inputs), attrs)
        self.nodes.append(node)
        if alias:
            self.aliases[f"{name}:0"] = node.id
        return node

    def register_loss(self, node_id: int) -> None:
        self.losses.append(node_id)

    def add_alias(self, tensor_name: str, node_id: int) -> None:
        self.aliases[tensor_name] = node_id

    # -- lookup -------------------------------------------------------------

    def resolve(self, tensor_name: str) -> int:
        """Resolve a TF1-style tensor name ('x:0', 'out/Sigmoid:0', or bare 'x')."""
        for cand in (tensor_name, f"{tensor_name}:0"):
            if cand in self.aliases:
                return self.aliases[cand]
        known = ", ".join(sorted(self.aliases))
        raise KeyError(f"tensor {tensor_name!r} not found in graph; known tensors: {known}")

    def placeholders(self) -> List[Node]:
        return [n for n in self.nodes if n.op == "placeholder"]

    # -- serialization ------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "format": FORMAT_NAME,
            "version": FORMAT_VERSION,
            "nodes": [n.to_json() for n in self.nodes],
            "losses": self.losses,
            "aliases": self.aliases,
        })

    @staticmethod
    def from_json(s: str) -> "GraphDef":
        d = json.loads(s)
        if d.get("format") != FORMAT_NAME:
            raise ValueError(f"not a {FORMAT_NAME} document (format={d.get('format')!r})")
        g = GraphDef()
        g.nodes = [Node.from_json(nd) for nd in d["nodes"]]
        g.losses = list(d["losses"])
        g.aliases = dict(d["aliases"])
        # mark full names AND base scope names (e.g. 'out' for 'out/BiasAdd')
        # as taken so extending a deserialized graph can't silently collide
        for n in g.nodes:
            g._taken.add(n.name)
            g._taken.add(n.name.split("/")[0])
        for a in g.aliases:
            g._taken.add(a.split(":")[0])
        return g


# ---------------------------------------------------------------------------
# Op registry: shape inference, parameter shapes, evaluation
# ---------------------------------------------------------------------------

Shape = Tuple[Optional[int], ...]

_INITIALIZERS: Dict[str, Callable[..., Any]] = {
    "glorot_uniform": jax.nn.initializers.glorot_uniform,
    "glorot_normal": jax.nn.initializers.glorot_normal,
    "he_uniform": jax.nn.initializers.he_uniform,
    "he_normal": jax.nn.initializers.he_normal,
    "lecun_normal": jax.nn.initializers.lecun_normal,
    "lecun_uniform": jax.nn.initializers.lecun_uniform,
}


def _get_initializer(name: str, gain_axes: Tuple[int, ...] = (-2, -1)):
    if name == "zeros":
        return jax.nn.initializers.zeros
    if name == "ones":
        return jax.nn.initializers.ones
    if name.startswith("normal"):
        stddev = 0.05
        if "(" in name:
            stddev = float(name[name.index("(") + 1:name.index(")")])
        return jax.nn.initializers.normal(stddev)
    if name in _INITIALIZERS:
        return _INITIALIZERS[name]()
    raise ValueError(f"unknown initializer {name!r}")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


_ACTIVATIONS: Dict[str, Callable] = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "gelu": jax.nn.gelu,
    "elu": jax.nn.elu,
    "leaky_relu": jax.nn.leaky_relu,
    "softplus": jax.nn.softplus,
    "swish": jax.nn.swish,
    "identity": lambda x: x,
}

# Canonical TF1 op-scope names so 'out/Sigmoid:0'-style tensor names match
# what the reference's users are used to (tf.layers.dense(name='out',
# activation=tf.nn.sigmoid) -> tensor 'out/Sigmoid:0').
_TF_ACT_SCOPE = {
    "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh", "softmax": "Softmax",
    "log_softmax": "LogSoftmax", "gelu": "Gelu", "elu": "Elu",
    "leaky_relu": "LeakyRelu", "softplus": "Softplus", "swish": "Swish",
}


class _EvalCtx:
    """Per-apply context threaded through op evaluation."""

    __slots__ = ("params", "feeds", "train", "rng", "compute_dtype",
                 "quant_mode")

    def __init__(self, params, feeds, train, rng, compute_dtype,
                 quant_mode=None):
        self.params = params
        self.feeds = feeds
        self.train = train
        self.rng = rng
        self.compute_dtype = compute_dtype
        self.quant_mode = quant_mode

    def next_rng(self):
        if self.rng is None:
            raise ValueError("this graph uses dropout during training; pass rng to apply()")
        self.rng, sub = jax.random.split(self.rng)
        return sub


def _cast(x, dtype):
    if dtype is None:
        return x
    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(dtype)
    return x


# Each op: infer(node, in_shapes) -> out_shape;
#          params(node, in_shapes) -> {pname: (shape, init_name)} or {};
#          eval(node, ins, ctx) -> array.

def _infer_placeholder(node, ins):
    return tuple(node.attrs["shape"])


def _infer_dense(node, ins):
    return tuple(ins[0][:-1]) + (node.attrs["units"],)


def _params_dense(node, ins):
    in_dim = ins[0][-1]
    if in_dim is None:
        raise ValueError(f"dense layer {node.name!r}: input feature dim must be static")
    p = {"kernel": ((in_dim, node.attrs["units"]), node.attrs.get("kernel_init", "glorot_uniform"))}
    if node.attrs.get("use_bias", True):
        p["bias"] = ((node.attrs["units"],), node.attrs.get("bias_init", "zeros"))
    return p


def _eval_dense(node, ins, ctx, p):
    x = _cast(ins[0], ctx.compute_dtype)
    if "kernel_q8" in p:  # int8-quantized serving tree (utils/quant.py)
        from .utils.quant import quantized_dense
        return _cast(quantized_dense(x, p, ctx.quant_mode or "weight_only",
                                     compute_dtype=ctx.compute_dtype),
                     ctx.compute_dtype)
    k = _cast(p["kernel"], ctx.compute_dtype)
    # same-dtype operands keep the VJP well-typed; with bf16 compute the TPU
    # MXU still accumulates in f32 internally. Without a compute dtype, ask
    # for f32 accumulation explicitly.
    if ctx.compute_dtype is None:
        y = jnp.matmul(x, k, preferred_element_type=jnp.float32)
    else:
        y = jnp.matmul(x, k)
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


def _conv_out_dim(size, k, stride, padding):
    if size is None:
        return None
    if padding == "SAME":
        return -(-size // stride)
    return -(-(size - k + 1) // stride)


def _infer_conv2d(node, ins):
    n, h, w, _ = ins[0]
    kh, kw = _pair(node.attrs["kernel_size"])
    sh, sw = _pair(node.attrs.get("strides", 1))
    pad = node.attrs.get("padding", "VALID").upper()
    return (n, _conv_out_dim(h, kh, sh, pad), _conv_out_dim(w, kw, sw, pad), node.attrs["filters"])


def _params_conv2d(node, ins):
    cin = ins[0][-1]
    kh, kw = _pair(node.attrs["kernel_size"])
    p = {"kernel": ((kh, kw, cin, node.attrs["filters"]),
                    node.attrs.get("kernel_init", "glorot_uniform"))}
    if node.attrs.get("use_bias", True):
        p["bias"] = ((node.attrs["filters"],), node.attrs.get("bias_init", "zeros"))
    return p


def _eval_conv2d(node, ins, ctx, p):
    x = _cast(ins[0], ctx.compute_dtype)
    if "kernel_q8" in p:  # conv always serves weight-only (see utils/quant.py)
        from .utils.quant import dequantize_tensor
        k = _cast(dequantize_tensor(p["kernel_q8"], p["kernel_scale"]),
                  ctx.compute_dtype)
    else:
        k = _cast(p["kernel"], ctx.compute_dtype)
    sh, sw = _pair(node.attrs.get("strides", 1))
    pad = node.attrs.get("padding", "VALID").upper()
    y = jax.lax.conv_general_dilated(
        x, k, window_strides=(sh, sw), padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=None if ctx.compute_dtype is not None else jnp.float32)
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


def _infer_pool(node, ins):
    n, h, w, c = ins[0]
    kh, kw = _pair(node.attrs["pool_size"])
    sh, sw = _pair(node.attrs.get("strides", node.attrs["pool_size"]))
    pad = node.attrs.get("padding", "VALID").upper()
    return (n, _conv_out_dim(h, kh, sh, pad), _conv_out_dim(w, kw, sw, pad), c)


def _eval_pool(node, ins, ctx, reducer, init_val):
    kh, kw = _pair(node.attrs["pool_size"])
    sh, sw = _pair(node.attrs.get("strides", node.attrs["pool_size"]))
    pad = node.attrs.get("padding", "VALID").upper()
    x = ins[0]
    y = jax.lax.reduce_window(x, init_val, reducer, (1, kh, kw, 1), (1, sh, sw, 1), pad)
    if node.op == "avg_pool2d":
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, (1, kh, kw, 1), (1, sh, sw, 1), pad)
        y = y / counts
    return y


def _infer_flatten(node, ins):
    n = ins[0][0]
    rest = ins[0][1:]
    if any(d is None for d in rest):
        raise ValueError("flatten: non-batch dims must be static")
    return (n, int(np.prod(rest)) if rest else 1)


def _infer_reshape(node, ins):
    shape = list(node.attrs["shape"])
    # -1 in position 0 keeps the batch dim; a single other -1 is inferred.
    in_shape = ins[0]
    known = [d for d in in_shape if d is not None]
    out = []
    for i, d in enumerate(shape):
        if d == -1 and i == 0:
            out.append(in_shape[0])
        elif d == -1:
            out.append(None)  # resolved at eval time
        else:
            out.append(int(d))
    # try to resolve inner -1 statically
    if None not in in_shape:
        total = int(np.prod(in_shape))
        fixed = int(np.prod([d for d in out if d is not None])) or 1
        out = [d if d is not None else total // fixed for d in out]
    return tuple(out)


def _eval_reshape(node, ins, ctx):
    shape = [int(d) for d in node.attrs["shape"]]
    x = ins[0]
    if shape.count(-1) > 1:
        # a leading -1 means "keep the batch dim"; resolve it so at most one
        # unknown remains for jnp.reshape
        shape[0] = x.shape[0]
    return jnp.reshape(x, tuple(shape))


def _infer_elementwise(node, ins):
    return ins[0]


def _infer_broadcast(node, ins):
    """Numpy-style broadcast of input shapes (None dims stay None)."""
    out: List[Optional[int]] = []
    rank = max(len(s) for s in ins)
    shapes = [(None,) * (rank - len(s)) + tuple(s) for s in ins]
    for dims in zip(*shapes):
        known = [d for d in dims if d is not None and d != 1]
        if known and any(k != known[0] for k in known):
            raise ValueError(f"{node.op} {node.name!r}: shapes {ins} do not broadcast")
        if known:
            out.append(known[0])
        elif all(d == 1 for d in dims):
            out.append(1)
        else:
            out.append(None)
    return tuple(out)


def _infer_argmax(node, ins):
    ax = node.attrs.get("axis", 1)
    s = list(ins[0])
    del s[ax]
    return tuple(s)


def _infer_matmul(node, ins):
    return tuple(ins[0][:-1]) + (ins[1][-1],)


def _infer_concat(node, ins):
    ax = node.attrs.get("axis", -1)
    s = list(ins[0])
    ax = ax if ax >= 0 else len(s) + ax
    dims = [i[ax] for i in ins]
    s[ax] = None if any(d is None for d in dims) else sum(dims)
    return tuple(s)


def _infer_loss(node, ins):
    return (ins[0][0],)  # per-example vector


def _params_layer_norm(node, ins):
    d = ins[0][-1]
    return {"scale": ((d,), "ones"), "bias": ((d,), "zeros")}


def _eval_batch_norm(node, ins, ctx, p):
    """Batch-statistics normalization over all non-channel axes + learned
    scale/shift. Deliberately stateless (no running averages): moving stats are
    cross-step mutable state that breaks pure-functional training; for
    train/serve parity prefer layer_norm/group_norm (what the zoo models use)."""
    x = ins[0].astype(jnp.float32)
    eps = node.attrs.get("epsilon", 1e-5)
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return _cast(y, ctx.compute_dtype)


def _eval_layer_norm(node, ins, ctx, p):
    x = ins[0].astype(jnp.float32)
    eps = node.attrs.get("epsilon", 1e-6)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"] + p["bias"]
    return _cast(y, ctx.compute_dtype)


def _params_embedding(node, ins):
    return {"embedding": ((node.attrs["vocab_size"], node.attrs["dim"]),
                          node.attrs.get("init", "normal(0.02)"))}


def _eval_dropout(node, ins, ctx):
    x = ins[0]
    if len(node.inputs) > 1:
        rate = ins[1]
    else:
        rate = node.attrs.get("rate", 0.5)
    mode = node.attrs.get("mode", "keep")  # 'keep': rate = keep-prob (tf.nn.dropout TF1)
    keep = rate if mode == "keep" else 1.0 - rate
    if not ctx.train:
        return x
    keep = jnp.asarray(keep, jnp.float32)

    def apply_drop(x):
        mask = jax.random.bernoulli(ctx.next_rng(), jnp.maximum(keep, 1e-8), x.shape)
        return jnp.where(mask, x / jnp.maximum(keep, 1e-8), jnp.zeros_like(x))

    # keep == 1.0 -> identity; jnp.where keeps it jittable for traced keep values
    dropped = apply_drop(x)
    return jnp.where(keep >= 1.0, x, dropped)


# Per-example losses (reduced over feature axes only; batch axis preserved so
# the trainer can mask padded rows).

def _eval_softmax_ce(node, ins, ctx):
    labels, logits = ins[0].astype(jnp.float32), ins[1].astype(jnp.float32)
    logz = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(labels * logz, axis=tuple(range(1, logits.ndim)))


def _eval_sigmoid_ce(node, ins, ctx):
    labels, logits = ins[0].astype(jnp.float32), ins[1].astype(jnp.float32)
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.mean(per, axis=tuple(range(1, logits.ndim)))


def _eval_mse(node, ins, ctx):
    a, b = ins[0].astype(jnp.float32), ins[1].astype(jnp.float32)
    per = jnp.square(a - b)
    return jnp.mean(per, axis=tuple(range(1, per.ndim)))


def _eval_abs_diff(node, ins, ctx):
    a, b = ins[0].astype(jnp.float32), ins[1].astype(jnp.float32)
    per = jnp.abs(a - b)
    return jnp.mean(per, axis=tuple(range(1, per.ndim)))


def _eval_huber(node, ins, ctx):
    a, b = ins[0].astype(jnp.float32), ins[1].astype(jnp.float32)
    delta = node.attrs.get("delta", 1.0)
    err = a - b
    abs_err = jnp.abs(err)
    quad = jnp.minimum(abs_err, delta)
    lin = abs_err - quad
    per = 0.5 * quad * quad + delta * lin
    return jnp.mean(per, axis=tuple(range(1, per.ndim)))


def _eval_log_loss(node, ins, ctx):
    labels, preds = ins[0].astype(jnp.float32), ins[1].astype(jnp.float32)
    eps = 1e-7
    per = -labels * jnp.log(preds + eps) - (1 - labels) * jnp.log(1 - preds + eps)
    return jnp.mean(per, axis=tuple(range(1, per.ndim)))


_LOSS_EVALS = {
    "softmax_cross_entropy": _eval_softmax_ce,
    "sigmoid_cross_entropy": _eval_sigmoid_ce,
    "mean_squared_error": _eval_mse,
    "absolute_difference": _eval_abs_diff,
    "huber_loss": _eval_huber,
    "log_loss": _eval_log_loss,
}


class _OpDef:
    __slots__ = ("infer", "params", "eval")

    def __init__(self, infer, eval, params=None):
        self.infer = infer
        self.eval = eval
        self.params = params


def _simple_eval(fn):
    return lambda node, ins, ctx: fn(ins[0])


def _eval_placeholder(node, ins, ctx):
    if node.name in ctx.feeds:
        return ctx.feeds[node.name]
    if "default" in node.attrs:
        return jnp.asarray(node.attrs["default"],
                           dtype=node.attrs.get("dtype", "float32"))
    raise KeyError(f"placeholder {node.name!r} was not fed and has no default")


OPS: Dict[str, _OpDef] = {
    "placeholder": _OpDef(_infer_placeholder, _eval_placeholder),
    "constant": _OpDef(lambda n, i: tuple(np.asarray(n.attrs["value"]).shape),
                       lambda n, i, c: jnp.asarray(n.attrs["value"],
                                                   dtype=n.attrs.get("dtype", "float32"))),
    "dense": _OpDef(_infer_dense, None, _params_dense),
    "conv2d": _OpDef(_infer_conv2d, None, _params_conv2d),
    "max_pool2d": _OpDef(_infer_pool,
                         lambda n, i, c: _eval_pool(n, i, c, jax.lax.max, -jnp.inf)),
    "avg_pool2d": _OpDef(_infer_pool,
                         lambda n, i, c: _eval_pool(n, i, c, jax.lax.add, 0.0)),
    "flatten": _OpDef(_infer_flatten,
                      lambda n, i, c: jnp.reshape(i[0], (i[0].shape[0], -1))),
    "reshape": _OpDef(_infer_reshape, _eval_reshape),
    "dropout": _OpDef(_infer_elementwise, _eval_dropout),
    "argmax": _OpDef(_infer_argmax,
                     lambda n, i, c: jnp.argmax(i[0], axis=n.attrs.get("axis", 1)).astype(jnp.float32)),
    "add": _OpDef(_infer_broadcast, lambda n, i, c: i[0] + i[1]),
    "subtract": _OpDef(_infer_broadcast, lambda n, i, c: i[0] - i[1]),
    "multiply": _OpDef(_infer_broadcast, lambda n, i, c: i[0] * i[1]),
    "matmul": _OpDef(_infer_matmul,
                     lambda n, i, c: jnp.matmul(
                         _cast(i[0], c.compute_dtype), _cast(i[1], c.compute_dtype),
                         preferred_element_type=(jnp.float32 if c.compute_dtype is None
                                                 else None))),
    "concat": _OpDef(_infer_concat,
                     lambda n, i, c: jnp.concatenate(list(i), axis=n.attrs.get("axis", -1))),
    "layer_norm": _OpDef(_infer_elementwise, None, _params_layer_norm),
    "batch_norm": _OpDef(_infer_elementwise, None, _params_layer_norm),
    "embedding": _OpDef(lambda n, i: tuple(i[0]) + (n.attrs["dim"],), None, _params_embedding),
}

OPS["dense"].eval = _eval_dense
OPS["conv2d"].eval = _eval_conv2d
OPS["layer_norm"].eval = _eval_layer_norm
OPS["batch_norm"].eval = _eval_batch_norm
OPS["embedding"].eval = lambda n, i, c, p: jnp.take(p["embedding"], i[0].astype(jnp.int32), axis=0)

for _name, _act in _ACTIVATIONS.items():
    if _name == "identity":
        continue
    OPS[_name] = _OpDef(_infer_elementwise, _simple_eval(_act))

for _name, _fn in _LOSS_EVALS.items():
    OPS[_name] = _OpDef(_infer_loss, _fn)

PARAM_OPS = {name for name, od in OPS.items() if od.params is not None}
LOSS_OPS = set(_LOSS_EVALS)


# ---------------------------------------------------------------------------
# GraphModel: executable init/apply derived from a GraphDef
# ---------------------------------------------------------------------------


class GraphModel:
    """Executable form of a :class:`GraphDef`: pure ``init``/``apply``.

    ``init(rng)`` returns a params pytree ``{layer_name: {param_name: array}}``
    in node order (this ordering defines the flat-weight-list compatibility with
    the reference's ``tf.trainable_variables`` list,
    ``sparkflow/ml_util.py:9-13``).

    ``apply(params, feeds, outputs=[...])`` evaluates only the subgraph needed
    for the requested tensors — the analog of fetching named tensors from a
    ``tf.Session`` (``sparkflow/ml_util.py:65-73``) but pure and jittable.
    """

    def __init__(self, graphdef: GraphDef, compute_dtype: Optional[Any] = None):
        self.graphdef = graphdef
        self.compute_dtype = compute_dtype
        # int8 serving (utils/quant.py): apply() consumes quantized trees when
        # present; 'dynamic' additionally routes dense matmuls through the
        # int8 MXU path. Set via quantize_for_serving() or directly.
        self.quant_mode: Optional[str] = None
        self._shapes: Dict[int, Shape] = {}
        self._infer_shapes()

    @staticmethod
    def from_json(s: str, compute_dtype: Optional[Any] = None) -> "GraphModel":
        return GraphModel(GraphDef.from_json(s), compute_dtype)

    # -- shapes -------------------------------------------------------------

    def _infer_shapes(self):
        for node in self.graphdef.nodes:
            od = OPS.get(node.op)
            if od is None:
                raise ValueError(f"unknown op {node.op!r} (node {node.name!r})")
            in_shapes = [self._shapes[i] for i in node.inputs]
            self._shapes[node.id] = od.infer(node, in_shapes)

    def tensor_shape(self, tensor_name: str) -> Shape:
        return self._shapes[self.graphdef.resolve(tensor_name)]

    def input_specs(self) -> Dict[str, Tuple[Shape, str]]:
        return {n.name: (tuple(n.attrs["shape"]), n.attrs.get("dtype", "float32"))
                for n in self.graphdef.placeholders()}

    # -- params -------------------------------------------------------------

    def param_specs(self) -> Dict[str, Dict[str, Tuple[Shape, str]]]:
        specs = {}
        for node in self.graphdef.nodes:
            od = OPS[node.op]
            if od.params is not None:
                in_shapes = [self._shapes[i] for i in node.inputs]
                specs[node.name] = od.params(node, in_shapes)
        return specs

    def init(self, rng) -> Dict[str, Dict[str, jax.Array]]:
        params = {}
        for lname, pspec in self.param_specs().items():
            layer = {}
            for pname, (shape, init_name) in pspec.items():
                rng, sub = jax.random.split(rng)
                init_fn = _get_initializer(init_name)
                layer[pname] = init_fn(sub, shape, jnp.float32)
            params[lname] = layer
        return params

    # -- apply --------------------------------------------------------------

    def _needed(self, targets: Sequence[int]) -> List[Node]:
        need = set()
        stack = list(targets)
        while stack:
            nid = stack.pop()
            if nid in need:
                continue
            need.add(nid)
            stack.extend(self.graphdef.nodes[nid].inputs)
        return [n for n in self.graphdef.nodes if n.id in need]

    def apply(self, params, feeds: Dict[str, Any], outputs: Sequence[str],
              train: bool = False, rng=None) -> Dict[str, jax.Array]:
        """Evaluate the graph. ``feeds`` keys may use ':0' suffixes; so may outputs."""
        norm_feeds = {k.split(":")[0]: v for k, v in feeds.items()}
        target_ids = [o if isinstance(o, int) else self.graphdef.resolve(o)
                      for o in outputs]
        ctx = _EvalCtx(params, norm_feeds, train, rng, self.compute_dtype,
                       self.quant_mode)
        values: Dict[int, Any] = {}
        for node in self._needed(target_ids):
            od = OPS[node.op]
            ins = [values[i] for i in node.inputs]
            if od.params is not None:
                values[node.id] = od.eval(node, ins, ctx, params[node.name])
            else:
                values[node.id] = od.eval(node, ins, ctx)
        return {o: values[t] for o, t in zip(outputs, target_ids)}

    def quantize_for_serving(self, params, mode: str = "weight_only",
                             min_size: int = 4096):
        """int8-quantize a trained params tree for inference and set this
        model to serve it (``utils/quant.py``). Returns the quantized tree;
        training must keep the original full-precision params."""
        from .utils.quant import quantize_for_serving
        return quantize_for_serving(self, params, mode, min_size)

    def loss_vector(self, params, feeds: Dict[str, Any], train: bool = True,
                    rng=None) -> jax.Array:
        """Per-example total loss (sum of registered losses), shape [batch]."""
        if not self.graphdef.losses:
            raise ValueError("graph has no registered losses; use a loss op from "
                             "sparkflow_tpu.nn (softmax_cross_entropy, mean_squared_error, ...)")
        outs = self.apply(params, feeds, self.graphdef.losses, train=train, rng=rng)
        vals = list(outs.values())
        total = vals[0]
        for v in vals[1:]:
            total = total + v
        return total


# ---------------------------------------------------------------------------
# Flat weight-list compatibility helpers
# ---------------------------------------------------------------------------


def params_to_list(model: GraphModel, params: Dict[str, Dict[str, Any]]) -> List[np.ndarray]:
    """Flatten params to a list of arrays in graph-node (creation) order — the
    analog of the reference's ``tf.trainable_variables`` weight list
    (``sparkflow/ml_util.py:9-13``). Order comes from the model's param specs,
    NOT dict iteration order: ``jax.tree`` ops rebuild dicts with sorted keys,
    so insertion order is not stable across optimizer updates."""
    out = []
    for lname, pspec in model.param_specs().items():
        for pname in pspec:
            out.append(np.asarray(params[lname][pname]))
    return out


def list_to_params(model: GraphModel, weights: Sequence[np.ndarray]):
    specs = model.param_specs()
    needed = sum(len(p) for p in specs.values())
    if needed != len(weights):
        raise ValueError(f"weight list has {len(weights)} arrays; model needs {needed}")
    params = {}
    i = 0
    for lname, pspec in specs.items():
        layer = {}
        for pname, (shape, _init) in pspec.items():
            w = jnp.asarray(weights[i])
            if tuple(w.shape) != tuple(shape):
                raise ValueError(f"weight {i} for {lname}/{pname} has shape "
                                 f"{tuple(w.shape)}, expected {tuple(shape)}")
            layer[pname] = w
            i += 1
        params[lname] = layer
    return params
