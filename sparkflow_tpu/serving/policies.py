# graftcheck: pure-policy
"""Pure fleet policies: every routing/health/gate *decision*, no transport.

The fleet-scale simulator (:mod:`sparkflow_tpu.sim`) replays million-request
traces against the SAME policy code the live router runs — which is only
sound if the policies are deterministic functions of observed state. This
module is that contract, enforced by graftcheck rule **GC-S501**
(impure-policy): nothing here may read a wall clock, draw randomness, sleep,
or touch sockets/files. Time arrives as a ``now`` argument; randomness
arrives pre-drawn (``prefer_canary`` is a bool the caller rolled); state
arrives as frozen snapshots (:class:`ReplicaView`, :class:`VersionStats`).

The serving plane (``membership.py`` / ``router.py``) and the simulator
(``sim/core.py``) both call these functions — the HTTP stack supplies
``time.monotonic`` snapshots and live counters, the simulator supplies a
virtual clock and modelled replicas, and the decisions are identical by
construction (pinned by the parity tests in ``tests/test_policies.py``).

Decisions covered
-----------------
- :func:`pick_order` / :func:`predict_pick_key` / :func:`generate_pick_key`
  — least-loaded replica ranking, with the least-served tie-break
  (equal-load ties go to the replica with the fewest cumulative dispatches
  instead of always the lowest index — the bias the deterministic replay
  exposed) and the **inflight-debited byte-headroom** generate rule that
  predicts KV exhaustion from stale probe reports before the replica
  sheds (found in sim, confirmed by ``bench.py --sim``).
- :func:`classify_outcome` — what one dispatch outcome means: success,
  eject-and-reroute (draining), reroute-without-breaker (overload),
  breaker-feeding failure (5xx/wire error), or authoritative client error.
- :func:`canary_gate` / :func:`canary_reorder` — the promote/rollback/
  continue verdict over per-version stats and the version-aware reorder of
  a load-sorted candidate list.
- :func:`token_bucket_admit` — the admission refill/spend arithmetic.
- :func:`probe_is_stale` — whether a replica's load report is too old to
  trust (its decision half lives here; reading the clock stays the
  caller's job).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ReplicaView", "VersionStats", "OUTCOME_SUCCESS", "OUTCOME_EJECT",
    "OUTCOME_REROUTE", "OUTCOME_FAILURE", "OUTCOME_CLIENT_ERROR",
    "GATE_CONTINUE", "GATE_PROMOTE", "GATE_ROLLBACK",
    "predict_pick_key", "generate_pick_key", "pick_order",
    "classify_outcome", "canary_gate", "canary_reorder",
    "token_bucket_admit", "probe_is_stale", "percentile_nearest_rank",
]


@dataclass(frozen=True)
class ReplicaView:
    """Frozen snapshot of one replica's observed state — the ONLY replica
    shape policies see. ``Membership`` builds these under its lock from
    live :class:`~sparkflow_tpu.serving.membership.Replica` records; the
    simulator builds them from modelled replicas."""

    index: int
    healthy: bool = True
    inflight: int = 0
    queue_depth: int = 0
    decode_free_slots: int = -1
    decode_pages_free: int = -1
    kv_bytes_per_page: int = -1
    version: int = -1
    dispatched: int = 0  # cumulative dispatches ever sent to this replica

    @property
    def free_kv_bytes(self) -> int:
        """Effective decode byte headroom: pages_free weighted by the
        replica's bytes-per-page (unknown byte figure weights 1, so a fleet
        that never reports bytes ranks by raw pages exactly as before)."""
        if self.decode_pages_free <= 0:
            return self.decode_pages_free
        bpp = self.kv_bytes_per_page if self.kv_bytes_per_page > 0 else 1
        return self.decode_pages_free * bpp


def predict_pick_key(view: ReplicaView) -> Tuple:
    """Sort key for predict dispatch: router-side in-flight, then the
    replica-reported queue depth, then the **least-served** tie-break
    (cumulative dispatches, then index).

    The old tie-break was the bare index: an idle or perfectly balanced
    fleet sent EVERY tied pick to replica 0 — deterministic replay in the
    simulator showed replica 0 absorbing the whole head of each burst
    while the tail idled. Tie-breaking on the cumulative dispatch count is
    self-balancing (the tied replica that has served least wins, and
    serving bumps its count past its peers), deterministic, and — unlike a
    rotating counter — a pure function of the view, so an incremental
    argmin structure (the simulator's lazy heap) only re-keys the one
    replica that changed."""
    return (view.inflight, view.queue_depth, view.dispatched, view.index)


# Pages one live stream is assumed to consume beyond the last probe
# report (the debit below). 32 pages x 16-token pages = a ~512-token
# prompt+completion — the workload median, not the tail; the debit is a
# steering signal, the replica's own admission is the hard limit.
EST_PAGES_PER_STREAM = 32


def generate_pick_key(view: ReplicaView,
                      est_pages_per_stream: int = EST_PAGES_PER_STREAM
                      ) -> Tuple:
    """Sort key for generate (decode) dispatch: least-loaded with
    **inflight-debited byte headroom**.

    Ranks by (starved, inflight, -effective-free-bytes, least-served
    tie) — queue depth is deliberately NOT a generate signal (the decode
    plane's own slot/page figures say more than the predict-plane queue)
    — where the effective headroom debits the *stale* probe report by
    the router's *live* in-flight count:

    ``eff_pages = decode_pages_free - est_pages_per_stream * inflight``

    - ``starved``: zero free pages or slots — or an effective headroom
      debited to <= 0 — sorts last outright (still dispatchable as a
      final resort: the replica's own 503 is the real backpressure).
    - The probe report is up to a probe interval old; every dispatch the
      router sent since then is eating pages the report still shows as
      free. Deterministic trace replay in the simulator showed the
      undebited rule happily piling bursts onto replicas whose pools had
      already paged out, then paying a queue_full reroute storm per
      burst; the debit predicts exhaustion *before* the replica sheds
      (sim: fewer queue_full reroutes and 30-70% lower p95 across
      homogeneous and mixed-pool fleets; confirmed real by
      ``bench.py --sim``).
    - ``-eff_bytes`` (debited pages weighted by the replica's
      ``kv_bytes_per_page``) breaks equal-inflight ties toward the pool
      with the most remaining capacity, so heterogeneous bf16/int8
      fleets fill proportionally.
    - Replicas with unknown headroom (no decode plane probed yet) keep
      their raw figure as the tie value — after known-positive headroom
      at equal load, exactly as before.
    """
    starved = 1 if (view.decode_pages_free == 0
                    or view.decode_free_slots == 0) else 0
    pages = view.decode_pages_free
    if pages > 0:
        eff = pages - est_pages_per_stream * view.inflight
        if eff <= 0:
            starved = 1
        bpp = (view.kv_bytes_per_page if view.kv_bytes_per_page > 0
               else 1)
        eff_bytes = eff * bpp
    else:
        eff_bytes = pages   # unknown (-1) / zero: passthrough, as before
    return (starved, view.inflight, -eff_bytes, view.dispatched,
            view.index)


def pick_order(views: Sequence[ReplicaView], signal: str = "predict"
               ) -> List[int]:
    """Full dispatch preference order (healthy views only) as a list of
    ``view.index`` values, best first. The caller walks it until a breaker
    admits one — breaker state is live/mutable, so consulting it stays
    outside the pure layer."""
    key = generate_pick_key if signal == "generate" else predict_pick_key
    return [v.index for v in sorted((v for v in views if v.healthy),
                                    key=key)]


# -- dispatch-outcome classification -----------------------------------------

OUTCOME_SUCCESS = "success"            # 200: record_success
OUTCOME_EJECT = "eject"                # draining 503: eject now, reroute
OUTCOME_REROUTE = "reroute"            # overload 503: reroute, no breaker
OUTCOME_FAILURE = "failure"            # 5xx / wire error: feed the breaker
OUTCOME_CLIENT_ERROR = "client_error"  # 4xx: authoritative, pass through


def classify_outcome(status: Optional[int], error_code: str = "",
                     wire_error: bool = False) -> str:
    """What one dispatch outcome means for membership/retry bookkeeping.

    ``status`` is the HTTP status (None with ``wire_error=True`` for a
    connection-level failure), ``error_code`` the structured error code
    from the body. The verdicts map 1:1 onto the router's historical
    behavior: draining 503s eject immediately; queue_full 503s reroute
    without feeding the breaker (overloaded, not broken — least-loaded
    pick already steers away); other 5xx and wire errors count against
    the breaker; 4xx is the client's problem."""
    if wire_error:
        return OUTCOME_FAILURE
    if status == 200:
        return OUTCOME_SUCCESS
    if status == 503 and error_code == "draining":
        return OUTCOME_EJECT
    if status == 503:
        return OUTCOME_REROUTE
    if status is None or status >= 500:
        return OUTCOME_FAILURE
    return OUTCOME_CLIENT_ERROR


# -- canary gate -------------------------------------------------------------

GATE_CONTINUE = "continue"
GATE_PROMOTE = "promote"
GATE_ROLLBACK = "rollback"


@dataclass(frozen=True)
class VersionStats:
    """Per-version outcome counters the canary gate judges over."""

    requests: int = 0
    errors: int = 0
    nans: int = 0
    latencies_ms: Tuple[float, ...] = field(default_factory=tuple)

    @property
    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    @property
    def latency_p95(self) -> float:
        return percentile_nearest_rank(self.latencies_ms, 95.0)


def percentile_nearest_rank(samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile matching the canary gate's historical p95
    (``sorted[min(n-1, round(q/100 * (n-1)))]``); 0.0 on no samples."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))]


def canary_gate(canary: VersionStats, incumbent: Optional[VersionStats], *,
                min_requests: int, error_rate_margin: float,
                latency_factor: float, latency_floor_ms: float
                ) -> Tuple[str, str]:
    """Judge a canary version against the incumbent: ``(verdict, reason)``
    where verdict is GATE_CONTINUE / GATE_PROMOTE / GATE_ROLLBACK.

    The order of checks is the contract (pinned by the parity tests):
    any NaN/Inf rolls back instantly; before ``min_requests`` the trial
    continues; an error rate exceeding the incumbent's by more than
    ``error_rate_margin`` rolls back; a latency p95 above
    ``max(latency_floor_ms, latency_factor x incumbent p95)`` rolls back
    (skipped while the incumbent has no latency history); otherwise the
    canary promotes."""
    if canary.nans:
        return GATE_ROLLBACK, "NaN/Inf outputs"
    if canary.requests < min_requests:
        return GATE_CONTINUE, (f"{canary.requests}/{min_requests} "
                               f"requests observed")
    inc_err = incumbent.error_rate if incumbent is not None else 0.0
    err = canary.error_rate
    if err > inc_err + error_rate_margin:
        return GATE_ROLLBACK, (f"error rate {err:.3f} vs incumbent "
                               f"{inc_err:.3f}")
    inc_p95 = incumbent.latency_p95 if incumbent is not None else 0.0
    if inc_p95 > 0.0:
        p95 = canary.latency_p95
        bar = max(latency_floor_ms, latency_factor * inc_p95)
        if p95 > bar:
            return GATE_ROLLBACK, f"latency p95 {p95:.1f}ms > {bar:.1f}ms"
    return GATE_PROMOTE, "healthy at min_requests"


def canary_reorder(indices: Sequence[int], versions: Dict[int, int],
                   canary: Optional[int], quarantined: frozenset,
                   prefer_canary: bool) -> List[int]:
    """Version-aware reorder of a load-sorted candidate list (indices into
    the fleet, best first). Quarantined versions are dropped outright —
    zero post-gate traffic, an all-quarantined fleet yields ``[]`` and the
    router 503s rather than serve bad weights. With a canary under trial,
    ``prefer_canary`` (the caller's pre-drawn ~``canary_fraction`` coin)
    puts the canary group first, else last; relative load order inside
    each group is preserved."""
    live = [i for i in indices if versions.get(i, -1) not in quarantined]
    if canary is None:
        return live
    cgroup = [i for i in live if versions.get(i, -1) == canary]
    rest = [i for i in live if versions.get(i, -1) != canary]
    if not cgroup or not rest:
        return live
    return cgroup + rest if prefer_canary else rest + cgroup


# -- admission ---------------------------------------------------------------

def token_bucket_admit(tokens: float, last: float, now: float, *,
                       rate: float, burst: float, n: float = 1.0
                       ) -> Tuple[bool, float, float]:
    """One token-bucket admission decision: refill from ``last`` to ``now``
    at ``rate`` (capped at ``burst``), spend ``n`` if available. Returns
    ``(admitted, tokens_after, now)`` — the caller stores the last two as
    the bucket's new state under its own lock."""
    tokens = min(burst, tokens + (now - last) * rate)
    if tokens >= n:
        return True, tokens - n, now
    return False, tokens, now


# -- probe staleness ---------------------------------------------------------

def probe_is_stale(last_probe_t: float, now: float,
                   probe_interval_s: float, factor: float = 3.0) -> bool:
    """Is a replica's probed load report too old to trust? True once the
    report is older than ``factor`` probe intervals (a wedged prober must
    not freeze stale 'idle' load figures into the pick forever). A replica
    never probed (``last_probe_t <= 0``) is not stale — optimistic until
    the first report, matching the historical bootstrap behavior."""
    if last_probe_t <= 0.0:
        return False
    return (now - last_probe_t) > factor * probe_interval_s
