"""Deterministic discrete-event fleet simulator.

Replays a request trace (:mod:`sparkflow_tpu.sim.trace`) against a
simulated fleet whose *decisions* come from the exact policy code the
real serving plane runs — :mod:`sparkflow_tpu.serving.policies` for
pick order / outcome classification / staleness, the real
:class:`~sparkflow_tpu.serving.membership.CircuitBreaker` and
:class:`~sparkflow_tpu.serving.router.TokenBucket` (both on the
simulator's virtual clock), the real
:class:`~sparkflow_tpu.serving.router.CanaryController` when canary
dispatch is on, and the real
:class:`~sparkflow_tpu.resilience.retry.RetryPolicy` backoff schedule.
Only *transport and compute* are simulated: instead of HTTP and a TPU,
each replica prices its work with a :class:`~sparkflow_tpu.sim.costmodel.
CostModel` fitted from bench measurements. That separation is the whole
design — a policy bug found here is a policy bug in production code, not
in a reimplementation.

Determinism contract: one ``seed`` drives every random draw (canary
coin, retry jitter), the event heap breaks time ties with a monotone
sequence number, and no wall-clock value is ever read. Same trace + same
fleet + same seed => byte-identical event log (asserted via the running
sha256 ``digest`` in :class:`SimReport`, which is computed even when
per-event records are not retained).

Scale: picks use a lazy min-heap over the pure pick keys rather than the
O(n log n) full sort the real router can afford at its fleet sizes. The
least-served tie-break in ``policies`` makes every key a function of one
replica's state alone, so each dispatch/finish/probe invalidates exactly
one heap entry — 1000 replicas x 1M requests runs in seconds. A parity
test pins heap-argmin == ``policies.pick_order(...)[0]``; canary runs
use the full sort + real ``filter_replicas`` path (canary fleets are
small).

Reported vs true state mirrors production: the pick sees each replica's
*last probe report* (queue depth, free slots, free pages refreshed every
``probe_interval_s``, staggered per replica) plus the router-side live
``inflight`` counter — never the replica's instantaneous truth. Routing
pathologies caused by stale load reports reproduce here for free.
"""

from __future__ import annotations

import hashlib
import heapq
import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..resilience.retry import RetryPolicy
from ..serving import policies
from ..serving.membership import BreakerState, CircuitBreaker
from ..serving.policies import ReplicaView
from ..serving.router import CanaryController, TokenBucket

__all__ = ["ReplicaSpec", "SimReplica", "SimReport", "FleetSimulator",
           "SimAutoscaler", "legacy_generate_pick_key"]

# event kinds (ints: compared only via the heap's (t, seq) prefix)
_ARRIVE, _PROBE, _FINISH, _RETRY, _CHAOS, _SCALE, _SPAWN = range(7)


def legacy_generate_pick_key(view: ReplicaView) -> Tuple:
    """The pre-debit generate pick rule, kept for what-if A/B runs.

    Trusts the probe's ``decode_pages_free`` figure as-is. That report is
    up to a probe interval stale, so during a burst this rule keeps
    dispatching to replicas whose pools already paged out and pays a
    queue_full reroute storm once they shed — the failure mode the
    simulator surfaced and the inflight debit in
    ``policies.generate_pick_key`` fixes (see ``docs/sim.md``).
    """
    starved = 1 if (view.decode_pages_free == 0
                    or view.decode_free_slots == 0) else 0
    return (starved, view.inflight, -view.free_kv_bytes,
            view.dispatched, view.index)


@dataclass(frozen=True)
class SimAutoscaler:
    """Elastic-fleet hook for :class:`FleetSimulator`: runs the REAL
    :func:`sparkflow_tpu.serving.policies.scale_decision` on the virtual
    clock, so a :class:`~sparkflow_tpu.serving.policies.ScaleTargets`
    candidate is A/B-tuned against deterministic traffic steps before the
    live :class:`~sparkflow_tpu.serving.autoscaler.Autoscaler` ever spawns
    a process.

    ``specs`` passed to the simulator describe the *physical pool* (the
    machines the fleet could occupy); ``initial`` of them start live and
    ``targets.max_replicas`` bounds growth. ``spawn_delay_s`` models
    boot-to-serving time — the quantity the zero-compile cold start
    attacks, and exactly what makes a sluggish policy visible: capacity
    ordered at the band edge arrives ``spawn_delay_s`` late."""

    targets: policies.ScaleTargets = field(
        default_factory=policies.ScaleTargets)
    initial: int = 1
    decide_interval_s: float = 1.0
    spawn_delay_s: float = 2.0
    queue_wait_window: int = 256   # samples in the rolling p95 window


@dataclass(frozen=True)
class ReplicaSpec:
    """Static description of one simulated replica."""

    slots: int = 8                    # concurrent decode lanes / predict
    pages_total: int = 4096           # KV pool size, pages
    kv_bytes_per_page: int = 1 << 20  # pool bytes one page costs
    version: int = 0                  # live-weight version it serves
    speed: float = 1.0                # service-time divisor (hetero rigs)


class SimReplica:
    """Mutable per-replica simulation state (truth + last probe report)."""

    __slots__ = ("index", "spec", "up", "probe_healthy", "probe_misses",
                 "inflight", "active", "pages_free", "queue", "running",
                 "epoch", "reported_queue_depth", "reported_free_slots",
                 "reported_pages_free", "last_probe_t", "dispatched",
                 "completed", "busy_s", "breaker", "version",
                 "_breaker_state", "in_fleet", "draining")

    def __init__(self, index: int, spec: ReplicaSpec,
                 clock: Callable[[], float],
                 failure_threshold: int, recovery_s: float):
        self.index = index
        self.spec = spec
        self.up = True                 # chaos truth
        self.probe_healthy = True      # router's belief
        self.probe_misses = 0          # consecutive failed probes
        self.inflight = 0              # router-side live counter
        self.active = 0                # lanes busy (replica truth)
        self.pages_free = spec.pages_total
        self.queue: deque = deque()    # rids waiting for a lane
        self.running: Dict[int, int] = {}   # rid -> pages pinned
        self.epoch = 0                 # bumped on chaos kill
        self.reported_queue_depth = 0
        self.reported_free_slots = spec.slots
        self.reported_pages_free = spec.pages_total
        self.last_probe_t = 0.0
        self.dispatched = 0
        self.completed = 0
        self.busy_s = 0.0
        self.version = spec.version
        self.in_fleet = True           # registered with the router
        self.draining = False          # scale-down in progress
        self.breaker = CircuitBreaker(failure_threshold=failure_threshold,
                                      recovery_s=recovery_s, clock=clock)
        self._breaker_state = BreakerState.CLOSED

    def view(self) -> ReplicaView:
        """The pick's-eye view: last probe report + live inflight.

        Mirrors ``Membership.view_of``; probe staleness needs no runtime
        ``now`` here because a down replica fails its probe (-> excluded
        as unhealthy) before its report could go stale.
        """
        return ReplicaView(
            index=self.index, healthy=self.probe_healthy,
            inflight=self.inflight, queue_depth=self.reported_queue_depth,
            decode_free_slots=self.reported_free_slots,
            decode_pages_free=self.reported_pages_free,
            kv_bytes_per_page=self.spec.kv_bytes_per_page,
            version=self.version, dispatched=self.dispatched,
            probe_misses=self.probe_misses)


@dataclass
class SimReport:
    """Everything a run produced. ``digest`` is the sha256 of the full
    event stream (computed even when ``events`` retention is off)."""

    requests: int = 0
    completed: int = 0
    rejected: int = 0
    failed_dispatches: int = 0
    reroutes: int = 0
    queue_full: int = 0
    admission_rejects: int = 0
    breaker_transitions: int = 0
    canary_promotions: int = 0
    canary_rollbacks: int = 0
    scale_ups: int = 0          # scale-up decisions taken
    scale_downs: int = 0        # scale-down decisions taken
    replacements: int = 0       # crashed replicas respawned
    final_fleet_size: int = 0   # live replicas when the run ended
    sim_time_s: float = 0.0
    wall_s: float = 0.0
    ttft_p50_ms: float = 0.0
    ttft_p95_ms: float = 0.0
    latency_p50_ms: float = 0.0
    latency_p95_ms: float = 0.0
    throughput_rps: float = 0.0
    digest: str = ""
    per_replica: List[Dict[str, Any]] = field(default_factory=list)
    events: Optional[List[str]] = None
    latencies_ms: List[float] = field(default_factory=list)
    ttfts_ms: List[float] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d = {k: getattr(self, k) for k in (
            "requests", "completed", "rejected", "failed_dispatches",
            "reroutes", "queue_full", "admission_rejects",
            "breaker_transitions", "canary_promotions",
            "canary_rollbacks", "scale_ups", "scale_downs",
            "replacements", "final_fleet_size",
            "sim_time_s", "wall_s", "ttft_p50_ms",
            "ttft_p95_ms", "latency_p50_ms", "latency_p95_ms",
            "throughput_rps", "digest")}
        d["per_replica"] = self.per_replica
        return d


class FleetSimulator:
    """One simulation run: ``FleetSimulator(specs, trace, ...).run()``.

    Parameters
    ----------
    specs : sequence of ReplicaSpec
        The fleet. Heterogeneity (slots, pool size, bytes/page, speed)
        is the interesting case.
    trace : sequence of trace.Request
        The workload, sorted by arrival time.
    cost : CostModel
        Prices compute; see :mod:`sparkflow_tpu.sim.costmodel`.
    mode : "generate" | "predict"
        Which serving plane to model: paged-KV decode (TTFT + per-token)
        or flat-latency predict.
    pick_key : callable(ReplicaView) -> tuple, optional
        Override the pick policy for what-if runs (default: the real
        ``policies.generate_pick_key`` / ``predict_pick_key``).
    admission_rate / admission_burst : float, optional
        Wire a real ``TokenBucket`` (virtual clock) at the front door.
    canary : bool
        Route through a real ``CanaryController`` (full-sort pick path).
    chaos : sequence of (t, index, "down"|"up"|("version", v))
        Scheduled replica kills/recoveries/hot-swaps.
    record_events : bool
        Retain the event log lines in the report (the digest is always
        computed).
    """

    def __init__(self, specs: Sequence[ReplicaSpec], trace: Sequence,
                 cost, *, mode: str = "generate", seed: int = 0,
                 probe_interval_s: float = 2.0,
                 pick_key: Optional[Callable[[ReplicaView], Tuple]] = None,
                 admission_rate: Optional[float] = None,
                 admission_burst: Optional[float] = None,
                 canary: bool = False,
                 canary_kwargs: Optional[Dict[str, Any]] = None,
                 chaos: Sequence[Tuple] = (),
                 autoscaler: Optional[SimAutoscaler] = None,
                 max_attempts: int = 5,
                 failure_threshold: int = 3, recovery_s: float = 2.0,
                 record_events: bool = False):
        if mode not in ("generate", "predict"):
            raise ValueError(f"mode must be generate|predict, got {mode!r}")
        if not specs:
            raise ValueError("specs must describe at least one replica")
        self.mode = mode
        self.cost = cost
        self.seed = seed
        self.probe_interval_s = float(probe_interval_s)
        self.max_attempts = int(max_attempts)
        self._now = 0.0
        clock = lambda: self._now  # noqa: E731 - the virtual clock
        self.replicas = [SimReplica(i, s, clock, failure_threshold,
                                    recovery_s)
                         for i, s in enumerate(specs)]
        self.trace = list(trace)
        self._pick_key = pick_key or (
            policies.generate_pick_key if mode == "generate"
            else policies.predict_pick_key)
        self._custom_key = pick_key is not None
        self.bucket = None
        if admission_rate is not None:
            self.bucket = TokenBucket(admission_rate,
                                      burst=admission_burst, clock=clock)
        self.canary = None
        if canary:
            kw = dict(min_requests=20, seed=seed)
            kw.update(canary_kwargs or {})
            self.canary = CanaryController(**kw)
        self.retry = RetryPolicy(max_attempts=max_attempts, base_s=0.05,
                                 multiplier=2.0, max_s=1.0, jitter=0.5,
                                 seed=seed, clock=clock,
                                 sleep=lambda _s: None)
        self.chaos = sorted(chaos, key=lambda c: (c[0], c[1]))
        self.record_events = record_events
        # elastic-fleet hook: specs are the physical pool; replicas past
        # ``initial`` start deactivated and the real scale_decision (on
        # the virtual clock) activates/drains them
        self.autoscaler = autoscaler
        self._scale_state = policies.AutoscalerState(
            desired=autoscaler.initial if autoscaler else len(self.replicas))
        self._pending_spawn: set = set()
        self._wait_samples: deque = deque(
            maxlen=autoscaler.queue_wait_window if autoscaler else 256)
        if autoscaler is not None:
            if not 0 < autoscaler.initial <= len(self.replicas):
                raise ValueError("autoscaler.initial must be within the "
                                 "physical pool size")
            for r in self.replicas[autoscaler.initial:]:
                r.in_fleet = False
        # per-request mutable state
        n = len(self.trace)
        self._attempts = [0] * n
        self._t_first = [0.0] * n
        self._t_done = [0.0] * n
        self._pages = [0] * n
        # event machinery
        self._heap: List[Tuple] = []
        self._seq = 0
        self._hash = hashlib.sha256()
        self._events: List[str] = []
        # lazy pick heap: (key, index, stamp); stale stamps are skipped
        self._pick_heap: List[Tuple] = []
        self._stamp = [0] * len(self.replicas)
        self._probe_live = [False] * len(self.replicas)
        self.report = SimReport(requests=n)

    # -- event plumbing ----------------------------------------------------

    def _push(self, t: float, kind: int, a: int = 0, b: int = 0) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, a, b))

    def _log(self, line: str) -> None:
        rec = f"{self._now:.6f} {line}"
        self._hash.update(rec.encode())
        self._hash.update(b"\n")
        if self.record_events:
            self._events.append(rec)

    def _note_breaker(self, r: SimReplica) -> None:
        st = r.breaker.state
        if st is not r._breaker_state:
            self._log(f"breaker r{r.index} "
                      f"{r._breaker_state.value}->{st.value}")
            r._breaker_state = st
            self.report.breaker_transitions += 1

    def _reindex(self, r: SimReplica) -> None:
        """Refresh one replica's pick-heap entry (its key changed)."""
        i = r.index
        self._stamp[i] += 1
        if r.probe_healthy and r.in_fleet:
            heapq.heappush(self._pick_heap,
                           (self._pick_key(r.view()), i, self._stamp[i]))

    # -- pick --------------------------------------------------------------

    def _pick(self, exclude: frozenset) -> Optional[SimReplica]:
        """Heap-argmin pick: same order as ``policies.pick_order`` under
        the active key, then the real breaker walk."""
        if self.canary is not None:
            return self._pick_full_sort(exclude)
        heap, stamp = self._pick_heap, self._stamp
        setaside = []
        found = None
        while heap:
            entry = heap[0]
            key, i, stm = entry
            r = self.replicas[i]
            if stm != stamp[i] or not r.probe_healthy or not r.in_fleet:
                heapq.heappop(heap)      # stale, dead, or drained entry
                continue
            if i in exclude:
                setaside.append(heapq.heappop(heap))
                continue
            if r.breaker.allow():
                self._note_breaker(r)
                found = r
                break
            self._note_breaker(r)
            setaside.append(heapq.heappop(heap))
        for e in setaside:
            heapq.heappush(heap, e)
        return found

    def _pick_full_sort(self, exclude: frozenset) -> Optional[SimReplica]:
        """The real router's exact path: full policy sort + canary
        filter + breaker walk. Used when canary routing is on."""
        cand = [r for r in self.replicas
                if r.in_fleet and r.index not in exclude]
        views = [r.view() for r in cand]
        if self._custom_key:
            order = [v.index for v in sorted(
                (v for v in views if v.healthy), key=self._pick_key)]
        else:
            order = policies.pick_order(views, signal=self.mode)
        by_index = {r.index: r for r in cand}
        ordered = [by_index[i] for i in order]
        if self.canary is not None:
            ordered = self.canary.filter_replicas(
                ordered, lambda r: r.version)
        for r in ordered:
            ok = r.breaker.allow()
            self._note_breaker(r)
            if ok:
                return r
        return None

    # -- request lifecycle -------------------------------------------------

    def _try_dispatch(self, rid: int) -> None:
        """One client attempt: admission, then pick+dispatch with
        same-instant reroutes (the router's in-attempt walk), then
        backoff retry or terminal rejection."""
        req = self.trace[rid]
        if self.bucket is not None and not self.bucket.try_acquire():
            self.report.admission_rejects += 1
            self._log(f"admit_reject rid={rid}")
            self._backoff_or_reject(rid)
            return
        exclude = set()
        for _ in range(len(self.replicas)):
            r = self._pick(frozenset(exclude))
            if r is None:
                break
            verdict = self._dispatch(rid, req, r)
            if verdict is None:          # accepted (running or queued)
                return
            exclude.add(r.index)
            if verdict == policies.OUTCOME_REROUTE:
                self.report.reroutes += 1
            else:
                self.report.failed_dispatches += 1
        self._backoff_or_reject(rid)

    def _backoff_or_reject(self, rid: int) -> None:
        self._attempts[rid] += 1
        att = self._attempts[rid]
        if att >= self.max_attempts:
            self.report.rejected += 1
            self._log(f"reject rid={rid} attempts={att}")
            return
        delay = self.retry.backoff(att - 1)
        self._push(self._now + delay, _RETRY, rid)

    def _dispatch(self, rid: int, req, r: SimReplica) -> Optional[str]:
        """Send one request to one replica. Returns ``None`` when the
        replica accepted it, else the ``policies`` outcome verdict."""
        if not r.up:
            # wire error: the real router classifies this FAILURE and
            # records it on the breaker
            verdict = policies.classify_outcome("", wire_error=True)
            r.breaker.record_failure()
            self._note_breaker(r)
            self._log(f"dispatch_fail rid={rid} r{r.index} {verdict}")
            return verdict
        pages = 0
        if self.mode == "generate":
            pages = self.cost.pages_for(req.prompt_tokens,
                                        req.output_tokens)
            if pages > r.pages_free:
                # replica-side admission: queue_full 503 -> reroute,
                # breaker NOT recorded (backpressure is not ill health)
                verdict = policies.classify_outcome(503, "queue_full")
                self.report.queue_full += 1
                self._log(f"queue_full rid={rid} r{r.index}")
                return verdict
            r.pages_free -= pages
        r.inflight += 1
        r.dispatched += 1
        self._pages[rid] = pages
        self._log(f"dispatch rid={rid} r{r.index}")
        if r.active < r.spec.slots:
            self._start(rid, req, r)
        else:
            r.queue.append(rid)
        self._reindex(r)
        return None

    def _start(self, rid: int, req, r: SimReplica) -> None:
        """Begin service on a free lane; schedules the finish event."""
        # queue-wait sample: arrival -> service start, the autoscaler's
        # overload signal (covers replica queueing AND client retries)
        self._wait_samples.append((self._now - req.arrival_s) * 1e3)
        before = r.active
        r.active += 1
        speed = r.spec.speed
        if self.mode == "generate":
            ttft = self.cost.ttft_s(req.prompt_tokens, before,
                                    r.spec.slots) / speed
            dur = ttft + self.cost.decode_s(req.output_tokens, before,
                                            r.spec.slots) / speed
        else:
            dur = self.cost.predict_s(before, r.spec.slots) / speed
            ttft = dur
        self._t_first[rid] = self._now + ttft
        r.running[rid] = self._pages[rid]
        r.busy_s += dur
        self._push(self._now + dur, _FINISH, rid, r.index | (r.epoch << 32))

    def _finish(self, rid: int, packed: int) -> None:
        idx, epoch = packed & 0xFFFFFFFF, packed >> 32
        r = self.replicas[idx]
        if epoch != r.epoch:
            return                      # killed by chaos; already failed
        req = self.trace[rid]
        r.active -= 1
        r.inflight = max(0, r.inflight - 1)
        r.pages_free += r.running.pop(rid, 0)
        r.completed += 1
        self._t_done[rid] = self._now
        lat_ms = (self._now - req.arrival_s) * 1e3
        self.report.completed += 1
        self.report.latencies_ms.append(lat_ms)
        self.report.ttfts_ms.append(
            (self._t_first[rid] - req.arrival_s) * 1e3)
        r.breaker.record_success()
        self._note_breaker(r)
        if self.canary is not None:
            self.canary.observe(r.version, True, latency_ms=lat_ms)
        self._log(f"finish rid={rid} r{idx} lat_ms={lat_ms:.3f}")
        if r.queue:
            nxt = r.queue.popleft()
            self._start(nxt, self.trace[nxt], r)
        if r.draining and r.active == 0 and not r.queue:
            r.draining = False
            self._log(f"scale_down_complete r{idx}")
        self._reindex(r)

    # -- probes and chaos --------------------------------------------------

    def _probe(self, idx: int) -> None:
        r = self.replicas[idx]
        if not r.in_fleet:
            # deregistered (drained): the probe chain dies; a respawn
            # restarts it — mirrors Membership.deregister cancelling probes
            self._probe_live[idx] = False
            return
        if r.up:
            was = r.probe_healthy
            r.probe_healthy = True
            r.probe_misses = 0
            r.reported_queue_depth = len(r.queue)
            r.reported_free_slots = max(0, r.spec.slots - r.active)
            r.reported_pages_free = r.pages_free
            r.last_probe_t = self._now
            if not was:
                self._log(f"probe_recover r{idx}")
            self._reindex(r)
        else:
            if r.probe_healthy:
                self._log(f"probe_fail r{idx}")
            r.probe_healthy = False
            r.probe_misses += 1
            self._stamp[idx] += 1       # drop its pick-heap entry
        self._push(self._now + self.probe_interval_s, _PROBE, idx)

    def _chaos(self, idx: int, action) -> None:
        r = self.replicas[idx]
        if isinstance(action, tuple) and action[0] == "version":
            r.version = int(action[1])
            self._log(f"chaos r{idx} version={r.version}")
            self._reindex(r)
            return
        if action == "down":
            r.up = False
            r.epoch += 1
            self._log(f"chaos r{idx} down "
                      f"killed={len(r.running) + len(r.queue)}")
            victims = list(r.running) + list(r.queue)
            r.running.clear()
            r.queue.clear()
            r.active = 0
            r.inflight = 0
            r.pages_free = r.spec.pages_total
            for rid in victims:
                # each broken connection is a recorded failure, and the
                # client re-enters through the retry path
                r.breaker.record_failure()
                self._note_breaker(r)
                self.report.failed_dispatches += 1
                self._push(self._now + self.cost.net_rtt_ms / 1e3,
                           _RETRY, rid)
            # the router does NOT know yet: the replica stays pickable
            # (and fails at the wire, feeding the breaker) until its next
            # probe marks it unhealthy — exactly the production window
            self._reindex(r)
        elif action == "up":
            r.up = True
            self._log(f"chaos r{idx} up")
        else:
            raise ValueError(f"unknown chaos action {action!r}")

    # -- elastic scaling ---------------------------------------------------

    def _scale_tick(self) -> None:
        """One autoscaler decision on the virtual clock: build views of
        the registered fleet, run the REAL ``policies.scale_decision``,
        apply the action. Mirrors ``Autoscaler.tick``'s overlays: a
        breaker-OPEN replica is dead to the policy past the probe-miss
        debounce (detection at request cadence, not probe cadence), and a
        spawn already in flight counts as live-but-booting capacity — the
        real autoscaler spawns synchronously inside its tick, so without
        the synthetic view every tick during ``spawn_delay_s`` would
        re-order the same deficit and overshoot the target."""
        a = self.autoscaler
        views = []
        for r in self.replicas:
            if not r.in_fleet or r.index in self._pending_spawn:
                continue
            v = r.view()
            if r.breaker.state is BreakerState.OPEN:
                v = replace(v, healthy=False,
                            probe_misses=max(v.probe_misses,
                                             a.targets.dead_after_misses))
            views.append(v)
        for i in sorted(self._pending_spawn):
            spec = self.replicas[i].spec
            views.append(ReplicaView(
                index=i, healthy=True,
                decode_free_slots=spec.slots,
                decode_pages_free=spec.pages_total,
                kv_bytes_per_page=spec.kv_bytes_per_page))
        wait = (policies.percentile_nearest_rank(
                    list(self._wait_samples), 95.0)
                if self._wait_samples else None)
        action = policies.scale_decision(views, a.targets,
                                         self._scale_state, self._now,
                                         queue_wait_p95_ms=wait)
        self._scale_state = action.state
        if action.kind == policies.SCALE_REPLACE:
            for idx in action.targets:
                if idx in self._pending_spawn:
                    continue
                self._pending_spawn.add(idx)
                self.report.replacements += 1
                self._log(f"scale replace r{idx} ({action.reason})")
                self._push(self._now + a.spawn_delay_s, _SPAWN, idx)
        elif action.kind == policies.SCALE_UP:
            spare = [r.index for r in self.replicas
                     if not r.in_fleet and not r.draining
                     and r.index not in self._pending_spawn]
            took = spare[:action.count]
            if took:
                self.report.scale_ups += 1
                self._log(f"scale up +{len(took)} {took} "
                          f"({action.reason})")
            for idx in took:
                self._pending_spawn.add(idx)
                self._push(self._now + a.spawn_delay_s, _SPAWN, idx)
        elif action.kind == policies.SCALE_DOWN:
            self.report.scale_downs += 1
            for idx in action.targets:
                r = self.replicas[idx]
                r.in_fleet = False       # deregister: out of the pick now
                r.draining = r.active > 0 or bool(r.queue)
                self._stamp[idx] += 1    # drop its pick-heap entry
                self._log(f"scale down r{idx} draining={r.draining} "
                          f"({action.reason})")
        self._push(self._now + a.decide_interval_s, _SCALE)

    def _spawned(self, idx: int) -> None:
        """Spawn complete after ``spawn_delay_s``: the replica boots (or
        reboots, for a crash replacement) into a clean serving state and
        registers with the fleet."""
        r = self.replicas[idx]
        self._pending_spawn.discard(idx)
        r.up = True
        r.in_fleet = True
        r.draining = False
        r.probe_healthy = True
        r.probe_misses = 0
        # a replacement is a NEW process in production: its breaker starts
        # CLOSED, so the respawned slot must not stay dead to the policy
        r.breaker.record_success()
        self._note_breaker(r)
        r.active = 0
        r.inflight = 0
        r.queue.clear()
        r.running.clear()
        r.pages_free = r.spec.pages_total
        r.reported_queue_depth = 0
        r.reported_free_slots = r.spec.slots
        r.reported_pages_free = r.spec.pages_total
        r.last_probe_t = self._now
        self._log(f"spawned r{idx}")
        self._reindex(r)
        if not self._probe_live[idx]:
            self._probe_live[idx] = True
            self._push(self._now + self.probe_interval_s, _PROBE, idx)

    # -- run ---------------------------------------------------------------

    def run(self) -> SimReport:
        wall0 = time.monotonic()
        # prime: first probe per replica, staggered so reports do not
        # refresh in lockstep (mirrors independent probe loops)
        nrep = len(self.replicas)
        for r in self.replicas:
            if not r.in_fleet:
                continue                 # deactivated pool slot
            self._reindex(r)
            self._probe_live[r.index] = True
            self._push((r.index + 1) * self.probe_interval_s / (nrep + 1),
                       _PROBE, r.index)
        if self.autoscaler is not None:
            self._push(self.autoscaler.decide_interval_s, _SCALE)
        for rid, req in enumerate(self.trace):
            self._push(req.arrival_s, _ARRIVE, rid)
        for t, idx, action in self.chaos:
            self._seq += 1
            heapq.heappush(self._heap, (t, self._seq, _CHAOS, idx, action))
        heap = self._heap
        rep = self.report
        total = rep.requests
        while heap and rep.completed + rep.rejected < total:
            t, _seq, kind, a, b = heapq.heappop(heap)
            self._now = t
            if kind == _ARRIVE or kind == _RETRY:
                self._try_dispatch(a)
            elif kind == _FINISH:
                self._finish(a, b)
            elif kind == _PROBE:
                self._probe(a)
            elif kind == _CHAOS:
                self._chaos(a, b)
            elif kind == _SCALE:
                self._scale_tick()
            elif kind == _SPAWN:
                self._spawned(a)
        self._finalize(time.monotonic() - wall0)
        return self.report

    def _finalize(self, wall_s: float) -> None:
        rep = self.report
        rep.sim_time_s = self._now
        rep.wall_s = wall_s
        rep.final_fleet_size = sum(1 for r in self.replicas if r.in_fleet)
        lat = sorted(rep.latencies_ms)
        ttft = sorted(rep.ttfts_ms)
        rep.latency_p50_ms = policies.percentile_nearest_rank(lat, 50.0)
        rep.latency_p95_ms = policies.percentile_nearest_rank(lat, 95.0)
        rep.ttft_p50_ms = policies.percentile_nearest_rank(ttft, 50.0)
        rep.ttft_p95_ms = policies.percentile_nearest_rank(ttft, 95.0)
        if self._now > 0:
            rep.throughput_rps = rep.completed / self._now
        if self.canary is not None:
            stats = self.canary.stats()
            rep.canary_promotions = stats.get("promotions", 0)
            rep.canary_rollbacks = stats.get("rollbacks", 0)
        for r in self.replicas:
            util = (r.busy_s / (r.spec.slots * self._now)
                    if self._now > 0 else 0.0)
            rep.per_replica.append({
                "index": r.index, "dispatched": r.dispatched,
                "completed": r.completed, "busy_s": round(r.busy_s, 6),
                "utilization": round(util, 6),
                "kv_bytes_per_page": r.spec.kv_bytes_per_page,
                "pages_total": r.spec.pages_total,
                "breaker": r.breaker.state.value})
        rep.digest = self._hash.hexdigest()
        if self.record_events:
            rep.events = self._events
