"""int8 weight quantization for TPU inference.

A serving-side capability beyond the reference (which serves f32 through
``tf.Session``, ``sparkflow/ml_util.py:65-73``): quantize a trained params
tree to symmetric per-output-channel int8 and serve it through the same
``apply``/``predict_func`` paths. Two modes, both TPU-motivated:

- ``weight_only``: kernels stored int8 + per-channel f32 scale, dequantized
  to the compute dtype at the matmul. Halves the weight HBM traffic vs
  bf16 (4x vs f32) — the win for bandwidth-bound serving — with activations
  untouched, so accuracy loss is just the 8-bit weight rounding.
- ``dynamic``: activations additionally quantized per-row at runtime
  (dynamic absmax), and the matmul runs int8 x int8 -> int32 on the MXU's
  int8 path (2x the bf16 peak on v5e: 394 TOPS) before rescaling by
  ``row_scale x channel_scale``.

Quantization happens AFTER training/deserialization, on the serving side
(``quantize_params``); the stored model stays full-precision, so the wire
format (weights JSON / npz) and training are untouched.

The quantized tree swaps each selected ``kernel`` leaf for
``kernel_q8`` (int8) + ``kernel_scale`` (f32 per output channel); the
graphdef ``dense``/``conv2d`` evals check for the ``_q8`` form, so the whole
GraphModel serving surface (predict_func, SparkAsyncDLModel.transform,
predict_in_chunks) serves quantized trees unchanged. Conv kernels always
serve weight-only (int8 conv dot-generals lower poorly; the dequant fuses
into the conv anyway).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

MODES = ("weight_only", "dynamic")

#: pool layouts the paged KV cache can serve ("bf16" is the unquantized
#: compute-dtype pool; int8/fp8 store quantized rows + per-page-per-head
#: f32 scales). fp8 is gated on the installed jax/ml_dtypes exposing
#: float8_e4m3fn — no new dependency, just feature detection.
KV_DTYPES = ("bf16", "int8", "fp8")

_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)
#: symmetric quantization ceilings: int8 clips at +-127, e4m3 saturates
#: at +-448 (the format's largest finite value)
_KV_QMAX = {"int8": 127.0, "fp8": 448.0}


def quantize_tensor(w, axis: int = -1):
    """Symmetric per-channel int8: returns ``(q8, scale)`` with
    ``q8 * scale ~= w``; ``scale`` keeps ``w``'s rank with size-1 axes
    everywhere except ``axis`` (broadcasts back without reshapes)."""
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(a for a in range(w.ndim) if a != (axis % w.ndim))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_tensor(q8, scale, dtype=jnp.float32):
    return (q8.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(x, q8, scale):
    """``x @ dequant(q8)`` with the contraction in int8 x int8 -> int32.

    ``x`` [..., K] float; ``q8`` [K, N] int8; ``scale`` [1, N] (or [N]) f32.
    Activations quantize per-row (dynamic absmax over K). The int32
    accumulator rescales by ``row_scale * channel_scale``.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)        # [..., 1]
    xs = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, q8, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                        # [..., N]
    return acc.astype(jnp.float32) * xs * jnp.reshape(scale, (1,) * (acc.ndim - 1) + (-1,))


def quantized_dense(x, layer_params, mode: str = "weight_only",
                    compute_dtype=None, prefix: str = "kernel"):
    """Dense matmul over a possibly-quantized layer dict. Returns None when
    the layer is NOT quantized (caller runs its normal path). The mode is a
    property of the serving model (``quant_mode``), not the tree — the same
    quantized tree serves either mode. ``prefix`` selects the kernel within
    a multi-projection layer dict (e.g. 'qkv_kernel' in a transformer
    block); the bias is looked up as the matching ``*bias`` name."""
    if not isinstance(layer_params, dict) or f"{prefix}_q8" not in layer_params:
        return None
    q8 = layer_params[f"{prefix}_q8"]
    scale = layer_params[f"{prefix}_scale"]
    if mode == "dynamic" and q8.ndim == 2:
        y = int8_matmul(x, q8, scale)
    else:
        k = dequantize_tensor(q8, scale,
                              compute_dtype or jnp.result_type(x, jnp.float32))
        y = jnp.matmul(x.astype(k.dtype), k)
    bias_name = prefix[:-6] + "bias"  # 'kernel' -> 'bias', 'o_kernel' -> 'o_bias'
    if bias_name in layer_params:
        y = y + layer_params[bias_name].astype(y.dtype)
    return y


def quantize_for_serving(model, params, mode: str = "weight_only",
                         min_size: int = 4096):
    """Shared implementation behind the model families'
    ``quantize_for_serving``: validate, set the model's ``quant_mode``,
    return the quantized tree (``quantize_params`` warns if nothing
    matched)."""
    if mode not in MODES:
        raise ValueError(f"quant mode must be one of {MODES}, got {mode!r}")
    model.quant_mode = mode
    return quantize_params(params, min_size=min_size)


def _is_quantizable_kernel(path_leaf: str, arr) -> bool:
    # 'kernel' (graphdef dense/conv2d, the classifier head) or the
    # transformer family's named projections ('qkv_kernel', 'o_kernel',
    # 'fc1_kernel', ...); 2-D matmul or 4-D conv kernels
    return ((path_leaf == "kernel" or path_leaf.endswith("_kernel"))
            and getattr(arr, "ndim", 0) in (2, 4))


def quantize_params(params: Dict[str, Dict[str, Any]],
                    min_size: int = 4096) -> Dict[str, Dict[str, Any]]:
    """Quantize every dense/conv ``kernel`` leaf with >= ``min_size`` elements
    (small layers aren't worth the rounding) in a nested-dict params tree —
    the shape both GraphModel and the registry models use. Non-kernel leaves
    (biases, norms, embeddings) pass through untouched.

    The quantized tree is mode-agnostic; the serving model's ``quant_mode``
    ('weight_only' | 'dynamic') picks the matmul path. Conv kernels always
    serve weight-only.

    Warns when NO leaf quantized — naming conventions the matcher doesn't
    know (e.g. TF1 graphs with variables named 'W'/'weights', or everything
    under ``min_size``) would otherwise silently serve full precision while
    the caller believes it's int8. The warning lives HERE so every entry
    point (quantize_for_serving, the estimator's serving-side
    _cached_quantized_params) gets it.
    """

    def qlayer(layer):
        if not isinstance(layer, dict):
            return layer
        out = {}
        for name, arr in layer.items():
            if isinstance(arr, dict):
                out[name] = qlayer(arr)
                continue
            size = int(np_size(arr))
            if _is_quantizable_kernel(name, arr) and size >= min_size:
                q8, scale = quantize_tensor(arr, axis=-1)  # per out-channel
                out[f"{name}_q8"] = q8
                out[f"{name}_scale"] = scale
            else:
                out[name] = arr
        return out

    q = {k: qlayer(v) for k, v in params.items()}

    def _count_q8(d):
        return sum(_count_q8(v) if isinstance(v, dict)
                   else int(isinstance(k, str) and k.endswith("_q8"))
                   for k, v in d.items())

    if _count_q8(q) == 0:
        import logging
        logging.getLogger(__name__).warning(
            "quantize_params: no kernel leaf quantized — every matmul/conv "
            "kernel is either below min_size=%d elements or not named "
            "'kernel'/'*_kernel' (e.g. raw TF1 variables named "
            "'W'/'weights'); serving will run FULL PRECISION", min_size)
    return q


def np_size(arr) -> int:
    try:
        return int(arr.size)
    except Exception:
        import numpy as np

        return int(np.asarray(arr).size)


# ---------------------------------------------------------------------------
# Quantized paged-KV pool: per-page-per-head symmetric scales
# ---------------------------------------------------------------------------
#
# The serving pool stores K/V rows in int8 or fp8 (e4m3) with ONE f32 scale
# per (layer, page, head), kept in a tensor alongside the page tables. The
# scheme is the same symmetric absmax quantization as `quantize_tensor`, at
# page-head granularity: dequantized row = stored_row * scale[page, head].
# A scale of 0 marks a page-head nothing nonzero was ever written to — its
# stored rows are exact zeros, so readers multiply by the raw scale without
# a guard and still get exact zeros.
#
# Appends update the scale as a RUNNING absmax: when a new row raises a
# page-head's absmax, the page's already-stored rows are rescaled in place
# (q_new = cast(q_old * old_scale / new_scale)) so every row in a page
# always shares the page's current scale. A row landing at offset 0 resets
# the running max — the page is being reused and its prior content (and
# scale) is stale. Rescaling is exact when the scale did not change
# (ratio == 1) and touches only the pages being written, never the pool.


def kv_quant_supported(kv_dtype: str) -> bool:
    """True when this install can serve the given pool layout ("fp8"
    requires jnp.float8_e4m3fn; "bf16"/"int8" always work)."""
    return kv_dtype in KV_DTYPES and (kv_dtype != "fp8"
                                      or _FP8_DTYPE is not None)


def kv_pool_dtype(kv_dtype: str) -> Tuple[Any, float]:
    """``"int8" | "fp8" -> (storage dtype, quantization ceiling)``."""
    if kv_dtype not in ("int8", "fp8"):
        raise ValueError(f"kv_dtype must be one of {KV_DTYPES}, got "
                         f"{kv_dtype!r} (bf16 pools are not quantized)")
    if kv_dtype == "fp8":
        if _FP8_DTYPE is None:
            raise ValueError(
                "kv_dtype='fp8' needs jax.numpy.float8_e4m3fn, which this "
                "jax/ml_dtypes install does not expose; use 'int8'")
        return _FP8_DTYPE, _KV_QMAX["fp8"]
    return jnp.int8, _KV_QMAX["int8"]


def kv_cast(x, dtype, qmax: float):
    """f32 -> pool storage dtype with symmetric saturation. int8 rounds to
    nearest; fp8 rounds via the hardware/emulated e4m3 cast."""
    if dtype == jnp.int8:
        return jnp.clip(jnp.round(x), -qmax, qmax).astype(jnp.int8)
    return jnp.clip(x, -qmax, qmax).astype(dtype)


def quantize_kv_pages(pages, kv_dtype: str):
    """Quantize whole pages: ``pages [..., page, H, D]`` float ->
    ``(q [..., page, H, D], scale [..., H])`` with one symmetric scale per
    trailing (page, head) block — absmax over the page's rows and head_dim.
    Empty (all-zero) page-heads get scale 0 (see module note)."""
    dtype, qmax = kv_pool_dtype(kv_dtype)
    pf = jnp.asarray(pages, jnp.float32)
    amax = jnp.max(jnp.abs(pf), axis=(-3, -1))            # [..., H]
    scale = amax / qmax
    eff = jnp.where(scale > 0, scale, 1.0)
    q = kv_cast(pf / eff[..., None, :, None], dtype, qmax)
    return q, scale


def dequantize_kv_pages(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv_pages`: ``q [..., page, H, D]`` with
    ``scale [..., H]`` -> float pages. Safe for scale == 0 (stored rows are
    exact zeros there)."""
    return (q.astype(jnp.float32) * scale[..., None, :, None]).astype(dtype)


def paged_quant_write_pages(q_pool, scales, layer, page_ids, pages):
    """Commit whole freshly-computed pages into the quantized pool (the
    prefill ladder's write): quantize each page with its own per-head scale
    and overwrite both the rows and the scale entries.

    ``q_pool [L, pages, page, H, D]`` int8/fp8; ``scales [L, pages, H]``
    f32; ``page_ids [N]`` int32; ``pages [N, page, H, D]`` float."""
    qmax = _KV_QMAX["int8"] if q_pool.dtype == jnp.int8 else _KV_QMAX["fp8"]
    pf = jnp.asarray(pages, jnp.float32)
    amax = jnp.max(jnp.abs(pf), axis=(1, 3))              # [N, H]
    scale = amax / qmax
    eff = jnp.where(scale > 0, scale, 1.0)
    q = kv_cast(pf / eff[:, None, :, None], q_pool.dtype, qmax)
    q_pool = q_pool.at[layer, page_ids].set(q)
    scales = scales.at[layer, page_ids].set(scale)
    return q_pool, scales


def paged_quant_append(q_pool, scales, layer, page_ids, offs, rows):
    """Append rows into the quantized pool at ``(layer, page_ids, offs)``,
    maintaining the per-page-per-head running scale.

    ``rows [..., H, D]`` float with matching ``page_ids``/``offs [...]``
    int32 (any batch shape — decode lanes, suffix-chunk tokens, or the
    verify grid's [B, S]). Steps, all on the touched pages only:

    1. scatter-max the new rows' absmax into the scale plane (a row at
       offset 0 first RESETS its page's running max — page reuse);
    2. rescale the touched pages' stored rows from the old scale to the
       new one (exact no-op when the scale did not grow);
    3. quantize the new rows with the final scale and scatter them in.

    Duplicate page targets (several rows landing in one page, or masked
    rows aimed at scratch page 0) are sound: the scatter-max folds their
    maxima, and the page-rescale scatter writes identical values."""
    qmax = _KV_QMAX["int8"] if q_pool.dtype == jnp.int8 else _KV_QMAX["fp8"]
    num_pages = q_pool.shape[1]
    h, d = q_pool.shape[-2], q_pool.shape[-1]
    pids = jnp.reshape(page_ids, (-1,))
    offv = jnp.reshape(jnp.broadcast_to(offs, jnp.shape(page_ids)), (-1,))
    rowsf = jnp.reshape(jnp.asarray(rows, jnp.float32), (-1, h, d))
    rmax = jnp.max(jnp.abs(rowsf), axis=-1)               # [N, H]

    plane = scales[layer]                                 # [pages, H]
    fresh = jnp.zeros((num_pages, 1), jnp.float32).at[pids].max(
        (offv == 0).astype(jnp.float32)[:, None])
    old_plane = plane * (1.0 - fresh)
    new_plane = old_plane.at[pids].max(rmax / qmax)

    eff = new_plane[pids]                                 # [N, H]
    eff = jnp.where(eff > 0, eff, 1.0)
    rows_q = kv_cast(rowsf / eff[:, :, None], q_pool.dtype, qmax)
    ratio = old_plane[pids] / eff                         # <= 1; 0 when fresh
    pages_q = q_pool[layer, pids]                         # [N, page, H, D]
    pages_r = kv_cast(pages_q.astype(jnp.float32) * ratio[:, None, :, None],
                      q_pool.dtype, qmax)
    q_pool = q_pool.at[layer, pids].set(pages_r)
    q_pool = q_pool.at[layer, pids, offv].set(rows_q)
    scales = scales.at[layer].set(new_plane)
    return q_pool, scales


def paged_quant_gather(q_pool, scales, layer, page_ids, dtype=jnp.float32):
    """Gather-and-dequantize pages ``page_ids`` of one layer — the
    suffix-prefill attend's manual gather. The convert runs on the GATHERED
    rows, never the whole pool (the defect GC-J108 exists to catch)."""
    g = q_pool[layer, page_ids].astype(jnp.float32)       # [..., page, H, D]
    s = scales[layer, page_ids]                           # [..., H]
    return (g * s[..., None, :, None]).astype(dtype)
