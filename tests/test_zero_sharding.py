"""ZeRO-2/3 under the declarative ShardingConfig: stage parity, checkpoint
interchange, offload, retrace stability, and the GC-J106 jaxpr gate.

The contract under test (docs/sharding.md): the zero stage changes WHERE
bytes live, never WHAT is computed —

- stages 0-3 produce the same losses/params within reduction-order drift
  (pinned ATOL/RTOL), for every registry optimizer;
- checkpoints always hold the standard layout, so a directory written at
  any stage restores at any other bit-identically;
- ``offload_opt_state`` changes residency only;
- one compile per (stage, shapes): repeated steps never retrace;
- the declared config matches the program's observed collectives (GC-J106
  fires on a planted mismatch, stays silent on every repo-built stage).
"""

import shutil
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkflow_tpu.models.presets import mlp
from sparkflow_tpu.optimizers import AVAILABLE_OPTIMIZERS, build_optimizer
from sparkflow_tpu.optimizers_sharded import (gather_zero3_params,
                                              place_zero1_state,
                                              shard_zero3_params,
                                              sharded_update,
                                              zero3_param_shardings,
                                              zero_memory_report)
from sparkflow_tpu.parallel.dp import make_dp_train_step
from sparkflow_tpu.parallel.mesh import make_mesh
from sparkflow_tpu.sharding import ShardingConfig, as_sharding_config
from sparkflow_tpu.trainer import Trainer

# reduction-order float drift only: every stage computes the same math
ATOL = 5e-5
RTOL = 1e-5

pytestmark = pytest.mark.skipif(jax.device_count() < 8,
                                reason="needs the 8-virtual-device harness")


def _model():
    from sparkflow_tpu.models import model_from_json
    # hidden=17 -> every weight/bias size is ragged mod 8
    return model_from_json(mlp(10, 3, hidden=(17,)))


def _data(n=64):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(n, 10), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)])
    mask = jnp.ones((n,), jnp.float32)
    return x, y, mask


def _init_for_stage(m, opt, mesh, stage, p0):
    """(params, opt_state) in the layout stage expects, placed on mesh."""
    if stage == 0:
        return jax.tree.map(jnp.array, p0), opt.init(p0)
    state = place_zero1_state(sharded_update(opt, 8, "dp").init(p0), mesh, 8)
    if stage >= 3:
        p = shard_zero3_params(p0, 8)
        p = jax.tree.map(jax.device_put, p, zero3_param_shardings(p, mesh, 8))
        return p, state
    return jax.tree.map(jnp.array, p0), state


def _run_stage(m, opt, mesh, stage, p0, steps=2):
    x, y, mask = _data()
    rng = jax.random.PRNGKey(1)
    step = make_dp_train_step(m, opt, mesh, "x:0", "y:0",
                              sharding=ShardingConfig(zero_stage=stage))
    p, s = _init_for_stage(m, opt, mesh, stage, p0)
    losses = []
    for i in range(steps):
        p, s, l = step(p, s, x, y, mask, jax.random.fold_in(rng, i))
        losses.append(float(l))
    if stage >= 3:
        p = gather_zero3_params(p, p0)
    return losses, p


# -- the config itself ------------------------------------------------------

def test_config_validation_errors():
    with pytest.raises(ValueError, match="zero_stage must be one of"):
        ShardingConfig(zero_stage=5)
    with pytest.raises(ValueError, match="DIFFERENT mesh axis"):
        ShardingConfig(data_axis="dp", dcn_axis="dp")
    mesh = make_mesh({"dp": 8})
    with pytest.raises(ValueError, match="not a mesh axis"):
        ShardingConfig(dcn_axis="dnc").validate(mesh)  # typo'd axis
    # the dp-less message is actionable: names the fix
    pp = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match=r"make_mesh\({'dp': N}\)"):
        ShardingConfig(zero_stage=1).validate(pp)


def test_config_dp_less_mesh_falls_back_to_replicated_rows():
    """The ISSUE-1 sharp edge, now through the config path: a mesh without
    the data axis yields replicated rows (P()), not an unknown-axis crash."""
    from jax.sharding import PartitionSpec as P
    pp = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    cfg = ShardingConfig()
    assert cfg.data_spec(pp) == P()
    assert cfg.batch_axes(pp) == ()
    cfg.validate(pp)  # stage 0: fine without a data axis
    assert cfg.data_spec(make_mesh({"dp": 8})) == P("dp")


def test_config_coercion_and_legacy_mapping():
    assert as_sharding_config(None) == ShardingConfig()
    cfg = ShardingConfig(zero_stage=2)
    assert as_sharding_config(cfg) is cfg
    assert as_sharding_config({"zero_stage": 3}).zero_stage == 3
    with pytest.raises(TypeError, match="ShardingConfig"):
        as_sharding_config(3)
    assert ShardingConfig.from_legacy("off").zero_stage == 0
    assert ShardingConfig.from_legacy("auto").zero_stage == 1
    assert ShardingConfig.from_legacy("on").zero_stage == 1
    with pytest.raises(ValueError, match="weight_update_sharding"):
        ShardingConfig.from_legacy("maybe")
    d = ShardingConfig(zero_stage=3, offload_opt_state=True).describe()
    assert d["zero_stage"] == 3 and d["offload_opt_state"] is True


def test_config_model_parallel_axes():
    """tp_axis/ep_axis: orthogonality to the batch axes is enforced at
    construction, typo'd axes at validate(), and the introspection helpers
    report the mesh-resolved degrees."""
    with pytest.raises(ValueError, match="DIFFERENT mesh axis"):
        ShardingConfig(tp_axis="dp")  # collides with data_axis
    with pytest.raises(ValueError, match="DIFFERENT mesh axis"):
        ShardingConfig(dcn_axis="dcn", ep_axis="dcn")
    with pytest.raises(ValueError, match="distinct mesh axes"):
        ShardingConfig(tp_axis="mp", ep_axis="mp")
    with pytest.raises(ValueError, match="non-empty mesh axis"):
        ShardingConfig(tp_axis="")
    with pytest.raises(ValueError, match="tp_axis='tp' is not a mesh axis"):
        ShardingConfig(tp_axis="tp").validate(make_mesh({"dp": 8}))
    mp = make_mesh({"tp": 2, "ep": 4})
    cfg = ShardingConfig(tp_axis="tp", ep_axis="ep").validate(mp)
    assert cfg.tp_size(mp) == 2 and cfg.ep_size(mp) == 4
    assert cfg.model_parallel()
    assert cfg.dp_size(mp) == 1  # dp-less mesh, stage 0: fine
    plain = ShardingConfig()
    assert not plain.model_parallel()
    assert plain.tp_size(mp) == 1 and plain.ep_size(mp) == 1
    d = cfg.describe()
    assert d["tp_axis"] == "tp" and d["ep_axis"] == "ep"
    legacy = ShardingConfig.from_legacy("off", tp_axis="tp", ep_axis="ep")
    assert (legacy.zero_stage, legacy.tp_axis, legacy.ep_axis) == \
        (0, "tp", "ep")


def test_at_rest_leaf_spec_one_rule_two_layouts():
    """docs/sharding.md's claim that fsdp (GSPMD) and flat zero-3 are two
    spellings of ONE per-leaf decision, checked against both consumers."""
    from jax.sharding import PartitionSpec as P

    from sparkflow_tpu.optimizers_sharded import zero1_state_specs
    from sparkflow_tpu.parallel.tp import fsdp_pspecs
    from sparkflow_tpu.sharding import at_rest_leaf_spec

    # gspmd: the LARGEST dim shards, iff the leaf clears min_size
    assert at_rest_leaf_spec((512, 256), "fsdp", layout="gspmd") == \
        P("fsdp", None)
    assert at_rest_leaf_spec((128, 1024), "fsdp", layout="gspmd") == \
        P(None, "fsdp")
    assert at_rest_leaf_spec((17,), "fsdp", layout="gspmd") == P()
    assert at_rest_leaf_spec((4, 4), "fsdp", layout="gspmd",
                             min_size=8) == P("fsdp", None)
    assert at_rest_leaf_spec((), "fsdp", layout="gspmd") == P()
    # flat: dim 0 is shard-bearing by construction ([n_shards, s] leaves)
    assert at_rest_leaf_spec((8, 37), "dp", layout="flat",
                             n_shards=8) == P("dp")
    assert at_rest_leaf_spec((4, 37), "dp", layout="flat",
                             n_shards=8) == P()  # not the flat layout
    assert at_rest_leaf_spec((37,), "dp", layout="flat", n_shards=8) == P()
    with pytest.raises(ValueError, match="'gspmd' or 'flat'"):
        at_rest_leaf_spec((8, 8), "dp", layout="torus")
    # both consumers are pure projections of the rule
    m = _model()
    specs = fsdp_pspecs(m.param_specs(), min_size=64)
    for lname, pspec in m.param_specs().items():
        for pname, (shape, _init) in pspec.items():
            assert specs[lname][pname] == at_rest_leaf_spec(
                shape, "fsdp", layout="gspmd", min_size=64), (lname, pname)
    state = {"mu": jnp.zeros((8, 37)), "count": jnp.zeros(())}
    ss = zero1_state_specs(state, 8)
    assert ss["mu"] == P("dp") and ss["count"] == P()


# -- stage parity, every registry optimizer ---------------------------------

@pytest.mark.parametrize("opt_name", AVAILABLE_OPTIMIZERS)
def test_zero23_match_replicated_all_optimizers(opt_name):
    """Two steps at stages 2 and 3 vs the replicated stage-0 step: same
    losses and params within the pinned reduction-order tolerance, ragged
    param sizes, dp=8."""
    m = _model()
    opt = build_optimizer(opt_name, 1e-2, None)
    mesh = make_mesh({"dp": 8})
    p0 = m.init(jax.random.PRNGKey(0))
    l0, pr0 = _run_stage(m, opt, mesh, 0, p0)
    for stage in (2, 3):
        ls, ps = _run_stage(m, opt, mesh, stage, p0)
        for a, b in zip(l0, ls):
            assert abs(a - b) < ATOL, (opt_name, stage)
        for a, b in zip(jax.tree.leaves(pr0), jax.tree.leaves(ps)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=ATOL, rtol=RTOL,
                                       err_msg=f"{opt_name} stage {stage}")


def test_zero3_param_roundtrip_across_shard_counts():
    """Standard -> flat(8) -> standard -> flat(4) -> standard is exact: the
    flat layout is a pure reshape+pad, so checkpoints written at one dp
    size restore at another bit-for-bit."""
    p0 = _model().init(jax.random.PRNGKey(0))
    f8 = shard_zero3_params(p0, 8)
    assert all(l.shape[0] == 8 for l in jax.tree.leaves(f8))
    back = gather_zero3_params(f8, p0)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(back)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    f4 = shard_zero3_params(back, 4)
    back4 = gather_zero3_params(f4, p0)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(back4)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_zero_memory_report_shrinks_with_stage():
    opt = build_optimizer("adam", 1e-2, None)
    p0 = _model().init(jax.random.PRNGKey(0))
    reps = {s: zero_memory_report(opt, p0, 8, s) for s in (0, 1, 2, 3)}
    # stage >=1 shards grads+state at update time; stage 3 also params at rest
    assert reps[1]["grad_opt_at_update"] < reps[0]["grad_opt_at_update"] / 4
    assert reps[2]["grad_opt_at_update"] <= reps[1]["grad_opt_at_update"]
    assert reps[3]["params_at_rest"] < reps[0]["params_at_rest"] / 4
    # the bench acceptance bar, pinned structurally
    assert (reps[2]["grad_opt_at_update"]
            <= 1.3 * reps[2]["ideal_grad_opt"])


# -- trainer integration ----------------------------------------------------

def _fit(sharding, ckpt=None, iters=3, mesh=None, **kw):
    t = Trainer(mlp(10, 3, hidden=(17,)), "x:0", "y:0", optimizer="adam",
                learning_rate=1e-2, mini_batch_size=16, iters=iters, seed=3,
                mesh=mesh if mesh is not None else make_mesh({"dp": 8}),
                sharding=sharding, checkpoint_dir=ckpt,
                checkpoint_every=1 if ckpt else 0, **kw)
    rs = np.random.RandomState(0)
    X = rs.randn(64, 10).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    return t, t.fit(X, Y)


def test_trainer_all_stages_agree_and_return_standard_layout():
    runs = {s: _fit(ShardingConfig(zero_stage=s)) for s in (0, 1, 2, 3)}
    base = runs[0][1]
    std_shapes = [l.shape for l in jax.tree.leaves(base.params)]
    for s in (1, 2, 3):
        t, r = runs[s]
        assert t._zero_stage == s
        assert [l.shape for l in jax.tree.leaves(r.params)] == std_shapes
        for a, b in zip(base.losses, r.losses):
            assert abs(a - b) < ATOL, s
        for a, b in zip(jax.tree.leaves(base.params),
                        jax.tree.leaves(r.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=ATOL, rtol=RTOL)


@pytest.mark.parametrize("save_stage", [0, 1, 2, 3])
def test_checkpoint_interchange_matrix(save_stage, tmp_path):
    """A checkpoint written at any stage restores at EVERY other stage with
    bit-identical params: checkpoints always hold the standard layout, and
    stage conversion is pure layout (pad/reshape, no arithmetic)."""
    d = str(tmp_path / f"ck{save_stage}")
    t_save, _ = _fit(ShardingConfig(zero_stage=save_stage), ckpt=d, iters=2)
    want = [np.asarray(l) for l in jax.tree.leaves(t_save.params)]
    for restore_stage in (0, 1, 2, 3):
        t_r = Trainer(mlp(10, 3, hidden=(17,)), "x:0", "y:0",
                      optimizer="adam", learning_rate=1e-2,
                      mini_batch_size=16, iters=2, seed=3,
                      mesh=make_mesh({"dp": 8}),
                      sharding=ShardingConfig(zero_stage=restore_stage),
                      checkpoint_dir=d, checkpoint_every=1)
        rs = np.random.RandomState(0)
        X = rs.randn(64, 10).astype(np.float32)
        Y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
        t_r.fit(X, Y)  # resumes at the final epoch; trains nothing new
        got = [np.asarray(l) for l in jax.tree.leaves(t_r.params)]
        for a, b in zip(want, got):
            assert np.array_equal(a, b), (save_stage, restore_stage)


def test_offload_opt_state_equivalence():
    """offload_opt_state changes residency, not numerics: same losses and
    params as the on-device run, state on host between epochs."""
    t_dev, r_dev = _fit(ShardingConfig(zero_stage=2))
    t_off, r_off = _fit(ShardingConfig(zero_stage=2, offload_opt_state=True))
    assert t_off._offload_active
    for a, b in zip(r_dev.losses, r_off.losses):
        assert abs(a - b) < ATOL
    for a, b in zip(jax.tree.leaves(r_dev.params),
                    jax.tree.leaves(r_off.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=ATOL, rtol=RTOL)
    # the post-fit flush materializes the async host mirror: state ends
    # host-side even though the loop kept a device-resident working copy
    assert all(isinstance(l, np.ndarray)
               for l in jax.tree.leaves(t_off._last_opt_state))


def test_offload_double_buffer_bitwise():
    """The double-buffered offload never round-trips a value through the
    host mid-run (steady-state calls reuse their own device tree; the D2H
    copy is a background mirror), so against an on-device run of the SAME
    per-epoch loop program the losses and final opt state are bitwise
    equal — not merely within float drift."""
    # halt_on_nan forces the on-device arm off the fused multi-epoch
    # program and onto the loop path the offload wrapper uses
    t_dev, r_dev = _fit(ShardingConfig(zero_stage=2), halt_on_nan=True)
    t_off, r_off = _fit(ShardingConfig(zero_stage=2, offload_opt_state=True),
                        halt_on_nan=True)
    assert t_off._offload_active and not t_dev._offload_active
    assert r_dev.losses == r_off.losses
    for a, b in zip(jax.tree.leaves(t_dev._last_opt_state),
                    jax.tree.leaves(t_off._last_opt_state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(r_dev.params),
                    jax.tree.leaves(r_off.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_zero_steps_never_retrace():
    """One trace per stage: repeated steps with fresh data/rng hit the same
    compiled program (RecompileGuard counts traces of the raw stepper)."""
    from sparkflow_tpu.analysis.runtime_guards import RecompileGuard
    m = _model()
    opt = build_optimizer("adam", 1e-2, None)
    mesh = make_mesh({"dp": 8})
    p0 = m.init(jax.random.PRNGKey(0))
    x, y, mask = _data()
    for stage in (2, 3):
        raw = make_dp_train_step(m, opt, mesh, "x:0", "y:0",
                                 sharding=ShardingConfig(zero_stage=stage),
                                 _raw=True)
        guard = RecompileGuard(name=f"zero{stage}")
        step = jax.jit(guard.wrap(raw))
        p, s = _init_for_stage(m, opt, mesh, stage, p0)
        for i in range(3):
            p, s, _ = step(p, s, x + i, y, mask,
                           jax.random.fold_in(jax.random.PRNGKey(7), i))
        assert guard.traces == 1, (stage, guard.report())


def test_trainer_explicit_stage_requests_raise_when_ineligible():
    # dp-less mesh: the config's own actionable message
    with pytest.raises(ValueError, match="zero_stage=2"):
        _fit(ShardingConfig(zero_stage=2), mesh=make_mesh({"fsdp": 8}))
    # blocked optimizer options: shard-local update breaks their math
    with pytest.raises(ValueError, match="clip_norm"):
        _fit(ShardingConfig(zero_stage=2),
             optimizer_options={"clip_norm": 1.0})
    # no mesh at all
    with pytest.raises(ValueError, match="no mesh"):
        t = Trainer(mlp(10, 3), "x:0", "y:0", optimizer="adam",
                    mini_batch_size=16, iters=1,
                    sharding=ShardingConfig(zero_stage=2))
        rs = np.random.RandomState(0)
        t.fit(rs.randn(32, 10).astype(np.float32),
              np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)])


def test_trainer_dp_less_mesh_with_config_stage0_trains():
    """The dp-less fallback holds through the config path: stage 0 on a
    mesh without 'dp' trains via replicated rows."""
    t, r = _fit(ShardingConfig(zero_stage=0), mesh=make_mesh({"fsdp": 8}))
    assert r.stop_reason == "completed"
    assert np.isfinite(r.losses).all()
    assert t._zero_stage == 0


# -- GC-J106: declared config vs observed collectives ------------------------

def test_gc_j106_repo_stages_lint_clean():
    """The repo gate: every stage the unified builder produces matches its
    own declaration — zero findings, all four stages."""
    from sparkflow_tpu.analysis.jaxpr_lint import lint_dp_train_step
    m = _model()
    mesh = make_mesh({"dp": 8})
    for stage in (0, 1, 2, 3):
        findings = lint_dp_train_step(
            m, "adam", mesh=mesh, sharding=ShardingConfig(zero_stage=stage))
        assert findings == [], (stage, findings)


def test_gc_j106_planted_mismatch_both_directions():
    from sparkflow_tpu.analysis.jaxpr_lint import lint_sharding_config
    m = _model()
    opt = build_optimizer("adam", 1e-2, None)
    mesh = make_mesh({"dp": 8})
    p = jax.eval_shape(m.init, jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((8, 10), np.float32)
    y = jax.ShapeDtypeStruct((8, 3), np.float32)
    mask = jax.ShapeDtypeStruct((8,), np.float32)
    rng = jax.random.PRNGKey(0)

    # a stage-0 program declared as stage 2: no reduce_scatter -> finding
    step0 = make_dp_train_step(m, opt, mesh, "x:0", "y:0",
                               sharding=ShardingConfig(zero_stage=0),
                               _raw=True)
    s0 = jax.eval_shape(opt.init, p)
    found = lint_sharding_config(step0, (p, s0, x, y, mask, rng),
                                 ShardingConfig(zero_stage=2))
    assert len(found) == 1 and found[0].rule == "GC-J106"
    assert "reduce_scatter" in found[0].message

    # a stage-2 program declared as stage 0: scatter machinery -> finding
    step2 = make_dp_train_step(m, opt, mesh, "x:0", "y:0",
                               sharding=ShardingConfig(zero_stage=2),
                               _raw=True)
    s2 = jax.eval_shape(sharded_update(opt, 8, "dp").init, p)
    found = lint_sharding_config(step2, (p, s2, x, y, mask, rng),
                                 ShardingConfig(zero_stage=0))
    assert len(found) == 1 and found[0].rule == "GC-J106"
    # suppression works like every other rule
    assert lint_sharding_config(step2, (p, s2, x, y, mask, rng),
                                ShardingConfig(zero_stage=0),
                                ignore=("GC-J106",)) == []


# -- serving consumes the same config ----------------------------------------

def test_inference_engine_accepts_sharding_config():
    from sparkflow_tpu.serving.engine import InferenceEngine
    t, r = _fit(ShardingConfig(zero_stage=3))
    eng = InferenceEngine(mlp(10, 3, hidden=(17,)), r.params,
                          mesh=make_mesh({"dp": 8}),
                          sharding=ShardingConfig(zero_stage=3),
                          max_batch=16, warmup=False)
    out = eng.predict(np.random.RandomState(1).randn(16, 10)
                      .astype(np.float32))
    assert out.shape == (16, 3) and np.isfinite(out).all()
    assert eng.stats()["sharding"]["zero_stage"] == 3
    with pytest.raises(ValueError, match="not a mesh axis"):
        InferenceEngine(mlp(10, 3, hidden=(17,)), r.params,
                        mesh=make_mesh({"dp": 8}),
                        sharding=ShardingConfig(dcn_axis="oops"),
                        warmup=False)
