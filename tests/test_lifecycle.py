"""Resource-lifecycle analysis (GC-X601..X605): planted defects fire, the
fixed twins stay silent, and the runtime tracker balances a real chaos run.

Static side (:mod:`sparkflow_tpu.analysis.lifecycle`): one planted-defect /
fixed-twin pair per rule —

- GC-X601: a pool checkout with an early return (and a raise) before the
  release; twins with try/finally, a context manager, a None-guard, and an
  ownership transfer pass;
- GC-X602: a call that can raise between acquire and release with nothing
  routing the error branch through the release; try/finally and
  releasing-handler twins pass;
- GC-X603: started threads/subprocesses never joined/reaped, at class and
  function scope; joined, loop-joined, and handed-off twins pass;
- GC-X604: per-entity gauge namespaces with no cleanup on the *terminal*
  teardown path — cleanup only in deregister is NOT enough (live entities
  at stop() still leak, the PR 18 bug class);

plus the inline-suppression contract and the ``handle_arg`` pairs
(``kv.alloc(slot)``/``free(slot)``).

Dynamic side (:mod:`sparkflow_tpu.analysis.restrack`): balance accounting
with acquisition stacks, double-free detection, the env gate, the
zero-overhead-when-off contract (instrumentors return their argument
untouched — no wrapper in ``vars(obj)``), metrics-namespace tracking, and
a chaos leak test: a ``ContinuousBatcher`` killed mid-generation
(``close(drain=False)``) under the tracker ends with zero slot/admission
balance and every abandoned future failed.
"""

import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkflow_tpu.analysis import lifecycle, restrack
from sparkflow_tpu.analysis.restrack import ResourceTracker
from sparkflow_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src: str):
    return [f.rule for f in lifecycle.lint_source(src)]


# ---------------------------------------------------------------------------
# GC-X601: leak on escape
# ---------------------------------------------------------------------------

_POOL_PREAMBLE = '''
class ConnectionPool:
    def acquire(self): ...
    def release(self, conn, reuse=True): ...
    def close(self): ...
'''


def test_x601_early_return_fires():
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def bad(self, flag):
        conn, reused = self.pool.acquire()
        if flag:
            return None          # leaks conn
        self.pool.release(conn)
        return flag
'''
    assert rules_of(src) == ["GC-X601"]


def test_x601_raise_fires():
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def bad(self, flag):
        conn, reused = self.pool.acquire()
        if flag:
            raise ValueError(flag)   # leaks conn
        self.pool.release(conn)
'''
    assert rules_of(src) == ["GC-X601"]


def test_x601_try_finally_twin_silent():
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def good(self, flag):
        conn, reused = self.pool.acquire()
        try:
            if flag:
                return None
        finally:
            self.pool.release(conn)
        return flag
'''
    assert rules_of(src) == []


def test_x601_context_manager_silent():
    # an acquire consumed by a withitem is the CM protocol's to clean up
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def good(self, flag):
        with self.pool.acquire() as conn:
            if flag:
                return None
        return flag
'''
    assert rules_of(src) == []


def test_x601_none_guard_silent():
    # `if h is None: return` reacts to a FAILED acquire — nothing to release
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def good(self):
        conn = self.pool.acquire()
        if conn is None:
            return None
        self.pool.release(conn)
        return True
'''
    assert rules_of(src) == []


def test_x601_ownership_transfer_silent():
    # returning / storing / passing the handle hands the release duty off
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def checkout(self):
        conn, reused = self.pool.acquire()
        return conn

    def stash(self):
        conn, reused = self.pool.acquire()
        self._conn = conn
        if not reused:
            return None
        return True
'''
    assert rules_of(src) == []


def test_x601_kv_handle_arg():
    # kv.alloc(slot, ...): the handle is the ARGUMENT, released by free(slot)
    bad = '''
class PagedKVCache:
    def alloc(self, slot, n, total): ...
    def free(self, slot): ...

class Engine:
    def __init__(self):
        self.kv = PagedKVCache()

    def bad(self, slot, n):
        pages = self.kv.alloc(slot, n, n + 4)
        if n > 64:
            raise ValueError(n)   # pages leak
        self.kv.free(slot)
'''
    assert rules_of(bad) == ["GC-X601"]
    good = bad.replace("""        pages = self.kv.alloc(slot, n, n + 4)
        if n > 64:
            raise ValueError(n)   # pages leak
        self.kv.free(slot)""", """        pages = self.kv.alloc(slot, n, n + 4)
        try:
            if n > 64:
                raise ValueError(n)
        finally:
            self.kv.free(slot)""")
    assert rules_of(good) == []


def test_x601_inline_suppression():
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def intentional(self, flag):
        conn, reused = self.pool.acquire()
        if flag:
            return None  # graftcheck: disable=GC-X601
        self.pool.release(conn)
        return flag
'''
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# GC-X602: release skipped on error
# ---------------------------------------------------------------------------


def test_x602_unprotected_risky_call_fires():
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def bad(self, payload):
        conn, reused = self.pool.acquire()
        blob = encode(payload)        # can raise -> conn leaks
        self.pool.release(conn)
        return blob
'''
    assert rules_of(src) == ["GC-X602"]


def test_x602_try_finally_twin_silent():
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def good(self, payload):
        conn, reused = self.pool.acquire()
        try:
            data = send(conn, payload)
        finally:
            self.pool.release(conn)
        return data
'''
    assert rules_of(src) == []


def test_x602_releasing_handler_silent():
    # an except that releases (the client.py _http shape) is protection
    src = _POOL_PREAMBLE + '''
class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def good(self, payload):
        conn, reused = self.pool.acquire()
        try:
            data = send(conn, payload)
        except Exception:
            self.pool.release(conn, reuse=False)
            raise
        self.pool.release(conn)
        return data
'''
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# GC-X603: unreaped threads / subprocesses
# ---------------------------------------------------------------------------


def test_x603_class_thread_never_joined_fires():
    src = '''
import threading

class Worker:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._t.start()

    def _run(self): ...
'''
    assert rules_of(src) == ["GC-X603"]


def test_x603_joined_twin_silent():
    src = '''
import threading

class Worker:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._t.start()

    def stop(self):
        self._t.join(timeout=5.0)

    def _run(self): ...
'''
    assert rules_of(src) == []


def test_x603_loop_alias_join_silent():
    # `for w in self._workers: w.join()` must count as reaping the attr
    src = '''
import threading

class Pool:
    def __init__(self, n):
        self._workers = [threading.Thread(target=self._run)
                         for _ in range(n)]

    def start(self):
        for w in self._workers:
            w.start()

    def close(self):
        for w in self._workers:
            w.join()

    def _run(self): ...
'''
    assert rules_of(src) == []


def test_x603_local_thread_fires_and_handoff_silent():
    bad = '''
import threading

def bad():
    t = threading.Thread(target=work)
    t.start()
'''
    assert rules_of(bad) == ["GC-X603"]
    joined = bad.replace("    t.start()\n",
                         "    t.start()\n    t.join()\n")
    assert rules_of(joined) == []
    handed_off = bad.replace("    t.start()\n",
                             "    t.start()\n    registry.adopt(t)\n")
    assert rules_of(handed_off) == []


def test_x603_class_subprocess_never_reaped_fires():
    # Popen has no .start(): the ctor assignment IS the start, and
    # send_signal is not a reap — nothing ever waits/kills -> zombie
    bad = '''
import subprocess

class Manager:
    def spawn(self):
        self._proc = subprocess.Popen(["sleep", "1"])

    def kick(self):
        self._proc.send_signal(9)
'''
    assert rules_of(bad) == ["GC-X603"]
    fixed = bad.replace("    def kick(self):\n"
                        "        self._proc.send_signal(9)",
                        "    def stop(self):\n"
                        "        self._proc.kill()\n"
                        "        self._proc.wait()")
    assert rules_of(fixed) == []


def test_x603_local_subprocess():
    bad = '''
import subprocess

def bad():
    p = subprocess.Popen(["sleep", "1"])
    p.send_signal(9)
'''
    assert rules_of(bad) == ["GC-X603"]
    reaped = bad.replace("    p.send_signal(9)\n", "    p.wait()\n")
    assert rules_of(reaped) == []
    handed_off = bad.replace("    p.send_signal(9)\n",
                             "    manager.adopt(p)\n")
    assert rules_of(handed_off) == []


# ---------------------------------------------------------------------------
# GC-X604: gauge namespace without terminal cleanup
# ---------------------------------------------------------------------------

_GAUGE_BAD = '''
class Fleet:
    def __init__(self, metrics):
        self.metrics = metrics

    def publish(self, idx, depth):
        self.metrics.gauge(f"fleet/replica{idx}/depth", depth)

    def stop(self):
        self._running = False
'''


def test_x604_dynamic_gauges_no_cleanup_fires():
    assert rules_of(_GAUGE_BAD) == ["GC-X604"]


def test_x604_cleanup_in_stop_silent():
    src = _GAUGE_BAD.replace(
        "        self._running = False",
        "        self._running = False\n"
        "        self.metrics.remove_prefix(\"fleet/replica\")")
    assert rules_of(src) == []


def test_x604_transitive_cleanup_silent():
    # stop() -> self._teardown() -> remove_matching counts (fixpoint)
    src = _GAUGE_BAD.replace(
        "        self._running = False",
        "        self._running = False\n"
        "        self._teardown()\n\n"
        "    def _teardown(self):\n"
        "        self.metrics.remove_matching(r\"^fleet/replica\\d+/\")")
    assert rules_of(src) == []


def test_x604_deregister_alone_is_not_enough():
    # the PR 18 bug class: per-entity deregister cleans, stop() doesn't —
    # entities still live at stop() leak their gauges
    src = _GAUGE_BAD.replace(
        "    def stop(self):",
        "    def deregister(self, idx):\n"
        "        self.metrics.remove_prefix(f\"fleet/replica{idx}/\")\n\n"
        "    def stop(self):")
    assert rules_of(src) == ["GC-X604"]


def test_x604_static_names_exempt():
    src = '''
class Controller:
    def __init__(self, metrics):
        self.metrics = metrics

    def publish(self):
        self.metrics.gauge("controller/target", 1.0)

    def stop(self):
        self._running = False
'''
    assert rules_of(src) == []


def test_x604_no_lifecycle_method_out_of_scope():
    src = '''
class Recorder:
    def __init__(self, metrics):
        self.metrics = metrics

    def publish(self, idx):
        self.metrics.gauge(f"rec/shard{idx}/lag", 0.0)
'''
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# whole-path plumbing: lint_paths over real files
# ---------------------------------------------------------------------------


def test_lint_paths_cross_file_types(tmp_path):
    # the receiver type comes from ANOTHER file's class definition
    (tmp_path / "poolmod.py").write_text(_POOL_PREAMBLE)
    (tmp_path / "clientmod.py").write_text('''
from poolmod import ConnectionPool

class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def bad(self, flag):
        conn, reused = self.pool.acquire()
        if flag:
            return None
        self.pool.release(conn)
        return flag
''')
    findings = lifecycle.lint_paths([str(tmp_path)])
    assert [f.rule for f in findings] == ["GC-X601"]
    assert findings[0].path.endswith("clientmod.py")


# ---------------------------------------------------------------------------
# ResourceTracker battery
# ---------------------------------------------------------------------------


def test_tracker_balance_and_stacks():
    t = ResourceTracker()
    with t:
        t.acquire("kv-slot", 0)
        t.acquire("kv-slot", 0)
        t.acquire("kv-slot", 1)
        t.release("kv-slot", 0)
    assert t.balance() == 2
    assert t.balance("kv-slot") == 2
    assert t.balance("http-conn") == 0
    live = t.live()
    assert len(live[("kv-slot", 0)]) == 1
    assert len(live[("kv-slot", 1)]) == 1
    fs = t.findings()
    assert {f.rule for f in fs} == {"GC-X605"}
    assert all("test_lifecycle" in s for f in fs
               for s in f.detail["stacks"])
    with pytest.raises(AssertionError, match="restrack"):
        t.assert_balanced()


def test_tracker_clean_run_silent():
    t = ResourceTracker()
    t.acquire("x", "a")
    t.release("x", "a")
    assert t.balance() == 0
    assert t.findings() == []
    t.assert_balanced()


def test_tracker_double_free_detected():
    t = ResourceTracker()
    t.acquire("x", 1)
    t.release("x", 1)
    t.release("x", 1)
    fs = t.findings()
    assert len(fs) == 1 and fs[0].detail.get("double_release")
    with pytest.raises(AssertionError):
        t.assert_balanced()


def test_tracker_release_if_live_is_idempotent():
    t = ResourceTracker()
    t.acquire("x", 1)
    assert t.release_if_live("x", 1)
    assert not t.release_if_live("x", 1)   # no double-free violation
    assert t.findings() == []


def test_env_gate(monkeypatch):
    monkeypatch.delenv("SPARKFLOW_TPU_RESTRACK", raising=False)
    assert not restrack.enabled()
    monkeypatch.setenv("SPARKFLOW_TPU_RESTRACK", "0")
    assert not restrack.enabled()
    monkeypatch.setenv("SPARKFLOW_TPU_RESTRACK", "1")
    assert restrack.enabled()


def test_install_nesting_restores_outer():
    outer, inner = ResourceTracker(), ResourceTracker()
    outer.install()
    try:
        assert restrack.active() is outer
        with inner:
            assert restrack.active() is inner
        assert restrack.active() is outer
    finally:
        outer.uninstall()
    assert restrack.active() is None


def test_zero_overhead_when_off():
    # without an installed tracker every instrumentor is an identity
    # function: same object back, NO wrapper shadowing the methods — the
    # disabled-path cost is the single `_ACTIVE is None` check
    assert restrack.active() is None

    class Pool:
        def acquire(self):
            return (object(), False)

        def release(self, conn, reuse=True):
            pass

    p = Pool()
    assert restrack.instrument_pool(p) is p
    assert "acquire" not in vars(p) and "release" not in vars(p)
    m = Metrics()
    assert restrack.instrument_metrics(m, prefixes=("x/",)) is m
    assert "gauge" not in vars(m)


def test_instrument_pool_tracks_checkouts():
    class Pool:
        def __init__(self):
            self.conn = object()

        def acquire(self):
            return (self.conn, True)

        def release(self, conn, reuse=True):
            pass

    t = ResourceTracker()
    with t:
        p = restrack.instrument_pool(Pool())
        conn, _ = p.acquire()
        assert t.balance("http-conn") == 1
        p.release(conn, reuse=False)
        assert t.balance("http-conn") == 0
    assert t.findings() == []


def test_instrument_metrics_namespaces():
    m = Metrics()
    t = ResourceTracker()
    with t:
        restrack.instrument_metrics(m, prefixes=("router/replica",))
        m.gauge("router/replica0/healthy", 1.0)
        m.gauge("router/replica0/healthy", 0.0)   # same name: one acquire
        m.gauge("router/replica1/depth", 3.0)
        m.gauge("process/uptime", 9.0)            # outside prefixes
        assert t.balance("gauge-ns") == 2
        assert m.remove_prefix("router/replica0/") == 1
        assert t.balance("gauge-ns") == 1
        assert m.remove_matching(r"^router/replica\d+/depth$") == 1
        assert t.balance("gauge-ns") == 0
    assert t.findings() == []
    assert m.gauges() == {"process/uptime": 9.0}


def test_metrics_remove_matching_unit():
    m = Metrics()
    m.gauge("a/1/x", 1.0)
    m.incr("a/2/x")
    m.observe("a/3/x", 0.5)
    m.scalar("b/keep", 2.0)
    assert m.remove_matching(r"^a/\d+/x$") == 3
    assert m.remove_matching(lambda n: n.startswith("b/")) == 1
    assert m.summary()["counters"] == {}


# ---------------------------------------------------------------------------
# chaos leak test: kill a generation mid-stream under the tracker
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_engine():
    import jax
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving import DecodeEngine
    spec = build_registry_spec("transformer_lm", vocab_size=61, hidden=16,
                               num_layers=1, num_heads=2, mlp_dim=32,
                               max_len=64, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    return DecodeEngine(model, params, num_slots=2, page_size=8, seed=0)


def test_chaos_kill_mid_generation_zero_balance(small_engine):
    from sparkflow_tpu.serving import ContinuousBatcher
    engine = small_engine
    t = ResourceTracker().install()
    try:
        restrack.instrument_engine(engine)
        batcher = ContinuousBatcher(engine, max_queue=16)
        restrack.instrument_batcher(batcher)
        futures = [batcher.submit([3, 5, 7], max_new_tokens=48)
                   for _ in range(4)]
        deadline = time.monotonic() + 30.0
        while batcher.inflight_rows() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert batcher.inflight_rows() > 0, "nothing ever got admitted"
        # the client is gone mid-stream: hard close, no drain
        batcher.close(drain=False)
        # every abandoned future must resolve (exception), every slot and
        # admission must be paid back — zero balance or the stacks tell us
        # which acquire leaked
        for f in futures:
            assert f.done()
            if not f.cancelled():
                with pytest.raises(RuntimeError):
                    f.result(timeout=0)
    finally:
        t.uninstall()
    assert t.balance("decode-slot") == 0
    assert t.balance("batch-slot") == 0
    t.assert_balanced()
    assert t.acquired > 0  # the oracle actually saw checkouts


def test_drain_close_is_balanced_too(small_engine):
    from sparkflow_tpu.serving import ContinuousBatcher
    engine = small_engine
    t = ResourceTracker().install()
    try:
        restrack.instrument_engine(engine)
        batcher = ContinuousBatcher(engine, max_queue=16)
        restrack.instrument_batcher(batcher)
        futures = [batcher.submit([2 + i, 9], max_new_tokens=3)
                   for i in range(3)]
        batcher.close(drain=True, timeout=60.0)
        for f in futures:
            out = f.result(timeout=0)
            assert out["num_tokens"] > 0
    finally:
        t.uninstall()
    t.assert_balanced()
    assert t.acquired >= 6  # 3 decode slots + 3 admissions
