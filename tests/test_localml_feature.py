"""Round-2 localml widening: the rest of the pyspark.ml.feature subset
(Tokenizer, StopWordsRemover, StringIndexer, StandardScaler, MinMaxScaler,
Bucketizer) + BinaryClassificationEvaluator. Semantics follow pyspark 2.4,
the reference's pinned Spark (reference ``environment.yml:15``)."""

import numpy as np
import pytest

from sparkflow_tpu.localml import (
    Bucketizer, BinaryClassificationEvaluator, LocalSession, MinMaxScaler,
    Pipeline, StandardScaler, StopWordsRemover, StringIndexer, Tokenizer,
    Vectors)


@pytest.fixture(scope="module")
def spark():
    return LocalSession.builder.getOrCreate()


def test_tokenizer_and_stopwords(spark):
    df = spark.createDataFrame(
        [("The quick brown Fox",), ("IS this THE real life",)], ["text"])
    tok = Tokenizer(inputCol="text", outputCol="words")
    sw = StopWordsRemover(inputCol="words", outputCol="filtered")
    out = sw.transform(tok.transform(df)).collect()
    assert out[0]["words"] == ["the", "quick", "brown", "fox"]
    assert out[0]["filtered"] == ["quick", "brown", "fox"]
    assert out[1]["filtered"] == ["real", "life"]


def test_stopwords_case_sensitive_and_custom(spark):
    df = spark.createDataFrame([(["Keep", "keep", "drop"],)], ["words"])
    sw = StopWordsRemover(inputCol="words", outputCol="out",
                          stopWords=["keep"], caseSensitive=True)
    assert sw.transform(df).collect()[0]["out"] == ["Keep", "drop"]
    assert "the" in StopWordsRemover.loadDefaultStopWords("english")


def test_string_indexer_frequency_order(spark):
    df = spark.createDataFrame(
        [("b",), ("a",), ("b",), ("c",), ("b",), ("a",)], ["cat"])
    model = StringIndexer(inputCol="cat", outputCol="idx").fit(df)
    assert model.labels == ["b", "a", "c"]  # freq desc, ties alphabetical
    got = {r["cat"]: r["idx"] for r in model.transform(df).collect()}
    assert got == {"b": 0.0, "a": 1.0, "c": 2.0}


def test_string_indexer_handle_invalid(spark):
    train = spark.createDataFrame([("a",), ("b",)], ["cat"])
    test = spark.createDataFrame([("a",), ("z",)], ["cat"])
    with pytest.raises(ValueError, match="Unseen label"):
        StringIndexer(inputCol="cat", outputCol="idx").fit(train) \
            .transform(test).collect()
    keep = StringIndexer(inputCol="cat", outputCol="idx",
                         handleInvalid="keep").fit(train).transform(test)
    assert [r["idx"] for r in keep.collect()] == [0.0, 2.0]
    skip = StringIndexer(inputCol="cat", outputCol="idx",
                         handleInvalid="skip").fit(train).transform(test)
    assert [r["cat"] for r in skip.collect()] == ["a"]


def test_standard_scaler_matches_numpy(spark):
    rs = np.random.RandomState(0)
    mat = rs.rand(20, 3) * np.array([1.0, 10.0, 100.0]) + 5
    df = spark.createDataFrame([(Vectors.dense(row),) for row in mat], ["f"])
    m = StandardScaler(inputCol="f", outputCol="s", withMean=True,
                       withStd=True).fit(df)
    out = np.stack([np.asarray(r["s"].toArray())
                    for r in m.transform(df).collect()])
    expect = (mat - mat.mean(0)) / mat.std(0, ddof=1)
    np.testing.assert_allclose(out, expect, atol=1e-12)
    # default: withMean=False
    m2 = StandardScaler(inputCol="f", outputCol="s").fit(df)
    out2 = np.stack([np.asarray(r["s"].toArray())
                     for r in m2.transform(df).collect()])
    np.testing.assert_allclose(out2, mat / mat.std(0, ddof=1), atol=1e-12)


def test_min_max_scaler_with_constant_feature(spark):
    mat = np.array([[0.0, 7.0], [5.0, 7.0], [10.0, 7.0]])
    df = spark.createDataFrame([(Vectors.dense(row),) for row in mat], ["f"])
    m = MinMaxScaler(inputCol="f", outputCol="s").fit(df)
    out = np.stack([np.asarray(r["s"].toArray())
                    for r in m.transform(df).collect()])
    np.testing.assert_allclose(out[:, 0], [0.0, 0.5, 1.0])
    np.testing.assert_allclose(out[:, 1], [0.5, 0.5, 0.5])  # constant -> mid


def test_bucketizer(spark):
    df = spark.createDataFrame([(x,) for x in [-0.5, 0.0, 0.4, 1.0, 2.0]],
                               ["v"])
    b = Bucketizer(splits=[-1.0, 0.0, 1.0, 2.0], inputCol="v",
                   outputCol="bucket")
    got = [r["bucket"] for r in b.transform(df).collect()]
    assert got == [0.0, 1.0, 1.0, 2.0, 2.0]  # upper bound inclusive at end
    # out-of-range ALWAYS raises (Spark 2.4), even with handleInvalid=keep
    oob = spark.createDataFrame([(99.0,)], ["v"])
    with pytest.raises(ValueError, match="out of bucket range"):
        b.transform(oob).collect()
    b_keep = Bucketizer(splits=[-1.0, 0.0, 1.0, 2.0], inputCol="v",
                        outputCol="bucket", handleInvalid="keep")
    with pytest.raises(ValueError, match="out of bucket range"):
        b_keep.transform(oob).collect()
    # handleInvalid governs NaN entries only: keep -> extra bucket
    nan_df = spark.createDataFrame([(float("nan"),)], ["v"])
    assert b_keep.transform(nan_df).collect()[0]["bucket"] == 3.0
    with pytest.raises(ValueError, match="NaN"):
        b.transform(nan_df).collect()


def test_binary_evaluator_auc(spark):
    # perfectly separable scores -> AUC 1; anti-separable -> 0
    rows = [(1.0, 0.9), (1.0, 0.8), (0.0, 0.2), (0.0, 0.1)]
    df = spark.createDataFrame(rows, ["label", "rawPrediction"])
    ev = BinaryClassificationEvaluator()
    assert ev.evaluate(df) == pytest.approx(1.0)
    rows = [(0.0, 0.9), (0.0, 0.8), (1.0, 0.2), (1.0, 0.1)]
    assert ev.evaluate(
        spark.createDataFrame(rows, ["label", "rawPrediction"])) \
        == pytest.approx(0.0)
    # random-ish interleave: AUC strictly between
    rows = [(1.0, 0.9), (0.0, 0.8), (1.0, 0.7), (0.0, 0.6)]
    auc = ev.evaluate(spark.createDataFrame(rows, ["label", "rawPrediction"]))
    assert auc == pytest.approx(0.75)
    # tied scores get half credit and the result is row-order independent
    ties = [(1.0, 0.5), (0.0, 0.5)]
    assert ev.evaluate(
        spark.createDataFrame(ties, ["label", "rawPrediction"])) \
        == pytest.approx(0.5)
    assert ev.evaluate(
        spark.createDataFrame(ties[::-1], ["label", "rawPrediction"])) \
        == pytest.approx(0.5)
    # vector scores: last component is the positive-class score
    rows = [(1.0, Vectors.dense([0.1, 0.9])), (0.0, Vectors.dense([0.9, 0.1]))]
    assert ev.evaluate(
        spark.createDataFrame(rows, ["label", "rawPrediction"])) \
        == pytest.approx(1.0)
    # areaUnderPR on separable data is 1
    ev_pr = BinaryClassificationEvaluator(metricName="areaUnderPR")
    rows = [(1.0, 0.9), (1.0, 0.8), (0.0, 0.2), (0.0, 0.1)]
    assert ev_pr.evaluate(
        spark.createDataFrame(rows, ["label", "rawPrediction"])) \
        == pytest.approx(1.0)


def test_text_pipeline_end_to_end(spark):
    """Tokenize -> remove stop words -> index a label -> all inside a
    Pipeline; the save/load round-trip goes through the localml dill path."""
    import tempfile

    rows = [("the good movie", "pos"), ("a bad film", "neg"),
            ("good good film", "pos"), ("the bad one", "neg")]
    df = spark.createDataFrame(rows, ["text", "sentiment"])
    pipe = Pipeline(stages=[
        Tokenizer(inputCol="text", outputCol="words"),
        StopWordsRemover(inputCol="words", outputCol="filtered"),
        StringIndexer(inputCol="sentiment", outputCol="label"),
    ])
    model = pipe.fit(df)
    out = model.transform(df).collect()
    assert out[0]["filtered"] == ["good", "movie"]
    assert {r["label"] for r in out} == {0.0, 1.0}

    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/pipe"
        model.write().overwrite().save(path)
        from sparkflow_tpu.localml import PipelineModel
        loaded = PipelineModel.load(path)
        again = loaded.transform(df).collect()
        assert [r["label"] for r in again] == [r["label"] for r in out]
