"""Elastic bounded-staleness DP under chaos: staleness bounds, dampening,
lease membership, convergence parity with sync, and the ISSUE-6 acceptance
scenarios (10x straggler >= 3x sync throughput; mid-run preemption rejoins
without stalling survivors) — all deterministic. Every straggler/preemption
assertion runs on the virtual-time engine (``run_virtual``): simulated
seconds, zero sleeps on the assert path."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.parallel.elastic import (ElasticDPEngine,
                                            ElasticParamStore,
                                            ReplicaSpec, SparseRows,
                                            decode_grads, encode_grads,
                                            sync_baseline_examples_per_sec)
from sparkflow_tpu.resilience import faults
from sparkflow_tpu.trainer import Trainer
from sparkflow_tpu.utils.metrics import Metrics


# -- shared convex workload --------------------------------------------------
# linear regression: sync and async both reach the SAME global minimum, so
# parity can be asserted tightly (a nonconvex net would compare different
# local minima and prove nothing)

N, D = 256, 4


def _problem():
    rs = np.random.RandomState(0)
    X = rs.rand(N, D).astype(np.float32)
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    Y = X @ w + 0.01 * rs.randn(N, 1).astype(np.float32)
    return X, Y


def _loss_fn(params, x, y, mask, rng):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _params0():
    return {"w": jnp.zeros((D, 1)), "b": jnp.zeros((1,))}


def _shards(X, Y, k):
    return [(X[i::k], Y[i::k]) for i in range(k)]


def _engine(**kw):
    kw.setdefault("metrics", Metrics())
    return ElasticDPEngine(_loss_fn, optax.adam(0.05), _params0(), **kw)


# -- dense/sparse codec (the Parallax split) --------------------------------

def test_encode_decode_roundtrip_and_routing():
    g = {"emb": np.zeros((100, 8), np.float32),
         "w": np.ones((4, 4), np.float32),
         "b": np.ones((7,), np.float32)}
    g["emb"][[3, 7, 42]] = 1.5
    enc, dense_bytes, wire_bytes = encode_grads(g, 0.25)
    # 3/100 rows touched -> sparse; dense 4x4 and the rank-1 bias stay dense
    assert isinstance(enc["emb"], SparseRows)
    assert not isinstance(enc["w"], SparseRows)
    assert not isinstance(enc["b"], SparseRows)
    assert wire_bytes < dense_bytes
    dec = decode_grads(enc)
    np.testing.assert_array_equal(dec["emb"], g["emb"])
    np.testing.assert_array_equal(dec["w"], g["w"])


def test_encode_density_threshold_and_disable():
    g = {"emb": np.ones((10, 4), np.float32)}  # fully dense rows
    enc, _db, _wb = encode_grads(g, 0.25)
    assert not isinstance(enc["emb"], SparseRows)  # 100% density stays dense
    g2 = {"emb": np.zeros((10, 4), np.float32)}
    g2["emb"][0] = 1.0
    enc2, _db, _wb = encode_grads(g2, None)  # split disabled
    assert not isinstance(enc2["emb"], SparseRows)
    enc3, _db, wb3 = encode_grads(g2, 0.25)
    assert isinstance(enc3["emb"], SparseRows)
    assert enc3["emb"].indices.tolist() == [0]


def test_sparse_push_matches_dense_push():
    """An embedding-style sparse push must apply the SAME update as its
    densified twin — the wire format changes bytes, not math."""
    params = {"emb": jnp.zeros((20, 4)), "w": jnp.zeros((3, 3))}
    g = {"emb": np.zeros((20, 4), np.float32),
         "w": np.ones((3, 3), np.float32)}
    g["emb"][5] = 2.0

    outs = []
    for grads in (g, encode_grads(g, 0.25)[0]):
        store = ElasticParamStore(params, optax.sgd(0.1), metrics=Metrics())
        store.join("r0")
        res = store.push("r0", grads, 0)
        assert res.accepted
        outs.append(res.params)
    np.testing.assert_allclose(np.asarray(outs[0]["emb"]),
                               np.asarray(outs[1]["emb"]), atol=1e-7)
    np.testing.assert_allclose(np.asarray(outs[0]["w"]),
                               np.asarray(outs[1]["w"]), atol=1e-7)


# -- versioned store: staleness bound, dampening, membership ----------------

def _sgd_store(**kw):
    kw.setdefault("metrics", Metrics())
    return ElasticParamStore({"w": jnp.zeros((2,))}, optax.sgd(1.0), **kw)


def _g(v=1.0):
    return {"w": np.full((2,), v, np.float32)}


def test_staleness_bound_enforced():
    store = _sgd_store(max_staleness=2, dampening="none")
    store.join("fast")
    store.join("slow")
    v0, _ = store.pull("slow")
    for _ in range(3):  # fast pushes advance the version to 3
        v, p = store.pull("fast")
        assert store.push("fast", _g(), v).accepted
    res = store.push("slow", _g(), v0)  # staleness 3 > bound 2
    assert not res.accepted and res.reason == "stale" and res.staleness == 3
    assert res.version == 3 and res.params is not None  # piggybacked refresh
    # after refreshing to the piggybacked version the push lands
    res2 = store.push("slow", _g(), res.version)
    assert res2.accepted and res2.staleness == 0
    assert store.version == 4  # rejected push did NOT bump the version


def test_dampening_scales_update_by_staleness():
    # sgd(1.0): accepted update == -scale * grad, so params expose the scale
    store = _sgd_store(max_staleness=5, dampening="inverse")
    store.join("a")
    store.join("b")
    va, _ = store.pull("a")
    for _ in range(3):
        v, _p = store.pull("b")
        store.push("b", _g(0.0), v)  # zero grads: version moves, params don't
    res = store.push("a", _g(1.0), va)  # staleness 3 -> scale 1/4
    assert res.accepted and res.scale == pytest.approx(0.25)
    np.testing.assert_allclose(np.asarray(res.params["w"]),
                               [-0.25, -0.25], atol=1e-6)
    # constant dampening: a callable is honored as-is
    store2 = _sgd_store(max_staleness=5, dampening=lambda s: 0.5)
    store2.join("a")
    res2 = store2.push("a", _g(1.0), 0)
    assert res2.scale == pytest.approx(0.5)
    with pytest.raises(ValueError, match="dampening"):
        _sgd_store(dampening="bogus")


def test_lease_expiry_and_rejoin():
    t = [0.0]
    store = _sgd_store(lease_ttl_s=5.0, clock=lambda: t[0])
    v, _ = store.join("r0")
    assert store.alive_count() == 1
    t[0] = 3.0
    assert store.heartbeat("r0")  # renewed inside the ttl
    t[0] = 9.1  # 6.1s since the renewal > ttl
    res = store.push("r0", _g(), v)
    assert not res.accepted and res.reason == "lease_expired"
    assert store.alive_count() == 0 and store.evictions == 1
    v2, _ = store.join("r0")  # rejoin: pushes count again
    assert store.push("r0", _g(), v2).accepted
    assert not store.heartbeat("ghost")  # never joined


def test_membership_and_metrics_published():
    m = Metrics()
    store = ElasticParamStore({"w": jnp.zeros((2,))}, optax.sgd(1.0),
                              metrics=m, max_staleness=3)
    store.join("a")
    store.join("b")
    assert m.gauges()["elastic/replicas"] == 2
    v, _ = store.pull("a")
    store.push("a", _g(), v)
    store.leave("b")
    assert m.gauges()["elastic/replicas"] == 1
    mem = store.membership()
    assert set(mem) == {"a"} and mem["a"].pushes == 1
    assert m.counters()["elastic/push_accepted"] == 1
    assert m.histograms()["elastic/staleness"]["count"] == 1


def test_store_rejects_negative_max_staleness():
    with pytest.raises(ValueError, match="max_staleness"):
        _sgd_store(max_staleness=-1)


def test_concurrent_pushes_serialize():
    """8 threads x 25 unbounded-staleness pushes: every accepted push bumps
    the version exactly once (the store's lock discipline, observed from
    outside)."""
    store = _sgd_store(max_staleness=10**9, dampening="none")
    for i in range(8):
        store.join(f"r{i}")
    accepted = [0] * 8

    def worker(i):
        v, _p = store.pull(f"r{i}")
        for _ in range(25):
            res = store.push(f"r{i}", _g(0.0), v)
            v = res.version
            accepted[i] += int(res.accepted)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert sum(accepted) == 200 == store.version


# -- convergence: threaded engine vs sync DP --------------------------------

def test_threaded_convergence_parity_with_sync():
    """ISSUE-6 acceptance: elastic final loss within 5% of the sync baseline.
    Convex problem; sync == sequential full passes (dp=1 barrier semantics),
    elastic == 4 async replicas through the versioned store."""
    X, Y = _problem()

    params = _params0()
    opt = optax.adam(0.05)
    state = opt.init(params)
    grad = jax.jit(jax.value_and_grad(_loss_fn))
    rs = np.random.RandomState(0)
    for _epoch in range(30):
        for idx in np.array_split(rs.permutation(N), N // 16):
            _l, g = grad(params, X[idx], Y[idx], None, None)
            upd, state = opt.update(g, state, params)
            params = optax.apply_updates(params, upd)
    sync_final = float(_loss_fn(params, X, Y, None, None))

    eng = _engine(max_staleness=4)
    res = eng.run_threads(_shards(X, Y, 4), epochs=30, batch_size=16, seed=0)
    elastic_final = float(_loss_fn(res.params, X, Y, None, None))

    # both sit at the noise floor of the convex problem; the 5%-of-sync
    # acceptance bound allows the async path its staleness noise
    assert elastic_final <= sync_final * 1.05 + 1e-4, (
        f"elastic {elastic_final:.6f} vs sync {sync_final:.6f}")
    assert res.losses[-1] < res.losses[0]
    assert res.stats["accepted"] > 0
    assert res.version == res.stats["accepted"]


def test_threaded_single_replica_is_plain_sgd():
    """1 replica: no concurrency, staleness always 0, nothing rejected —
    the degenerate case HogwildTrainer hits on a 1-partition RDD."""
    X, Y = _problem()
    eng = _engine(max_staleness=0)
    res = eng.run_threads(_shards(X, Y, 1), epochs=20, batch_size=32, seed=0)
    assert res.stats["rejected_stale"] == 0
    assert res.stats["accepted"] == res.version == 20 * (N // 32)
    assert res.losses[-1] < 0.05


# -- virtual time: the ISSUE-6 chaos scenarios ------------------------------

def test_straggler_throughput_at_least_3x_sync():
    """ISSUE-6 acceptance: with a deterministic 10x straggler on one of 4
    replicas, elastic sustains >= 3x the sync-barrier throughput of the SAME
    fleet (sync bound = ideal lockstep gated on the slowest replica)."""
    X, Y = _problem()
    costs = [1.0, 1.0, 1.0, 10.0]
    eng = _engine(max_staleness=4)
    res = eng.run_virtual(_shards(X, Y, 4),
                          [ReplicaSpec(cost_s=c) for c in costs],
                          epochs=100, batch_size=16, seed=0, deadline_s=60.0)
    sync_eps = sync_baseline_examples_per_sec(costs, 16)
    assert res.examples_per_sec >= 3.0 * sync_eps, (
        f"elastic {res.examples_per_sec:.1f} ex/s < 3x sync "
        f"{sync_eps:.1f} ex/s")
    # the straggler delayed only ITSELF: fast replicas each accepted ~60
    # pushes while it managed a handful — and nobody stalled (losses moved)
    acc = res.stats["per_replica_accepted"]
    assert all(acc[f"replica-{i}"] >= 50 for i in range(3))
    assert acc["replica-3"] <= 10
    assert res.losses[-1] < res.losses[0]


def test_straggler_loss_parity_with_sync():
    """Same 10x-straggler fleet, loss side of the acceptance bar: the
    elastic final loss stays within 5% of the sync baseline trained on the
    same workload (both reach the convex optimum; the straggler's rare stale
    pushes must not poison it)."""
    X, Y = _problem()

    params = _params0()
    opt = optax.adam(0.05)
    state = opt.init(params)
    grad = jax.jit(jax.value_and_grad(_loss_fn))
    rs = np.random.RandomState(0)
    for _epoch in range(30):
        for idx in np.array_split(rs.permutation(N), N // 16):
            _l, g = grad(params, X[idx], Y[idx], None, None)
            upd, state = opt.update(g, state, params)
            params = optax.apply_updates(params, upd)
    sync_final = float(_loss_fn(params, X, Y, None, None))

    # the elastic fleet trains 2x the epochs: staleness dampening trades
    # per-step progress for never stalling, and its >= 3x barrier-free
    # throughput (previous test) means 60 elastic epochs still finish in
    # HALF the sync fleet's virtual wall-clock (fast replicas: 60*4*1s =
    # 240 vsec vs sync's 30*16*10s barrier = 4800 vsec)
    eng = _engine(max_staleness=4)
    res = eng.run_virtual(_shards(X, Y, 4),
                          [ReplicaSpec(1.0), ReplicaSpec(1.0),
                           ReplicaSpec(1.0), ReplicaSpec(10.0)],
                          epochs=60, batch_size=16, seed=0)
    elastic_final = float(_loss_fn(res.params, X, Y, None, None))
    assert elastic_final <= sync_final * 1.05 + 1e-4, (
        f"elastic {elastic_final:.6f} vs sync {sync_final:.6f}")


def test_preemption_mid_step_rejoins_without_stalling():
    """ISSUE-6 acceptance: a replica preempted mid-step loses its in-flight
    gradient and its lease, the survivors keep training at full rate, and
    the replica re-joins later and contributes again."""
    X, Y = _problem()
    eng = _engine(max_staleness=4, lease_ttl_s=3.0)
    specs = [ReplicaSpec(1.0), ReplicaSpec(1.0),
             ReplicaSpec(1.0, preempt_at=5.5, rejoin_at=15.0),
             ReplicaSpec(1.0)]
    res = eng.run_virtual(_shards(X, Y, 4), specs, epochs=12,
                          batch_size=16, seed=0)
    assert res.stats["evictions"] == 1  # the lease expired while it was gone
    acc = res.stats["per_replica_accepted"]
    total_steps = 12 * (64 // 16)
    # survivors never stalled: they completed every step, and their steps
    # kept landing DURING the outage window (membership dropped to 3 yet
    # the store version kept advancing)
    for i in (0, 1, 3):
        assert acc[f"replica-{i}"] + res.stats["dropped_stale"] >= total_steps - 1
    trace = res.stats["membership_trace"]
    during = [a for t, a in trace if 9.0 <= t < 15.0]
    assert during and max(during) == 3
    # the preempted replica re-joined and finished its remaining work
    assert acc["replica-2"] > 0
    rejoined = [a for t, a in trace if 15.0 <= t < 20.0]
    assert rejoined and max(rejoined) == 4


def test_replica_join_leave_mid_training():
    """Elastic width: a late replica joins a running fleet (dp width 2 -> 3)
    and an early-finishing fleet shrinks back — no restart, versions keep
    climbing monotonically."""
    X, Y = _problem()
    eng = _engine(max_staleness=6)
    specs = [ReplicaSpec(1.0), ReplicaSpec(1.0),
             ReplicaSpec(1.0, join_at=10.0)]
    res = eng.run_virtual(_shards(X, Y, 3), specs, epochs=8,
                          batch_size=16, seed=0)
    trace = res.stats["membership_trace"]
    alive_before = [a for t, a in trace if t < 10.0]
    alive_after = [a for t, a in trace if 10.0 <= t < 15.0]
    assert max(alive_before) == 2 and max(alive_after) == 3
    assert res.stats["per_replica_accepted"]["replica-2"] > 0
    versions = []  # monotonic store version implied by accepted == version
    assert res.version == res.stats["accepted"] > 0 or versions == []


def test_delayed_push_fault_costs_virtual_time_only():
    """faults.inject(delay_ms=...) on elastic.push: the delay lands on the
    VIRTUAL clock (store.fault_sleep), so the wall-clock assert path never
    sleeps. The 2000s delay also dwarfs the lease TTL — every push arrives
    lease-expired — so this doubles as the no-livelock pin: the bounded
    lease-retry rule drops each batch after one fresh re-join instead of
    re-joining forever."""
    import time as _time
    X, Y = _problem()
    eng = _engine(max_staleness=10)
    t0 = _time.perf_counter()
    with faults.inject("elastic.push", delay_ms=2_000_000.0) as spec:
        res = eng.run_virtual(_shards(X, Y, 2),
                              [ReplicaSpec(1.0), ReplicaSpec(1.0)],
                              epochs=2, batch_size=32, seed=0)
    wall = _time.perf_counter() - t0
    assert spec.calls == res.stats["pushes"] > 0
    # bounded work: one retry per batch, then the batch is dropped
    total_steps = 2 * 2 * (X[::2].shape[0] // 32)
    assert res.stats["dropped_lease"] == total_steps
    assert res.stats["pushes"] == 2 * total_steps
    # every push paid 2000 virtual seconds; none of it was slept
    assert res.wall_s >= 2000.0
    assert wall < 600.0  # engine overhead only (CI-loose; locally ~seconds)


def test_dropped_push_fault_is_counted_not_fatal():
    """A push that dies in transport (InjectedFault) loses that gradient —
    the replica resyncs and moves on; training completes and the drop is
    accounted. The reference printed and dropped; we count and drop."""
    X, Y = _problem()
    eng = _engine(max_staleness=10)
    with faults.inject("elastic.push", fail_calls=(1, 3)):
        res = eng.run_virtual(_shards(X, Y, 2),
                              [ReplicaSpec(1.0), ReplicaSpec(1.0)],
                              epochs=4, batch_size=32, seed=0)
    assert res.stats["dropped_fault"] == 2
    # dropped steps still advance the replica's pointer: the run terminates
    # with every non-dropped step accepted
    assert res.stats["accepted"] == res.version
    assert res.stats["accepted"] + res.stats["dropped_fault"] \
        + res.stats["dropped_stale"] == 2 * 4 * (X[::2].shape[0] // 32)


def test_persistent_straggler_never_livelocks():
    """max_staleness=0 with a 10x straggler: every straggler push is stale,
    every recompute is stale again — the one-retry-then-drop rule must
    terminate the run (bounded work), counting the drops."""
    X, Y = _problem()
    eng = _engine(max_staleness=0)
    res = eng.run_virtual(_shards(X, Y, 3),
                          [ReplicaSpec(1.0), ReplicaSpec(1.0),
                           ReplicaSpec(10.0)],
                          epochs=3, batch_size=32, seed=0)
    # termination IS the assertion; the straggler's work was mostly dropped
    assert res.stats["dropped_stale"] > 0
    assert res.stats["per_replica_accepted"]["replica-2"] \
        + res.stats["dropped_stale"] >= 3 * (X[::3].shape[0] // 32)


# -- Trainer / Hogwild wiring ------------------------------------------------

def _xor_graph():
    x = nn.placeholder([None, 2], name="x")
    y = nn.placeholder([None, 1], name="y")
    h = nn.dense(x, 8, activation="tanh")
    out = nn.dense(h, 1, name="out")
    nn.sigmoid_cross_entropy(y, out)


def _xor_data(n=128):
    rs = np.random.RandomState(0)
    X = rs.rand(n, 2).astype(np.float32)
    Y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.5)).astype(np.float32)
    return X, Y


def test_trainer_elastic_dp_strategy():
    X, Y = _xor_data()
    t = Trainer(build_graph(_xor_graph), "x:0", "y:0", optimizer="adam",
                optimizer_options={"learning_rate": 0.05}, iters=20,
                mini_batch_size=16, strategy="elastic_dp",
                elastic={"replicas": 4, "max_staleness": 4})
    res = t.fit(X, Y)
    assert res.stop_reason == "completed"
    assert res.losses[-1] < res.losses[0]
    assert t.last_elastic_stats["accepted"] > 0
    assert len(t.weights_list()) == 4  # two dense layers: w+b each
    # warm start accepted (params copied, not donated)
    res2 = t.fit(X, Y, init_params=t.params)
    assert np.isfinite(res2.losses).all()


def test_trainer_elastic_loss_callback_and_validation():
    X, Y = _xor_data(64)
    seen = []
    t = Trainer(build_graph(_xor_graph), "x:0", "y:0", iters=3,
                mini_batch_size=16, strategy="elastic_dp",
                elastic={"replicas": 2},
                loss_callback=lambda l, step, rid: seen.append((rid, step, l)))
    t.fit(X, Y)
    assert len(seen) == t.last_elastic_stats["accepted"]
    assert {rid for rid, _s, _l in seen} == {0, 1}

    with pytest.raises(ValueError, match="strategy"):
        Trainer(build_graph(_xor_graph), "x:0", "y:0", strategy="warp")
    with pytest.raises(ValueError, match="elastic_dp"):
        Trainer(build_graph(_xor_graph), "x:0", "y:0",
                elastic={"replicas": 2})
    with pytest.raises(ValueError, match="unknown elastic option"):
        Trainer(build_graph(_xor_graph), "x:0", "y:0",
                strategy="elastic_dp", elastic={"bogus": 1})
    with pytest.raises(ValueError, match="replicas"):
        Trainer(build_graph(_xor_graph), "x:0", "y:0",
                strategy="elastic_dp",
                elastic={"replicas": 0}).fit(X, Y)


def test_hogwild_trainer_trains_async():
    """HogwildTrainer now actually trains Hogwild-style: through the elastic
    engine, one replica per partition."""
    from sparkflow_tpu.hogwild import HogwildSparkModel

    X, Y = _xor_data(64)
    hw = HogwildSparkModel(
        tensorflowGraph=build_graph(_xor_graph), iters=5, tfInput="x:0",
        tfLabel="y:0", optimizer="adam", master_url="localhost:5000",
        mini_batch=16)
    weights = hw.train(list(zip(X, Y)))  # plain iterable -> 4 replicas
    assert len(weights) == 4
    assert hw.elastic_stats is not None
    assert hw.elastic_stats["accepted"] > 0
    assert hw._trainer.elastic["replicas"] == 4
    hw.stop_server()  # still a no-op, still callable


# -- satellite: dp-less mesh regression (trainer-level) ----------------------

def test_trainer_fit_on_dp_less_mesh():
    """Regression (ADVICE / ISSUE-6 satellite): a mesh WITHOUT a 'dp' axis
    must train via the replicated-rows fallback (core._rows_spec -> P()),
    not die inside GSPMD with an unknown-axis error."""
    from sparkflow_tpu.parallel.mesh import make_mesh

    X, Y = _xor_data(64)
    t = Trainer(build_graph(_xor_graph), "x:0", "y:0", iters=4,
                mini_batch_size=16, mesh=make_mesh({"fsdp": 8}))
    res = t.fit(X, Y)
    assert res.stop_reason == "completed"
    assert np.isfinite(res.losses).all()
