"""Shared plumbing for registry models: the GraphModel duck type.

A registry model exposes the same executable surface as
:class:`sparkflow_tpu.graphdef.GraphModel` — ``init``, ``apply(params, feeds,
outputs, train, rng)``, ``loss_vector``, ``param_specs`` (ordered, for the flat
weight-list wire format), ``input_specs`` and a ``graphdef.resolve`` shim for
tensor-name validation — so Trainer / predict_func / model_loader work on it
unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class _Names:
    """graphdef.resolve-compatible tensor-name table."""

    def __init__(self, names: Sequence[str]):
        self._names = {}
        for i, n in enumerate(names):
            self._names[n] = i
            self._names[f"{n}:0"] = i

    def resolve(self, tensor_name: str) -> int:
        for cand in (tensor_name, f"{tensor_name}:0"):
            if cand in self._names:
                return self._names[cand]
        known = ", ".join(sorted(k for k in self._names if not k.endswith(":0")))
        raise KeyError(f"tensor {tensor_name!r} not found; known tensors: {known}")


class RegistryModel:
    """Base for registry models. Subclasses define:

    - ``TENSORS``: output/input tensor names exposed to the estimator params
    - ``input_specs()``, ``param_specs()`` (ordered), ``init(rng)``
    - ``_forward(params, feeds, train, rng) -> dict of named tensors``
    - ``_loss(params, feeds, train, rng) -> per-example loss vector``
    """

    TENSORS: Sequence[str] = ()

    # model families whose evals consume int8-quantized trees set this True
    # (the transformer family); serving refuses quantized trees otherwise
    # instead of silently computing f32
    SUPPORTS_INT8_SERVING = False

    def __init__(self, compute_dtype: Optional[Any] = None):
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if isinstance(compute_dtype, str) else compute_dtype)
        self.quant_mode: Optional[str] = None
        self.graphdef = _Names(self.TENSORS)

    # -- GraphModel-compatible surface ---------------------------------------

    def apply(self, params, feeds: Dict[str, Any], outputs: Sequence[str],
              train: bool = False, rng=None) -> Dict[str, Any]:
        feeds = {k.split(":")[0]: v for k, v in feeds.items()}
        vals = self._forward(params, feeds, train, rng)
        out = {}
        for o in outputs:
            key = o.split(":")[0] if isinstance(o, str) else o
            if key not in vals:
                raise KeyError(f"tensor {o!r} not produced; have {sorted(vals)}")
            out[o] = vals[key]
        return out

    def loss_vector(self, params, feeds: Dict[str, Any], train: bool = True,
                    rng=None):
        feeds = {k.split(":")[0]: v for k, v in feeds.items()}
        return self._loss(params, feeds, train, rng)

    def init(self, rng):
        params = {}
        for lname, pspec in self.param_specs().items():
            layer = {}
            for pname, (shape, init_name) in pspec.items():
                rng, sub = jax.random.split(rng)
                layer[pname] = _initializer(init_name)(sub, shape, jnp.float32)
            params[lname] = layer
        return params

    def quantize_for_serving(self, params, mode: str = "weight_only",
                             min_size: int = 4096):
        """int8-quantize a trained params tree for inference (families with
        ``SUPPORTS_INT8_SERVING``; ``utils/quant.py``)."""
        if not self.SUPPORTS_INT8_SERVING:
            raise ValueError(
                f"{type(self).__name__} does not support int8 serving; "
                f"the transformer family and graphdef models do")
        from ..utils.quant import quantize_for_serving
        return quantize_for_serving(self, params, mode, min_size)

    # -- helpers --------------------------------------------------------------

    def cast(self, x):
        if self.compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x


def _initializer(name: str):
    from ..graphdef import _get_initializer
    return _get_initializer(name)


def softmax_xent(logits, y):
    """Per-example softmax cross entropy accepting EITHER one-hot labels
    [N, C] or class-index labels ([N] / [N, 1] — what the estimator's
    scalar ``labelCol`` marshalling produces, reference ``ml_util.py:
    86-101``). Index labels are one-hot'd here; without this, a [N, 1]
    label column silently broadcasts against [N, C] logits and the loss
    is meaningless."""
    y = jnp.asarray(y)
    c = logits.shape[-1]
    if y.ndim == logits.ndim and y.shape[-1] == c:
        onehot = y.astype(jnp.float32)
    else:
        idx = y.reshape(y.shape[0]).astype(jnp.int32)
        onehot = jax.nn.one_hot(idx, c, dtype=jnp.float32)
    return -jnp.sum(onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1)
