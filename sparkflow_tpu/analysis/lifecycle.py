"""Resource-lifecycle lint (GC-X601–X604): every acquire released on every
path.

The serving plane is a web of paired operations — a KV slot allocated by
``DecodeEngine.prefill`` must reach ``release``, a pooled connection checked
out of a :class:`~sparkflow_tpu.serving.client.ConnectionPool` must be
returned, a started worker thread must be joined, a ``router/replica<i>/*``
gauge namespace must be removed when the replica deregisters. The test
suite can only spot-check these pairings; this pass checks them statically,
over the whole package, reusing the same class/attribute type inference the
lock graph uses (:mod:`~sparkflow_tpu.analysis.lockgraph`), so
``self._pool.acquire()`` resolves through the ``ConnectionPool(...)``
assignment in ``__init__`` and ``replica.pool.acquire()`` resolves through
``Replica``'s annotated attributes.

What each rule means (the registry of pairs is :data:`PAIRS`):

- **GC-X601** (leak-on-escape): a registered acquire whose handle neither
  reaches a matching release nor transfers ownership (stored onto
  ``self``/a container, passed to a callee, returned) before an explicit
  escape — ``return``/``raise``/``break`` — leaves the function with the
  resource still held. ``with`` context managers and ``try/finally``
  releases are recognized; escapes inside the ``except`` handlers of the
  acquiring ``try`` are exempt (the acquire itself failed — there is
  nothing to release).
- **GC-X602** (release-skipped-on-error): the acquire *does* have a
  matching release later in the function, but code between them can raise
  (it contains calls) and nothing routes the error branch through the
  release — no ``finally``, no handler that releases. One exception and
  the resource leaks.
- **GC-X603** (unreaped-thread): a ``threading.Thread`` (or
  ``subprocess.Popen``) that is ``start()``-ed in a scope — a class, for
  ``self.<attr>`` threads, or one function, for locals — with no
  ``join``/``wait``/``kill``/``terminate`` anywhere in that scope, and no
  ownership transfer out of it.
- **GC-X604** (gauge-namespace-leak): a class publishes metrics under a
  *dynamic* namespace (an f-string name — per-replica, per-version,
  per-tenant) and has lifecycle-end methods (``stop``/``close``/
  ``deregister``/...), but none of them — directly or through a ``self.``
  call — ever calls ``Metrics.remove_prefix``/``remove_matching``. Every
  entity that ever existed stays in the exposition forever. Static gauge
  names are process-level state and exempt.

The dynamic twin of this pass is :mod:`~sparkflow_tpu.analysis.restrack`:
the same registry of pairs, enforced at runtime with per-resource balances
and acquisition stacks (``SPARKFLOW_TPU_RESTRACK=1``).

Intentional sites are suppressed inline — ``# graftcheck: disable=GC-X601``
on the flagged line — the same syntax every other analyzer honors.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .ast_lint import _attr_chain, iter_py_files
from .findings import Finding, filter_suppressed
from .lockgraph import _ClassInfo, _index_class, _module_name

__all__ = ["PAIRS", "ResourcePair", "lint_paths", "lint_source"]


@dataclass(frozen=True)
class ResourcePair:
    """One acquire/release pairing the analyzers (and the runtime
    :class:`~sparkflow_tpu.analysis.restrack.ResourceTracker`) enforce.

    ``owner`` is the class whose *instances* the methods are called on
    (resolved through attribute/local type inference); ``owner=None`` pairs
    match on bare/dotted call names instead (``tempfile.mkdtemp``).
    ``handle=False`` pairs have no caller-owned handle (gauge registration)
    and are checked only by their dedicated rule.
    """

    name: str
    owner: Optional[str]
    acquire: Tuple[str, ...]
    release: Tuple[str, ...]
    handle: bool = True
    #: when set, the caller-owned handle is this positional *argument* of
    #: the acquire, not its return value (``kv.alloc(slot, ...)`` — the
    #: caller names the slot; ``free(slot)`` takes the same name back)
    handle_arg: Optional[int] = None
    description: str = ""


#: The declarative acquire/release registry — the single source of truth
#: shared by GC-X601/X602 (handle pairs), GC-X603 (thread/subprocess pairs,
#: matched on ctor), GC-X604 (the gauge pair), and the runtime tracker.
PAIRS: Tuple[ResourcePair, ...] = (
    ResourcePair("kv-pages", "PagedKVCache", ("alloc",),
                 ("free", "truncate"), handle_arg=0,
                 description="paged KV slot + its pages"),
    ResourcePair("decode-slot", "DecodeEngine", ("prefill",), ("release",),
                 description="decode slot admitted by prefill"),
    ResourcePair("batch-slot", "ContinuousBatcher", ("_try_admit_locked",),
                 ("_finish",),
                 description="batcher admission (popped request -> retire)"),
    ResourcePair("http-conn", "ConnectionPool", ("acquire",),
                 ("release", "close"),
                 description="pooled keep-alive connection checkout"),
    ResourcePair("gauge-ns", "Metrics", ("gauge",),
                 ("remove_prefix", "remove_matching"), handle=False,
                 description="metrics namespace registration"),
    ResourcePair("thread", None, ("Thread", "Timer"), ("join",),
                 description="started worker thread"),
    ResourcePair("subprocess", None, ("Popen",),
                 ("wait", "communicate", "poll", "kill", "terminate"),
                 description="spawned child process"),
    ResourcePair("fault-point", None, ("inject",), ("__exit__",),
                 description="armed fault point (context-managed)"),
    ResourcePair("tempdir", None, ("mkdtemp",),
                 ("rmtree", "rename", "replace"),
                 description="temporary directory (create -> rename/rm)"),
)

_HANDLE_PAIRS = tuple(p for p in PAIRS
                      if p.handle and p.owner is not None)
#: owner=None handle pairs matched on the call name itself
_NAME_PAIRS = {"mkdtemp": next(p for p in PAIRS if p.name == "tempdir")}
_THREAD_CTORS = {"Thread", "Timer"}
_PROC_CTORS = {"Popen"}
_THREAD_REAP = {"join"}
_PROC_REAP = {"wait", "communicate", "poll", "kill", "terminate"}
_GAUGE_CLEANUP = {"remove_prefix", "remove_matching", "reset"}
#: terminal teardown — the object is done for good; per-entity gauges it
#: published MUST come down here (deregister may never run for every entity
#: before the owner stops, so cleanup only there is not enough)
_TERMINAL_END = {"stop", "close", "shutdown", "stop_all", "terminate",
                 "uninstall", "__exit__", "__del__"}
_LIFECYCLE_END = _TERMINAL_END | {"deregister", "drain"}


# ---------------------------------------------------------------------------
# model: classes + per-receiver type resolution (lockgraph's inference)
# ---------------------------------------------------------------------------


@dataclass
class _Model:
    classes: Dict[str, Optional[_ClassInfo]] = field(default_factory=dict)


def _build_model(trees: Sequence[Tuple[str, str, ast.Module]]) -> _Model:
    model = _Model()
    for path, module, tree in trees:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                info = _index_class(node, module, path)
                # bare-name collisions make resolution ambiguous: disable
                model.classes[info.name] = (
                    None if info.name in model.classes else info)
    return model


def _ctor_candidates(value: ast.AST) -> List[str]:
    """Every ctor name mentioned in an assigned expression (the lockgraph
    convention: ``m if m else Metrics()`` yields ``["Metrics"]``)."""
    out: List[str] = []
    for call in ast.walk(value):
        if isinstance(call, ast.Call):
            fn = call.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name is not None:
                out.append(name)
    return out


def _recv_types(recv: ast.AST, cls: Optional[_ClassInfo],
                local_types: Dict[str, List[str]],
                model: _Model) -> List[str]:
    """Candidate class names for a receiver expression: ``self`` -> the
    enclosing class, locals via recorded ctor/annotation candidates,
    ``self.attr`` (and chains like ``replica.pool``) via each class's
    inferred attribute types."""
    if isinstance(recv, ast.Name):
        if recv.id == "self" and cls is not None:
            return [cls.name]
        return list(local_types.get(recv.id, ()))
    if isinstance(recv, ast.Attribute):
        out: List[str] = []
        for base in _recv_types(recv.value, cls, local_types, model):
            info = model.classes.get(base)
            if info is not None:
                out.extend(info.attr_types.get(recv.attr, ()))
        return out
    return []


def _mentions(node: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


def _fn_nodes(fn: ast.AST) -> List[ast.AST]:
    """Every node in ``fn``'s own body, NOT descending into nested
    defs/lambdas/classes (they run later, on their own paths)."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _parents(fn: ast.AST) -> Dict[ast.AST, ast.AST]:
    par: Dict[ast.AST, ast.AST] = {}
    stack = [fn]
    while stack:
        n = stack.pop()
        if n is not fn and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                    ast.ClassDef)):
            continue
        for c in ast.iter_child_nodes(n):
            par[c] = n
            stack.append(c)
    return par


def _try_ancestry(node: ast.AST, par: Dict[ast.AST, ast.AST]
                  ) -> List[Tuple[ast.Try, str]]:
    """[(try node, which part of it holds ``node``)] innermost-first;
    part is 'body'/'handler'/'final'/'orelse'."""
    out: List[Tuple[ast.Try, str]] = []
    child, cur = node, par.get(node)
    while cur is not None:
        if isinstance(cur, ast.Try):
            if any(child is h or _contains(h, child)
                   for h in cur.handlers):
                out.append((cur, "handler"))
            elif any(child is s or _contains(s, child)
                     for s in cur.finalbody):
                out.append((cur, "final"))
            elif any(child is s or _contains(s, child)
                     for s in cur.orelse):
                out.append((cur, "orelse"))
            else:
                out.append((cur, "body"))
        child, cur = cur, par.get(cur)
    return out


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(root))


def _is_none_guard(test: ast.AST, handles: Set[str]) -> bool:
    """``if h is None:`` / ``if not h:`` — the acquire *failed*; an escape
    under this guard has nothing to release."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.ops[0], ast.Is) and \
            isinstance(test.left, ast.Name) and test.left.id in handles and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None:
        return True
    return (isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Name)
            and test.operand.id in handles)


def _under_none_guard(node: ast.AST, handles: Set[str],
                      par: Dict[ast.AST, ast.AST]) -> bool:
    child, cur = node, par.get(node)
    while cur is not None:
        if isinstance(cur, ast.If) and any(
                s is child or _contains(s, child) for s in cur.body) \
                and _is_none_guard(cur.test, handles):
            return True
        child, cur = cur, par.get(cur)
    return False


def _innermost_loop(node: ast.AST, par: Dict[ast.AST, ast.AST]
                    ) -> Optional[ast.AST]:
    cur = par.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return cur
        cur = par.get(cur)
    return None


# ---------------------------------------------------------------------------
# per-function scan: X601 / X602
# ---------------------------------------------------------------------------


@dataclass
class _Acquire:
    pair: ResourcePair
    node: ast.Call
    recv_chain: Tuple[str, ...]      # () for name-matched pairs (mkdtemp)
    handles: Set[str]                # local names bound to the result


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_release_call(call: ast.Call, acq: _Acquire,
                     cls: Optional[_ClassInfo],
                     local_types: Dict[str, List[str]],
                     model: _Model) -> bool:
    name = _call_name(call)
    if name not in acq.pair.release:
        return False
    if acq.recv_chain and isinstance(call.func, ast.Attribute):
        if tuple(_attr_chain(call.func.value)) == acq.recv_chain:
            return True
        types = _recv_types(call.func.value, cls, local_types, model)
        if acq.pair.owner in types:
            return True
        return False
    # name-matched pairs (tempdir): shutil.rmtree(d) / os.rename(d, ...)
    return bool(acq.handles) and any(_mentions(a, acq.handles)
                                     for a in call.args)


def _scan_function(fn: ast.AST, cls: Optional[_ClassInfo], model: _Model,
                   path: str) -> List[Finding]:
    findings: List[Finding] = []
    par = _parents(fn)
    nodes = _fn_nodes(fn)

    # pass 1: local types (assignment ctors, annotated params, loop aliases)
    local_types: Dict[str, List[str]] = {}
    args = getattr(fn, "args", None)
    if args is not None:
        from .lockgraph import _ann_tokens
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            if a.annotation is not None:
                toks = _ann_tokens(a.annotation)
                if toks:
                    local_types[a.arg] = toks
    for n in nodes:
        if isinstance(n, ast.Assign):
            cands = _ctor_candidates(n.value)
            for t in n.targets:
                if isinstance(t, ast.Name) and cands:
                    local_types[t.id] = cands
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            # `for w in self._workers:` — elements of a Thread-holding
            # container type like the container's recorded candidates
            if isinstance(n.target, ast.Name):
                elem = _recv_types(n.iter, cls, local_types, model)
                if not elem:
                    cands = (_ctor_candidates(n.iter)
                             if not isinstance(n.iter, ast.Name)
                             else local_types.get(n.iter.id, []))
                    elem = list(cands)
                if elem:
                    local_types[n.target.id] = elem

    # pass 2: acquires
    acquires: List[_Acquire] = []
    for n in nodes:
        if not isinstance(n, ast.Call):
            continue
        name = _call_name(n)
        pair: Optional[ResourcePair] = None
        recv_chain: Tuple[str, ...] = ()
        if name in _NAME_PAIRS:
            pair = _NAME_PAIRS[name]
        elif isinstance(n.func, ast.Attribute):
            for p in _HANDLE_PAIRS:
                if name in p.acquire:
                    types = _recv_types(n.func.value, cls, local_types,
                                        model)
                    if p.owner in types:
                        pair = p
                        recv_chain = tuple(_attr_chain(n.func.value))
                        break
        if pair is None:
            continue
        handles: Set[str] = set()
        if pair.handle_arg is not None:
            if len(n.args) > pair.handle_arg:
                for sub in ast.walk(n.args[pair.handle_arg]):
                    if isinstance(sub, ast.Name):
                        handles.add(sub.id)
        else:
            parent = par.get(n)
            while isinstance(parent, (ast.Tuple, ast.List, ast.Starred)):
                parent = par.get(parent)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            handles.add(sub.id)
        acquires.append(_Acquire(pair, n, recv_chain, handles))

    for acq in acquires:
        # context-managed acquire: `with pool.acquire() as c:` /
        # `with faults.inject(...):` — cleanup is the CM's job
        parent = par.get(acq.node)
        if isinstance(parent, ast.withitem):
            continue
        anc = _try_ancestry(acq.node, par)
        protective_final = False
        protective_handler = False
        for t, part in anc:
            if part != "body":
                continue
            for s in t.finalbody:
                for c in ast.walk(s):
                    if isinstance(c, ast.Call) and _is_release_call(
                            c, acq, cls, local_types, model):
                        protective_final = True
            for h in t.handlers:
                for c in ast.walk(h):
                    if isinstance(c, ast.Call) and _is_release_call(
                            c, acq, cls, local_types, model):
                        protective_handler = True
        if protective_final:
            continue

        acq_line = acq.node.lineno
        # where does this function's responsibility for the handle end?
        # the first matching release, or the first ownership transfer —
        # stored onto self/a container, passed into a call, returned/yielded
        end_line: Optional[int] = None
        end_node: Optional[ast.AST] = None
        release_line: Optional[int] = None
        for n in nodes:
            ln = getattr(n, "lineno", None)
            if ln is None or ln <= acq_line:
                continue
            if isinstance(n, ast.Call) and _is_release_call(
                    n, acq, cls, local_types, model):
                release_line = ln if release_line is None \
                    else min(release_line, ln)
                if end_line is None or ln < end_line:
                    end_line, end_node = ln, n
                continue
            if not acq.handles:
                continue
            transferred = False
            if isinstance(n, ast.Call) and n is not acq.node:
                if any(_mentions(a, acq.handles)
                       for a in (*n.args, *(kw.value for kw in n.keywords))):
                    transferred = True
            elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
                if n.value is not None and _mentions(n.value, acq.handles):
                    transferred = True
            elif isinstance(n, ast.Assign):
                stores = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in n.targets)
                if stores and _mentions(n.value, acq.handles):
                    transferred = True
            if transferred and (end_line is None or ln < end_line):
                end_line, end_node = ln, n
        # also: the acquire expression itself consumed by a transfer
        # (`return pool.acquire()`, `self.conn = pool.acquire()` — Assign
        # to an attribute target)
        p2 = par.get(acq.node)
        while p2 is not None and not isinstance(
                p2, (ast.Return, ast.Assign, ast.Call, ast.stmt)):
            p2 = par.get(p2)
        if isinstance(p2, ast.Return):
            continue
        if isinstance(p2, ast.Assign) and any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in p2.targets):
            continue

        horizon = end_line if end_line is not None else float("inf")

        # GC-X601: explicit escapes inside the exposure window
        for n in nodes:
            if not isinstance(n, (ast.Return, ast.Raise, ast.Break)):
                continue
            ln = getattr(n, "lineno", 0)
            if not (acq_line < ln < horizon):
                continue
            if acq.handles and isinstance(n, ast.Return) \
                    and n.value is not None \
                    and _mentions(n.value, acq.handles):
                continue  # returning the handle IS the transfer
            # `if h is None: return/break` — the acquire came back empty;
            # there is nothing to release on this path
            if acq.handles and _under_none_guard(n, acq.handles, par):
                continue
            # a `break` only skips the release/transfer if that release is
            # inside the same loop it breaks out of; a release below the
            # loop still runs
            if isinstance(n, ast.Break):
                loop = _innermost_loop(n, par)
                if loop is not None and end_node is not None and \
                        not _contains(loop, end_node):
                    continue
            esc_anc = _try_ancestry(n, par)
            # a finally on the escape's own path pays the release — the
            # canonical `h = acquire()` / `try: ... finally: release(h)`
            # puts the acquire OUTSIDE the try, so this must be checked on
            # the escape, not just on the acquire
            if any(part in ("body", "handler", "orelse") and any(
                    isinstance(c, ast.Call) and _is_release_call(
                        c, acq, cls, local_types, model)
                    for s in t.finalbody for c in ast.walk(s))
                   for t, part in esc_anc):
                continue
            # escapes inside the except handlers of the acquiring try are
            # reacting to the acquire's own failure: nothing was acquired
            if any(part == "handler" and any(
                    t2 is t and pt == "body"
                    for t2, pt in _try_ancestry(acq.node, par))
                   for t, part in esc_anc):
                continue
            kind = type(n).__name__.lower()
            findings.append(Finding(
                "GC-X601",
                f"{acq.pair.name}: {_call_name(acq.node)}() at line "
                f"{acq_line} acquires a {acq.pair.description or 'resource'}"
                f" but this {kind} escapes before any "
                f"{'/'.join(acq.pair.release)} — wrap the region in "
                f"try/finally or release before escaping",
                path=path, line=ln, source="lifecycle",
                detail={"pair": acq.pair.name, "acquire_line": acq_line}))
            break  # one report per acquire

        # GC-X602: a release exists but the error branch skips it
        if release_line is not None and not protective_handler \
                and (end_line is None or release_line <= end_line):
            risky = None
            for n in nodes:
                if not isinstance(n, ast.Call) or n is acq.node:
                    continue
                ln = getattr(n, "lineno", 0)
                if not (acq_line < ln < release_line):
                    continue
                if _is_release_call(n, acq, cls, local_types, model):
                    continue
                # `raise SomeError(...)`: the exception ctor is not a risky
                # call — the raise itself is the escape, and X601 owns it
                if isinstance(par.get(n), ast.Raise):
                    continue
                # a call whose own enclosing try releases in a handler or
                # finally is protected
                covered = False
                for t, part in _try_ancestry(n, par):
                    if part != "body":
                        continue
                    for s in (*t.finalbody, *t.handlers):
                        for c in ast.walk(s):
                            if isinstance(c, ast.Call) and _is_release_call(
                                    c, acq, cls, local_types, model):
                                covered = True
                if not covered:
                    risky = n
                    break
            if risky is not None:
                findings.append(Finding(
                    "GC-X602",
                    f"{acq.pair.name}: {_call_name(risky)}() between this "
                    f"{_call_name(acq.node)}() and its "
                    f"{'/'.join(acq.pair.release)} at line {release_line} "
                    f"can raise, and no try/finally or handler routes that "
                    f"error through the release",
                    path=path, line=acq_line, source="lifecycle",
                    detail={"pair": acq.pair.name,
                            "release_line": release_line,
                            "risky_line": risky.lineno}))
    return findings


# ---------------------------------------------------------------------------
# X603: started threads / spawned subprocesses must be reaped in scope
# ---------------------------------------------------------------------------


def _thread_kind(cands: Iterable[str]) -> Optional[str]:
    cands = set(cands)
    if cands & _THREAD_CTORS:
        return "thread"
    if cands & _PROC_CTORS:
        return "subprocess"
    return None


def _scan_threads_class(info: _ClassInfo, model: _Model, path: str
                        ) -> List[Finding]:
    """Class scope: a ``self.<attr>`` thread started anywhere in the class
    must be joined (wait/kill/terminate for processes) somewhere in the
    class."""
    kinds = {attr: _thread_kind(c)
             for attr, c in info.attr_types.items()}
    kinds = {a: k for a, k in kinds.items() if k is not None}
    if not kinds:
        return []
    started: Dict[str, ast.Call] = {}
    reaped: Set[str] = set()
    for m in info.methods.values():
        par = _parents(m)
        aliases: Dict[str, str] = {}  # loop var -> self attr
        for n in _fn_nodes(m):
            if isinstance(n, (ast.For, ast.AsyncFor)) and \
                    isinstance(n.target, ast.Name):
                it = n.iter
                # `for w in self._workers:` (also through list()/values())
                for sub in ast.walk(it):
                    if isinstance(sub, ast.Attribute) and \
                            isinstance(sub.value, ast.Name) and \
                            sub.value.id == "self" and sub.attr in kinds:
                        aliases[n.target.id] = sub.attr
        for n in _fn_nodes(m):
            # Popen has no .start(): the ctor assignment IS the start
            if isinstance(n, ast.Assign) and \
                    _thread_kind(_ctor_candidates(n.value)) == "subprocess":
                for t in n.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and t.attr in kinds:
                        started.setdefault(t.attr, n)
                continue
            if not isinstance(n, ast.Call) or \
                    not isinstance(n.func, ast.Attribute):
                continue
            recv = n.func.value
            attr = None
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and recv.attr in kinds:
                attr = recv.attr
            elif isinstance(recv, ast.Name) and recv.id in aliases:
                attr = aliases[recv.id]
            if attr is None:
                continue
            reap = (_THREAD_REAP if kinds[attr] == "thread" else _PROC_REAP)
            if n.func.attr == "start":
                started.setdefault(attr, n)
            elif n.func.attr in reap:
                reaped.add(attr)
    out = []
    for attr, site in started.items():
        if attr in reaped:
            continue
        kind = kinds[attr]
        verbs = ("join" if kind == "thread"
                 else "wait/poll/kill/terminate")
        out.append(Finding(
            "GC-X603",
            f"{info.name}.{attr}: {kind} started here is never "
            f"{verbs}-ed anywhere in {info.name} — stop()/close() "
            f"abandons it mid-flight",
            path=path, line=site.lineno, source="lifecycle",
            detail={"class": info.name, "attr": attr, "kind": kind}))
    return out


def _scan_threads_function(fn: ast.AST, cls: Optional[_ClassInfo],
                           model: _Model, path: str) -> List[Finding]:
    """Function scope: a local Thread/Popen started here must be reaped
    here, unless ownership escapes (returned, stored, passed along)."""
    local_kind: Dict[str, str] = {}
    proc_assigns: Dict[str, ast.Assign] = {}
    escaped: Set[str] = set()
    nodes = _fn_nodes(fn)
    for n in nodes:
        if isinstance(n, ast.Assign):
            kind = _thread_kind(_ctor_candidates(n.value))
            for t in n.targets:
                if isinstance(t, ast.Name):
                    if kind is not None:
                        local_kind[t.id] = kind
                        if kind == "subprocess":
                            # Popen has no .start(): the ctor IS the start
                            proc_assigns.setdefault(t.id, n)
                elif isinstance(t, (ast.Attribute, ast.Subscript)):
                    # self._t = threading.Thread(...) — class scope's job;
                    # d[k] = Popen(...) — container ownership, skip
                    pass
    if not local_kind:
        return []
    names = set(local_kind)
    aliases: Dict[str, str] = {}
    for n in nodes:
        if isinstance(n, (ast.For, ast.AsyncFor)) and \
                isinstance(n.target, ast.Name) and \
                isinstance(n.iter, ast.Name) and n.iter.id in names:
            aliases[n.target.id] = n.iter.id
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) and \
                getattr(n, "value", None) is not None and \
                _mentions(n.value, names):
            escaped |= {nm for nm in names if _mentions(n.value, {nm})}
        if isinstance(n, ast.Assign) and _mentions(n.value, names):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in n.targets):
                escaped |= {nm for nm in names if _mentions(n.value, {nm})}
        if isinstance(n, ast.Call):
            fname = _call_name(n)
            for a in (*n.args, *(kw.value for kw in n.keywords)):
                for nm in names:
                    if _mentions(a, {nm}):
                        # v.start()/v.join() receivers are not arguments;
                        # append(v) / register(v) hands ownership off
                        escaped.add(nm)
            del fname
    started: Dict[str, ast.Call] = {}
    reaped: Set[str] = set()
    for n in nodes:
        if not isinstance(n, ast.Call) or \
                not isinstance(n.func, ast.Attribute) or \
                not isinstance(n.func.value, ast.Name):
            continue
        rid = n.func.value.id
        target = rid if rid in names else aliases.get(rid)
        if target is None:
            continue
        kind = local_kind[target]
        reap = _THREAD_REAP if kind == "thread" else _PROC_REAP
        if n.func.attr == "start":
            started.setdefault(target, n)
        elif n.func.attr in reap:
            reaped.add(target)
    for nm, site in proc_assigns.items():
        started.setdefault(nm, site)
    out = []
    for nm, site in started.items():
        if nm in reaped or nm in escaped:
            continue
        kind = local_kind[nm]
        out.append(Finding(
            "GC-X603",
            f"local {kind} {nm!r} is started but never "
            f"{'joined' if kind == 'thread' else 'reaped'} in this "
            f"function, and never handed off — it outlives the scope that "
            f"knows about it",
            path=path, line=site.lineno, source="lifecycle",
            detail={"name": nm, "kind": kind}))
    return out


# ---------------------------------------------------------------------------
# X604: dynamic gauge namespaces need a cleanup path
# ---------------------------------------------------------------------------


def _is_metrics_recv(recv: ast.AST, cls: Optional[_ClassInfo],
                     model: _Model) -> bool:
    types = _recv_types(recv, cls, {}, model)
    if "Metrics" in types:
        return True
    chain = _attr_chain(recv)
    return bool(chain) and chain[-1] in ("metrics", "_metrics")


def _dynamic_name(arg: ast.AST) -> bool:
    """True when a metric name is built per-entity: an f-string with a
    formatted value, ``.format(...)``, or ``%``/``+`` composition over
    non-constants. A plain string literal (or Name) is process-level."""
    if isinstance(arg, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in arg.values)
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Attribute) \
            and arg.func.attr == "format":
        return True
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, (ast.Mod, ast.Add)):
        return any(not isinstance(x, ast.Constant)
                   for x in (arg.left, arg.right))
    return False


def _scan_gauges_class(info: _ClassInfo, model: _Model, path: str
                       ) -> List[Finding]:
    lifecycle_methods = [m for name, m in info.methods.items()
                         if name in _LIFECYCLE_END]
    if not lifecycle_methods:
        return []  # no shutdown path to hang a cleanup on: out of scope
    # does any lifecycle-end method reach remove_prefix/remove_matching,
    # directly or through self.* calls (fixpoint within the class)?
    cleans: Set[str] = set()
    calls_of: Dict[str, Set[str]] = {}
    for name, m in info.methods.items():
        called: Set[str] = set()
        for n in _fn_nodes(m):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute):
                if n.func.attr in _GAUGE_CLEANUP:
                    cleans.add(name)
                if isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == "self":
                    called.add(n.func.attr)
        calls_of[name] = called
    changed = True
    while changed:
        changed = False
        for name, called in calls_of.items():
            if name not in cleans and called & cleans:
                cleans.add(name)
                changed = True
    # terminal teardown (stop/close/...) must itself reach the cleanup:
    # per-entity deregister cleaning is necessary but not sufficient — live
    # entities at stop() time still leak their gauges (the PR 18 bug class)
    terminal = [m for m in lifecycle_methods if m.name in _TERMINAL_END]
    required = terminal if terminal else lifecycle_methods
    if any(m.name in cleans for m in required):
        return []
    # dynamic gauge registrations with no cleanup anywhere on shutdown
    for name, m in info.methods.items():
        for n in _fn_nodes(m):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "gauge" and n.args and \
                    _dynamic_name(n.args[0]) and \
                    _is_metrics_recv(n.func.value, info, model):
                ends = sorted(m2.name for m2 in lifecycle_methods)
                return [Finding(
                    "GC-X604",
                    f"{info.name}.{name}() publishes gauges under a "
                    f"per-entity namespace but none of its lifecycle-end "
                    f"methods ({', '.join(ends)}) removes them "
                    f"(Metrics.remove_prefix/remove_matching) — departed "
                    f"entities stay in the exposition forever",
                    path=path, line=n.lineno, source="lifecycle",
                    detail={"class": info.name, "method": name})]
    return []


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _lint_tree(path: str, module: str, tree: ast.Module,
               model: _Model) -> List[Finding]:
    findings: List[Finding] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            info = model.classes.get(node.name)
            if info is None or info.path != path:
                info = _index_class(node, module, path)  # shadowed dup
            findings.extend(_scan_threads_class(info, model, path))
            findings.extend(_scan_gauges_class(info, model, path))
            for m in info.methods.values():
                findings.extend(_scan_function(m, info, model, path))
                findings.extend(
                    _scan_threads_function(m, info, model, path))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_scan_function(node, None, model, path))
            findings.extend(
                _scan_threads_function(node, None, model, path))
    findings.sort(key=lambda f: (f.path or "", f.line or 0, f.rule))
    return findings


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    """The whole-package resource-lifecycle pass: one model over every
    ``.py`` under ``paths`` (so cross-file receiver types resolve), then
    GC-X601–X604 per file, inline suppressions honored."""
    trees: List[Tuple[str, str, ast.Module]] = []
    sources: Dict[str, str] = {}
    for f in iter_py_files(paths):
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src)
        except (SyntaxError, OSError):
            continue
        sources[f] = src
        trees.append((f, _module_name(f), tree))
    model = _build_model(trees)
    findings: List[Finding] = []
    for path, module, tree in trees:
        fs = _lint_tree(path, module, tree, model)
        findings.extend(filter_suppressed(fs, sources[path]))
    return findings


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    """Single-source convenience for tests: the model is just this file."""
    tree = ast.parse(source)
    module = "mod"
    model = _build_model([(path, module, tree)])
    return filter_suppressed(_lint_tree(path, module, tree, model), source)
