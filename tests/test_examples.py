"""Structural checks on examples/: every example must be directly runnable
(``python examples/foo.py`` from any cwd), which requires the repo-root
sys.path bootstrap — without it the import fails outside an installed
package — and a wedged-relay guard before first device use so examples
don't hang on a dead accelerator tunnel."""

import os
import py_compile

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _example_files():
    return sorted(f for f in os.listdir(EXAMPLES) if f.endswith(".py"))


@pytest.mark.parametrize("fname", _example_files())
def test_example_compiles(fname):
    py_compile.compile(os.path.join(EXAMPLES, fname), doraise=True)


@pytest.mark.parametrize("fname", _example_files())
def test_example_has_path_bootstrap(fname):
    src = open(os.path.join(EXAMPLES, fname)).read()
    assert "sys.path.insert" in src, (
        f"{fname} lacks the repo-root sys.path bootstrap; "
        f"`python examples/{fname}` would fail with ModuleNotFoundError")


@pytest.mark.parametrize("fname", _example_files())
def test_example_guards_against_wedged_relay(fname):
    src = open(os.path.join(EXAMPLES, fname)).read()
    assert "ensure_live_backend" in src, (
        f"{fname} never calls ensure_live_backend(); it would hang forever "
        f"on a wedged TPU relay instead of falling back to CPU")
