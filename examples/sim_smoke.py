"""Fleet-simulator smoke: a 1000-replica x 1M-request what-if, in seconds.

Run via ``make sim-smoke`` (or directly). The script

1. replays a 1,000,000-request synthetic trace (bursty MMPP arrivals,
   heavy-tail Pareto lengths, multi-turn sessions) against a simulated
   1000-replica heterogeneous fleet — 70% bf16 pools, 30% int8 pools
   with ~3.76x the pages per byte (the measured quantized-KV ratio) —
   using the REAL serving policies (``serving/policies.py``), real
   circuit breakers, and bench-fitted cost models;
2. verifies the run is fully accounted (every request completed or
   rejected), byte-deterministic (stable event-log sha256), and bounded
   in wall-clock;
3. sweeps arrival rate on a smaller trace to produce a **capacity
   report**: the knee where tail latency and shedding take off — the
   what-if question ("can this fleet take 1.5x traffic?") the simulator
   exists to answer without touching production.

Everything is pure CPU; no servers, no sockets, no model. Exits nonzero
if accounting, determinism, or the wall-clock bound break.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkflow_tpu.utils.hw import ensure_live_backend

ensure_live_backend()  # convention: never hang on a wedged TPU relay

from sparkflow_tpu.sim import (CostModel, FleetSimulator, ReplicaSpec,
                               synthetic_trace)

SMOKE = bool(os.environ.get("SPARKFLOW_TPU_SMOKE"))
WALL_BOUND_S = 240.0          # generous CI bound; typical is well under
FLEET = 100 if SMOKE else 1000
REQUESTS = 50_000 if SMOKE else 1_000_000


def build_fleet(n):
    # 70/30 bf16/int8: same device bytes, int8 holds ~3.76x the pages
    # (BENCH_NOTES kv-quant measurement), so byte-headroom routing has
    # real heterogeneity to work with
    specs = []
    for i in range(n):
        if i % 10 < 7:
            specs.append(ReplicaSpec(slots=8, pages_total=4096,
                                     kv_bytes_per_page=4 << 20))
        else:
            specs.append(ReplicaSpec(slots=8, pages_total=15400,
                                     kv_bytes_per_page=(4 << 20) * 4096
                                     // 15400))
    return specs


def main():
    cost = CostModel.from_bench_notes()
    specs = build_fleet(FLEET)

    print(f"== scale: {FLEET} replicas x {REQUESTS:,} requests ==")
    tr = synthetic_trace(REQUESTS, seed=7, rate_rps=40.0 * FLEET,
                         prompt_range=(16, 1024), output_range=(8, 256))
    rep = FleetSimulator(specs, tr, cost, mode="generate", seed=0).run()
    done = rep.completed + rep.rejected
    print(f"completed={rep.completed:,} rejected={rep.rejected:,} "
          f"queue_full={rep.queue_full:,} "
          f"p50={rep.latency_p50_ms:.1f}ms p95={rep.latency_p95_ms:.1f}ms")
    print(f"sim_time={rep.sim_time_s:.1f}s wall={rep.wall_s:.1f}s "
          f"({rep.completed / max(rep.wall_s, 1e-9):,.0f} sim-requests/s) "
          f"digest={rep.digest[:16]}")
    utils = sorted(r["utilization"] for r in rep.per_replica)
    print(f"replica utilization: min={utils[0]:.3f} "
          f"median={utils[len(utils) // 2]:.3f} max={utils[-1]:.3f}")
    ok = True
    if done != REQUESTS:
        print(f"FAIL: {REQUESTS - done} requests unaccounted")
        ok = False
    if rep.wall_s > WALL_BOUND_S:
        print(f"FAIL: wall {rep.wall_s:.1f}s > bound {WALL_BOUND_S}s")
        ok = False

    print(f"\n== capacity sweep: where does this fleet fall over? ==")
    # sessions off so the rate label IS the offered rate (session
    # follow-up turns trickle in over think-time tails and would dilute
    # the time-average far below the label)
    knee, base_p95 = None, None
    sweep_n = 12_000 if SMOKE else 120_000
    for rate in (30.0 * FLEET, 60.0 * FLEET, 90.0 * FLEET, 120.0 * FLEET):
        tr = synthetic_trace(sweep_n, seed=11, rate_rps=rate,
                             session_fraction=0.0,
                             prompt_range=(16, 1024),
                             output_range=(8, 256))
        r = FleetSimulator(specs, tr, cost, mode="generate", seed=0).run()
        shed = (r.rejected + r.queue_full) / sweep_n
        print(f"rate={rate:>8,.0f} rps  p95={r.latency_p95_ms:>9.1f}ms  "
              f"shed={shed:6.2%}  throughput={r.throughput_rps:,.0f} rps")
        if base_p95 is None:
            base_p95 = r.latency_p95_ms
        if knee is None and (shed > 0.01
                             or r.latency_p95_ms > 3.0 * base_p95):
            knee = rate
    if knee is not None:
        print(f"capacity knee: ~{knee:,.0f} rps on this fleet "
              f"(first rate with >1% shed or p95 > 3x the low-load p95)")
    else:
        print("capacity knee: beyond the swept range")

    print("\nsim-smoke", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
