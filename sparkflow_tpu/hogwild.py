"""``HogwildTrainer``: the ``HogwildSparkModel``-shaped direct training entry.

The reference lets users bypass the Estimator and train an RDD of
``(features, label)`` pairs directly (``HogwildSparkModel(...).train(rdd)``,
``sparkflow/HogwildSparkModel.py:110-143,246-266``; exercised by
``tests/dl_runner.py:187-214``). This class keeps that constructor surface —
including the parameter-server-era arguments — and now trains the way the
name promises: asynchronously, through the bounded-staleness elastic engine
(``parallel.elastic``). Each RDD partition maps to a replica that pushes
gradients to a versioned in-process parameter store whenever it finishes a
mini-batch — the reference's Hogwild loop, with the HTTP hop and the
unbounded staleness removed. ``master_url``, ``serverStartup`` and ``port``
are still accepted and ignored (the store is in-process: no server to spawn,
no fixed 8-second startup sleep — an anti-feature per SURVEY.md), and
``stop_server`` is a no-op kept for try/except cleanup code written against
the reference. ``acquire_lock`` is likewise accepted for parity: the store
ALWAYS serializes updates under its lock — SURVEY.md flags the reference's
lock-free default as a data-corruption misfeature, so unlocked application
is not offered.

Also exported under the reference's class name ``HogwildSparkModel``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np
import optax

from .ml_util import handle_features
from .optimizers import build_optimizer
from .trainer import Trainer


class HogwildTrainer:
    def __init__(self,
                 tensorflowGraph: Optional[str] = None,
                 iters: int = 1000,
                 tfInput: Optional[str] = None,
                 tfLabel: Optional[str] = None,
                 optimizer: Any = None,
                 master_url: Optional[str] = None,   # ignored: store is in-process
                 serverStartup: int = 8,             # ignored: nothing to wait for
                 acquire_lock: bool = False,         # store always locks (see module doc)
                 mini_batch: int = -1,
                 mini_stochastic_iters: int = -1,
                 shuffle: bool = True,
                 verbose: int = 0,
                 partition_shuffles: int = 1,
                 loss_callback: Optional[Callable] = None,
                 port: int = 5000,                   # ignored: no port to bind
                 mesh=None,
                 max_staleness: int = 4,
                 dampening="inverse"):
        if tensorflowGraph is None:
            raise ValueError("tensorflowGraph (JSON graph spec) is required")
        if optimizer is None:
            optimizer = build_optimizer("adam", 0.01, None)
        elif isinstance(optimizer, str):
            optimizer = build_optimizer(optimizer, 0.01, None)
        elif not isinstance(optimizer, optax.GradientTransformation):
            raise TypeError(
                "optimizer must be an optax.GradientTransformation or a name; "
                "TF optimizer objects do not exist in this framework — build one "
                "with sparkflow_tpu.optimizers.build_optimizer")
        self._trainer = Trainer(
            tensorflowGraph, tfInput, tfLabel,
            optimizer=optimizer,
            iters=iters,
            mini_batch_size=mini_batch,
            mini_stochastic_iters=mini_stochastic_iters,
            shuffle_per_iter=shuffle,
            partition_shuffles=partition_shuffles,
            verbose=verbose,
            loss_callback=loss_callback,
            acquire_lock=acquire_lock,
            strategy="elastic_dp",
            elastic={"max_staleness": max_staleness,
                     "dampening": dampening},
        )
        self.tfLabel = tfLabel
        self.weights: Optional[List[np.ndarray]] = None

    def train(self, rdd) -> List[np.ndarray]:
        """Train on an RDD (or any iterable) of ``(features, label)`` pairs —
        bare features when unsupervised — and return the flat weight list
        (reference ``HogwildSparkModel.train``, ``HogwildSparkModel.py:246-269``).

        One replica per RDD partition, like the reference's one async worker
        per ``foreachPartition`` task (clamped to [1, 8] — beyond that the
        in-process threads contend instead of overlapping); a plain iterable
        trains with 4 replicas."""
        if hasattr(rdd, "getNumPartitions"):
            replicas = max(1, min(8, int(rdd.getNumPartitions())))
        else:
            replicas = 4
        self._trainer.elastic["replicas"] = replicas
        items = rdd.collect() if hasattr(rdd, "collect") else list(rdd)
        features, labels = handle_features(items,
                                           is_supervised=self.tfLabel is not None)
        self._trainer.fit(features, labels)
        self.weights = self._trainer.weights_list()
        return self.weights

    @property
    def elastic_stats(self):
        """Push/staleness/membership accounting from the last ``train``
        (``ElasticResult.stats``), or None before training."""
        return self._trainer.last_elastic_stats

    def stop_server(self) -> None:
        """No server exists; kept so reference-style cleanup code runs
        (``tests/dl_runner.py:209-214``)."""

    @staticmethod
    def determine_master(port: Optional[int] = None) -> str:
        """Reference API parity (``HogwildSparkModel.determine_master``,
        ``HogwildSparkModel.py:145-154``): resolves a coordinator address.
        The reference's default was the Flask port (5000), which no longer
        exists; with no argument this now matches
        :func:`parallel.distributed.determine_master` so both bootstrap paths
        agree on the address."""
        from .parallel.distributed import determine_master as _dm
        return _dm(port) if port is not None else _dm()

    # reference attribute some callers poke at
    @property
    def server(self):
        return None


HogwildSparkModel = HogwildTrainer
