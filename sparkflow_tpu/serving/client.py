"""Minimal stdlib client for :class:`~sparkflow_tpu.serving.server.InferenceServer`.

Deliberately tiny — ``urllib.request`` plus JSON — because its jobs are the
smoke path (``make serve-smoke``), the e2e tests, and showing the wire
protocol in ~30 lines. Production callers can speak the same JSON from any
HTTP stack.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

import numpy as np


class ServingError(Exception):
    """Non-2xx reply from the server. Carries the structured error body."""

    def __init__(self, status: int, code: str, message: str):
        super().__init__(f"HTTP {status} [{code}]: {message}")
        self.status = status
        self.code = code
        self.message = message


class ServingClient:
    """``ServingClient(url).predict(rows)`` → np.ndarray of predictions."""

    def __init__(self, url: str, timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _request(self, path: str, payload: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.url + path,
            data=(json.dumps(payload).encode("utf-8")
                  if payload is not None else None),
            headers={"Content-Type": "application/json"},
            method="POST" if payload is not None else "GET")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            try:
                err = json.loads(exc.read().decode("utf-8"))["error"]
                raise ServingError(exc.code, err.get("code", "unknown"),
                                   err.get("message", "")) from None
            except (ValueError, KeyError):
                raise ServingError(exc.code, "unknown", str(exc)) from None

    def predict(self, inputs) -> np.ndarray:
        """``inputs``: rows (list/array) or, for multi-input engines, a dict
        of ``{input_name: rows}``. Raises :class:`ServingError` on rejection
        (e.g. ``code == 'queue_full'`` under overload)."""
        if isinstance(inputs, dict):
            wire: Any = {k: np.asarray(v).tolist() for k, v in inputs.items()}
        else:
            wire = np.asarray(inputs).tolist()
        reply = self._request("/v1/predict", {"inputs": wire})
        return np.asarray(reply["predictions"])

    def healthz(self) -> Dict[str, Any]:
        return self._request("/healthz")

    def metrics(self) -> Dict[str, Any]:
        return self._request("/metrics")
