"""Step-level checkpoint / resume (orbax-backed).

The reference has save-at-end only: weights become a JSON string Param and
optimizer state dies with the parameter-server process (SURVEY.md §5
"Checkpoint/resume"). This module is the capability upgrade: periodic
checkpoints of (params, opt_state, step, rng) during training, resumable
mid-run, plus a plain-weights export for the model loader.

Sharded opt-state interop: zero1 (weight-update-sharded) fits checkpoint the
STANDARD param-shaped opt state, not the flat sharded layout — the trainer
converts via ``optimizers_sharded.gather_zero1_state`` before ``save`` and
re-shards (re-padding for the restoring mesh's dp size) after ``restore``.
Checkpoint directories are therefore interchangeable between zero1-on/off
runs and across mesh-shape changes; ``save``'s ``np.asarray`` pass also
transparently gathers any still-device-sharded leaves it is handed.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False

from .graphdef import GraphModel, list_to_params, params_to_list


class CheckpointManager:
    """Periodic training checkpoints under one directory.

    Layout: ``<dir>/step_<n>/state`` (orbax pytree) + ``<dir>/latest.json``.
    Falls back to npz-per-leaf if orbax is unavailable.
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def save(self, step: int, state: Dict[str, Any]) -> None:
        path = self._step_dir(step)
        state = jax.tree.map(np.asarray, state)
        if _HAVE_ORBAX:
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.join(path, "state"), state, force=True)
        else:  # pragma: no cover
            os.makedirs(path, exist_ok=True)
            flat, _treedef = jax.tree.flatten(state)
            np.savez(os.path.join(path, "state.npz"),
                     **{f"l_{i}": x for i, x in enumerate(flat)})
        with open(os.path.join(self.directory, "latest.json"), "w") as f:
            json.dump({"latest_step": step}, f)
        self._gc()

    def _gc(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.keep] if self.keep > 0 else []:
            import shutil
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        p = os.path.join(self.directory, "latest.json")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f).get("latest_step")

    def restore(self, step: Optional[int] = None,
                like: Optional[Dict[str, Any]] = None) -> Optional[Dict[str, Any]]:
        """Restore the state pytree at ``step`` (default: latest). ``like`` is
        a template pytree used to restore exact structure/dtypes."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        path = self._step_dir(step)
        if _HAVE_ORBAX:
            ckptr = ocp.PyTreeCheckpointer()
            if like is not None:
                template = jax.tree.map(np.asarray, like)
                return ckptr.restore(os.path.join(path, "state"), item=template)
            return ckptr.restore(os.path.join(path, "state"))
        # npz fallback: leaves are stored flat in tree order; `like` supplies
        # the structure (pragma: orbax is present in the supported image)
        if like is None:  # pragma: no cover
            raise RuntimeError("orbax unavailable: npz restore needs `like` "
                               "(a template pytree with the same structure)")
        with np.load(os.path.join(path, "state.npz")) as z:  # pragma: no cover
            flat = [z[f"l_{i}"] for i in range(len(z.files))]
        treedef = jax.tree.structure(like)  # pragma: no cover
        return jax.tree.unflatten(treedef, flat)  # pragma: no cover

    # -- plain-weights interop (model_loader) -------------------------------

    @staticmethod
    def save_weights(directory: str, model: GraphModel, params) -> None:
        os.makedirs(directory, exist_ok=True)
        weights = params_to_list(model, params)
        np.savez(os.path.join(directory, "weights.npz"),
                 **{f"w_{i}": w for i, w in enumerate(weights)})

    @staticmethod
    def load_weights(directory: str, model: GraphModel) -> List[np.ndarray]:
        p = os.path.join(directory, "weights.npz")
        if os.path.exists(p):
            with np.load(p) as z:
                return [z[k] for k in sorted(z.files, key=lambda s: int(s.split("_")[-1]))]
        # orbax training checkpoint: pull params out of the latest state
        mgr = CheckpointManager(directory)
        state = mgr.restore()
        if state is None or "params" not in state:
            raise FileNotFoundError(f"no weights.npz or checkpoints in {directory}")
        return params_to_list(model, state["params"])
