"""Measure the reference-equivalent baseline: single-node Hogwild-style CNN
training throughput on CPU.

The reference (TF 1.10 + Spark 2.4.3) is not installable in this image, so the
baseline is a faithful CPU proxy of its training loop using torch (CPU): the same
MNIST CNN, mini-batch SGD-with-adam steps, plus the reference's per-batch
parameter-server exchange cost — every batch serializes the full gradient list
and deserializes the full weight list with pickle, exactly the wire work
``GET /parameters`` / ``POST /update`` did (``sparkflow/HogwildSparkModel.py:
22-35,57-58,75-76``; loopback HTTP latency excluded, which only favors the
baseline). Writes BASELINE_MEASURED.json; run once, committed.
"""

import json
import pickle
import time

import numpy as np
import torch
import torch.nn as tnn
import torch.nn.functional as F

torch.manual_seed(0)
torch.set_num_threads(1)  # reference guidance: --executor cores 1 (README.md:209-213)


class RefCNN(tnn.Module):
    """The cnn_example.py model (examples/cnn_example.py:10-22 in reference)."""

    def __init__(self):
        super().__init__()
        self.c1 = tnn.Conv2d(1, 32, 5)
        self.c2 = tnn.Conv2d(32, 64, 3)
        self.fc = tnn.Linear(64 * 5 * 5, 10)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.c1(x)), 2)
        x = F.max_pool2d(F.relu(self.c2(x)), 2)
        return self.fc(torch.flatten(x, 1))


def measure(batch_size=300, n_batches=12):
    model = RefCNN()
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)
    rs = np.random.RandomState(0)
    x = torch.tensor(rs.rand(batch_size, 1, 28, 28), dtype=torch.float32)
    y = torch.tensor(rs.randint(0, 10, batch_size), dtype=torch.long)

    # warmup
    for _ in range(2):
        opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        opt.step()

    t0 = time.perf_counter()
    for _ in range(n_batches):
        # per-batch PS wire work the reference pays (weights down, grads up)
        weights = [p.detach().numpy() for p in model.parameters()]
        _ = pickle.loads(pickle.dumps(weights, -1))
        opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        grads = [p.grad.detach().numpy() for p in model.parameters()]
        _ = pickle.loads(pickle.dumps(grads, -1))
        opt.step()
    wall = time.perf_counter() - t0
    return batch_size * n_batches / wall


if __name__ == "__main__":
    eps = measure()
    out = {
        "metric": "mnist_cnn_examples_per_sec",
        "baseline_examples_per_sec": round(eps, 1),
        "how": "torch-CPU single-thread proxy of the reference Hogwild loop "
               "(same CNN, adam, batch 300, full pickle weight+grad round-trip "
               "per batch; loopback HTTP latency excluded)",
    }
    with open("BASELINE_MEASURED.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
