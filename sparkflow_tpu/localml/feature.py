"""Feature transformers: the ``pyspark.ml.feature`` subset the reference examples
use (``VectorAssembler``, ``OneHotEncoder``, ``Normalizer`` — see reference
``examples/simple_dnn.py:40-41``, ``examples/autoencoder_example.py:26-27``)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .base import Estimator, Model, Transformer
from .linalg import DenseVector, SparseVector, Vectors, vector_to_array
from .param import Param, Params, TypeConverters, keyword_only, HasInputCol, HasOutputCol
from .sql import DataFrame, Row


class VectorAssembler(Transformer, HasInputCol, HasOutputCol):
    """Concatenates numeric / vector columns into one DenseVector column."""

    inputCols = Param(Params._dummy(), "inputCols", "input column names",
                      typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, inputCols=None, outputCol=None):
        super().__init__()
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getInputCols(self) -> List[str]:
        return self.getOrDefault(self.inputCols)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_cols = self.getInputCols()
        out_col = self.getOrDefault(self.outputCol)
        rows = []
        for r in dataset.collect():
            parts = [vector_to_array(r[c]) for c in in_cols]
            vec = Vectors.dense(np.concatenate(parts))
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class OneHotEncoder(Transformer, HasInputCol, HasOutputCol):
    """Category index -> one-hot sparse vector (pyspark 2.x OneHotEncoder
    semantics: transform-only; vector size inferred as max(index)+1; dropLast
    drops the final category — the reference uses ``dropLast=False``,
    ``examples/simple_dnn.py:41``)."""

    dropLast = Param(Params._dummy(), "dropLast", "drop the last category",
                     typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, dropLast=True):
        super().__init__()
        self._setDefault(dropLast=True)
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getDropLast(self) -> bool:
        return self.getOrDefault(self.dropLast)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        drop_last = self.getDropLast()
        values = [int(r[in_col]) for r in dataset.collect()]
        size = (max(values) + 1) if values else 0
        if drop_last:
            size -= 1
        rows = []
        for r, v in zip(dataset.collect(), values):
            if v < size:
                vec = SparseVector(size, [v], [1.0])
            else:  # dropped last category encodes as all-zeros
                vec = SparseVector(size, [], [])
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class Normalizer(Transformer, HasInputCol, HasOutputCol):
    """Scale each vector to unit p-norm (reference autoencoder example uses
    p=1.0, ``examples/autoencoder_example.py:27``)."""

    p = Param(Params._dummy(), "p", "norm order", typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, p=2.0):
        super().__init__()
        self._setDefault(p=2.0)
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getP(self) -> float:
        return self.getOrDefault(self.p)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        p = self.getP()
        rows = []
        for r in dataset.collect():
            arr = vector_to_array(r[in_col])
            norm = np.linalg.norm(arr, ord=p)
            vec = Vectors.dense(arr / norm if norm > 0 else arr)
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class WordpieceEncoder(Transformer, HasInputCol, HasOutputCol):
    """Text column -> fixed-shape token-id vector + attention-mask columns,
    ready for ``SparkAsyncDL`` transformer models
    (``extraInputCols=maskCol``). Backed by the native C++ WordPiece
    tokenizer (``sparkflow_tpu/native/tokenizer.cpp``); python fallback
    otherwise. No pyspark analog exists — a capability upgrade over the
    reference, which has no text front-end at all (SURVEY.md §5)."""

    maskCol = Param(Params._dummy(), "maskCol", "attention mask column",
                    typeConverter=TypeConverters.toString)
    maxLen = Param(Params._dummy(), "maxLen", "sequence length",
                   typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, maskCol=None,
                 maxLen=None, vocab=None):
        super().__init__()
        self._setDefault(maskCol="mask", maxLen=128)
        self._vocab = list(vocab) if vocab is not None else None
        kwargs = dict(self._input_kwargs)
        kwargs.pop("vocab", None)
        self._set(**{k: v for k, v in kwargs.items() if v is not None})

    def setVocab(self, vocab) -> "WordpieceEncoder":
        self._vocab = list(vocab)
        return self

    def _transform(self, dataset: DataFrame) -> DataFrame:
        from ..utils.text import WordpieceTokenizer, build_vocab
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        mask_col = self.getOrDefault(self.maskCol)
        max_len = self.getOrDefault(self.maxLen)
        rows = dataset.collect()
        texts = [str(r[in_col]) for r in rows]
        vocab = self._vocab
        if vocab is None:  # fit-free convenience: derive from this dataset
            vocab = build_vocab(texts)
            self._vocab = vocab
        tok = WordpieceTokenizer(vocab)
        ids, mask = tok.encode_batch(texts, max_len)
        out = []
        for r, i, m_ in zip(rows, ids, mask):
            out.append(Row(**{**r.asDict(),
                              out_col: Vectors.dense(i.astype(float)),
                              mask_col: Vectors.dense(m_.astype(float))}))
        cols = dataset.columns + [c for c in (out_col, mask_col)
                                  if c not in dataset.columns]
        return DataFrame(out, cols, dataset.num_partitions)


# ---------------------------------------------------------------------------
# round-2 widening: the rest of the pyspark.ml.feature subset a sparkflow user
# is likely to have in a Pipeline around the deep-learning stage. Semantics
# follow pyspark 2.4 (the reference's pinned Spark), cited per class.
# ---------------------------------------------------------------------------

# pyspark.ml.feature.StopWordsRemover.loadDefaultStopWords("english") subset —
# enough to be useful while staying compact; users can always setStopWords
_ENGLISH_STOP_WORDS = [
    "i", "me", "my", "myself", "we", "our", "ours", "ourselves", "you",
    "your", "yours", "he", "him", "his", "she", "her", "hers", "it", "its",
    "they", "them", "their", "theirs", "what", "which", "who", "whom",
    "this", "that", "these", "those", "am", "is", "are", "was", "were",
    "be", "been", "being", "have", "has", "had", "having", "do", "does",
    "did", "doing", "a", "an", "the", "and", "but", "if", "or", "because",
    "as", "until", "while", "of", "at", "by", "for", "with", "about",
    "against", "between", "into", "through", "during", "before", "after",
    "above", "below", "to", "from", "up", "down", "in", "out", "on", "off",
    "over", "under", "again", "further", "then", "once", "here", "there",
    "when", "where", "why", "how", "all", "any", "both", "each", "few",
    "more", "most", "other", "some", "such", "no", "nor", "not", "only",
    "own", "same", "so", "than", "too", "very", "s", "t", "can", "will",
    "just", "don", "should", "now",
]


def _with_col(dataset: DataFrame, out_col: str, values) -> DataFrame:
    rows = [Row(**{**r.asDict(), out_col: v})
            for r, v in zip(dataset.collect(), values)]
    cols = dataset.columns + ([out_col] if out_col not in dataset.columns
                              else [])
    return DataFrame(rows, cols, dataset.num_partitions)


class Tokenizer(Transformer, HasInputCol, HasOutputCol):
    """Lowercase + split on whitespace (pyspark.ml.feature.Tokenizer)."""

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None):
        super().__init__()
        self._set(**self._input_kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        vals = [str(r[in_col]).lower().split() for r in dataset.collect()]
        return _with_col(dataset, out_col, vals)


class StopWordsRemover(Transformer, HasInputCol, HasOutputCol):
    """Filter stop words out of a string-array column. Also the class the
    pyspark persistence carrier abuses (reference ``pipeline_util.py:30-31``);
    here it is a real transformer."""

    stopWords = Param(Params._dummy(), "stopWords", "words to filter out",
                      typeConverter=TypeConverters.toListString)
    caseSensitive = Param(Params._dummy(), "caseSensitive",
                          "case sensitive comparison",
                          typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, stopWords=None,
                 caseSensitive=False):
        super().__init__()
        self._setDefault(stopWords=list(_ENGLISH_STOP_WORDS),
                         caseSensitive=False)
        self._set(**{k: v for k, v in self._input_kwargs.items()
                     if v is not None})

    @staticmethod
    def loadDefaultStopWords(language: str) -> List[str]:
        if language != "english":
            raise ValueError("only 'english' default stop words are bundled")
        return list(_ENGLISH_STOP_WORDS)

    def getStopWords(self) -> List[str]:
        return self.getOrDefault(self.stopWords)

    def setStopWords(self, value) -> "StopWordsRemover":
        self._set(stopWords=list(value))
        return self

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        cs = self.getOrDefault(self.caseSensitive)
        stop = set(self.getStopWords() if cs
                   else [w.lower() for w in self.getStopWords()])
        vals = []
        for r in dataset.collect():
            words = list(r[in_col])
            vals.append([w for w in words
                         if (w if cs else w.lower()) not in stop])
        return _with_col(dataset, out_col, vals)


class StringIndexerModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, labels=None, handleInvalid="error"):
        super().__init__()
        self.labels: List[str] = list(labels or [])
        self._handle_invalid = handleInvalid

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        index = {v: float(i) for i, v in enumerate(self.labels)}
        rows, cols = [], dataset.columns + (
            [out_col] if out_col not in dataset.columns else [])
        for r in dataset.collect():
            v = str(r[in_col])
            if v in index:
                rows.append(Row(**{**r.asDict(), out_col: index[v]}))
            elif self._handle_invalid == "keep":
                rows.append(Row(**{**r.asDict(), out_col: float(len(index))}))
            elif self._handle_invalid == "skip":
                continue
            else:
                raise ValueError(f"Unseen label: {v!r} (StringIndexer "
                                 f"handleInvalid='error')")
        return DataFrame(rows, cols, dataset.num_partitions)


class StringIndexer(Estimator, HasInputCol, HasOutputCol):
    """Label string -> double index by descending frequency, ties broken
    alphabetically (pyspark 2.4 'frequencyDesc' order)."""

    handleInvalid = Param(Params._dummy(), "handleInvalid",
                          "error|skip|keep for unseen labels",
                          typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, handleInvalid="error"):
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**{k: v for k, v in self._input_kwargs.items()
                     if v is not None})

    def _fit(self, dataset: DataFrame) -> StringIndexerModel:
        in_col = self.getOrDefault(self.inputCol)
        counts: dict = {}
        for r in dataset.collect():
            v = str(r[in_col])
            counts[v] = counts.get(v, 0) + 1
        labels = sorted(counts, key=lambda v: (-counts[v], v))
        m = StringIndexerModel(labels,
                               self.getOrDefault(self.handleInvalid))
        m._set(inputCol=in_col,
               outputCol=self.getOrDefault(self.outputCol))
        return m


class StandardScalerModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, mean=None, std=None, with_mean=False, with_std=True):
        super().__init__()
        self.mean = np.asarray(mean) if mean is not None else None
        self.std = np.asarray(std) if std is not None else None
        self._with_mean, self._with_std = with_mean, with_std

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        vals = []
        for r in dataset.collect():
            arr = vector_to_array(r[in_col]).astype(float)
            if self._with_mean:
                arr = arr - self.mean
            if self._with_std:
                safe = np.where(self.std > 0, self.std, 1.0)
                arr = arr / safe
            vals.append(Vectors.dense(arr))
        return _with_col(dataset, out_col, vals)


class StandardScaler(Estimator, HasInputCol, HasOutputCol):
    """Unit-variance (and optionally zero-mean) scaling; std is the UNBIASED
    sample std, matching Spark MLlib."""

    withMean = Param(Params._dummy(), "withMean", "center before scaling",
                     typeConverter=TypeConverters.toBoolean)
    withStd = Param(Params._dummy(), "withStd", "scale to unit std",
                    typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, withMean=False,
                 withStd=True):
        super().__init__()
        self._setDefault(withMean=False, withStd=True)
        self._set(**{k: v for k, v in self._input_kwargs.items()
                     if v is not None})

    def _fit(self, dataset: DataFrame) -> StandardScalerModel:
        in_col = self.getOrDefault(self.inputCol)
        mat = np.stack([vector_to_array(r[in_col]).astype(float)
                        for r in dataset.collect()])
        mean = mat.mean(axis=0)
        std = mat.std(axis=0, ddof=1) if mat.shape[0] > 1 \
            else np.zeros(mat.shape[1])
        m = StandardScalerModel(mean, std,
                                self.getOrDefault(self.withMean),
                                self.getOrDefault(self.withStd))
        m._set(inputCol=in_col, outputCol=self.getOrDefault(self.outputCol))
        return m


class MinMaxScalerModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, emin=None, emax=None, lo=0.0, hi=1.0):
        super().__init__()
        self.originalMin = np.asarray(emin) if emin is not None else None
        self.originalMax = np.asarray(emax) if emax is not None else None
        self._lo, self._hi = lo, hi

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        rng = self.originalMax - self.originalMin
        vals = []
        for r in dataset.collect():
            arr = vector_to_array(r[in_col]).astype(float)
            # constant features map to the midpoint (Spark semantics)
            scaled = np.where(
                rng != 0,
                (arr - self.originalMin) / np.where(rng != 0, rng, 1.0)
                * (self._hi - self._lo) + self._lo,
                0.5 * (self._hi + self._lo))
            vals.append(Vectors.dense(scaled))
        return _with_col(dataset, out_col, vals)


class MinMaxScaler(Estimator, HasInputCol, HasOutputCol):
    min = Param(Params._dummy(), "min", "lower bound after scaling",
                typeConverter=TypeConverters.toFloat)
    max = Param(Params._dummy(), "max", "upper bound after scaling",
                typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, min=0.0, max=1.0):
        super().__init__()
        self._setDefault(min=0.0, max=1.0)
        self._set(**{k: v for k, v in self._input_kwargs.items()
                     if v is not None})

    def _fit(self, dataset: DataFrame) -> MinMaxScalerModel:
        in_col = self.getOrDefault(self.inputCol)
        mat = np.stack([vector_to_array(r[in_col]).astype(float)
                        for r in dataset.collect()])
        m = MinMaxScalerModel(mat.min(axis=0), mat.max(axis=0),
                              self.getOrDefault(self.min),
                              self.getOrDefault(self.max))
        m._set(inputCol=in_col, outputCol=self.getOrDefault(self.outputCol))
        return m


class Bucketizer(Transformer, HasInputCol, HasOutputCol):
    """Map a continuous column into bucket indices given split points;
    the last bucket includes its upper bound (pyspark semantics)."""

    splits = Param(Params._dummy(), "splits", "bucket split points",
                   typeConverter=TypeConverters.toListFloat)
    handleInvalid = Param(Params._dummy(), "handleInvalid",
                          "error|skip|keep for NaN/null entries",
                          typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, splits=None, inputCol=None, outputCol=None,
                 handleInvalid="error"):
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**{k: v for k, v in self._input_kwargs.items()
                     if v is not None})

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        splits = list(self.getOrDefault(self.splits))
        hi_mode = self.getOrDefault(self.handleInvalid)
        n_buckets = len(splits) - 1
        rows, cols = [], dataset.columns + (
            [out_col] if out_col not in dataset.columns else [])
        for r in dataset.collect():
            raw = r[in_col]
            v = float("nan") if raw is None else float(raw)
            if np.isnan(v):
                # Spark 2.4: handleInvalid governs NaN AND null entries
                if hi_mode == "keep":
                    b = float(n_buckets)
                elif hi_mode == "skip":
                    continue
                else:
                    raise ValueError("NaN/null value in Bucketizer input "
                                     "(handleInvalid='error')")
            elif v == splits[-1]:
                b = float(n_buckets - 1)
            elif splits[0] <= v < splits[-1]:
                b = float(int(np.searchsorted(splits, v, side="right")) - 1)
            else:
                # out-of-range is an error regardless of handleInvalid
                # (Spark 2.4 semantics)
                raise ValueError(f"value {v} out of bucket range "
                                 f"[{splits[0]}, {splits[-1]}]")
            rows.append(Row(**{**r.asDict(), out_col: b}))
        return DataFrame(rows, cols, dataset.num_partitions)


class IndexToString(Transformer, HasInputCol, HasOutputCol):
    """Inverse of StringIndexer: double index -> label string. ``labels``
    may be given explicitly (pyspark uses column metadata, which the local
    engine doesn't carry — pass the fitted StringIndexerModel's labels)."""

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, labels=None):
        super().__init__()
        self._labels = list(labels) if labels is not None else None
        kw = dict(self._input_kwargs)
        kw.pop("labels", None)
        self._set(**{k: v for k, v in kw.items() if v is not None})

    def setLabels(self, labels) -> "IndexToString":
        self._labels = list(labels)
        return self

    def _transform(self, dataset: DataFrame) -> DataFrame:
        if not self._labels:
            raise ValueError("IndexToString needs labels= (the local engine "
                             "carries no column metadata)")
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        vals = []
        for r in dataset.collect():
            i = int(r[in_col])
            if not 0 <= i < len(self._labels):
                raise ValueError(f"index {i} out of range for "
                                 f"{len(self._labels)} labels")
            vals.append(self._labels[i])
        return _with_col(dataset, out_col, vals)


class PCAModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, pc=None, explained_variance=None):
        super().__init__()
        # principal components [n_features, k], column-major like pyspark
        self.pc = np.asarray(pc) if pc is not None else None
        self.explainedVariance = (list(explained_variance)
                                  if explained_variance is not None else [])

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        vals = [Vectors.dense(vector_to_array(r[in_col]).astype(float)
                              @ self.pc)
                for r in dataset.collect()]
        return _with_col(dataset, out_col, vals)


class PCA(Estimator, HasInputCol, HasOutputCol):
    """Project vectors onto the top-k principal components. Like Spark
    MLlib, inputs are NOT re-centered at transform time; the components are
    computed from the centered covariance (SVD of X - mean)."""

    k = Param(Params._dummy(), "k", "number of components",
              typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, k=None, inputCol=None, outputCol=None):
        super().__init__()
        self._set(**{k_: v for k_, v in self._input_kwargs.items()
                     if v is not None})

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def _fit(self, dataset: DataFrame) -> PCAModel:
        k = self.getK()
        mat = np.stack([vector_to_array(r[self.getOrDefault(self.inputCol)])
                        .astype(float) for r in dataset.collect()])
        if k > mat.shape[1]:
            raise ValueError(f"k={k} > n_features={mat.shape[1]}")
        centered = mat - mat.mean(axis=0)
        _, svals, vt = np.linalg.svd(centered, full_matrices=False)
        var = (svals ** 2) / max(mat.shape[0] - 1, 1)
        ratio = var / var.sum() if var.sum() > 0 else var
        m = PCAModel(vt[:k].T, ratio[:k])
        m._set(inputCol=self.getOrDefault(self.inputCol),
               outputCol=self.getOrDefault(self.outputCol))
        return m


class ImputerModel(Model):
    def __init__(self, surrogates=None, input_cols=None, output_cols=None):
        super().__init__()
        self.surrogates = dict(surrogates or {})
        self._in = list(input_cols or [])
        self._out = list(output_cols or [])

    def _transform(self, dataset: DataFrame) -> DataFrame:
        out = dataset
        for ic, oc in zip(self._in, self._out):
            vals = []
            for r in out.collect():
                v = r[ic]
                bad = v is None or (isinstance(v, float) and v != v)
                vals.append(self.surrogates[ic] if bad else float(v))
            out = _with_col(out, oc, vals)
        return out


class Imputer(Estimator):
    """Replace missing values (null/NaN) in numeric columns with the
    column's mean or median (pyspark.ml.feature.Imputer)."""

    inputCols = Param(Params._dummy(), "inputCols", "columns to impute",
                      typeConverter=TypeConverters.toListString)
    outputCols = Param(Params._dummy(), "outputCols", "imputed columns",
                       typeConverter=TypeConverters.toListString)
    strategy = Param(Params._dummy(), "strategy", "mean|median",
                     typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, inputCols=None, outputCols=None, strategy="mean"):
        super().__init__()
        self._setDefault(strategy="mean")
        self._set(**{k: v for k, v in self._input_kwargs.items()
                     if v is not None})

    def _fit(self, dataset: DataFrame) -> ImputerModel:
        ics = self.getOrDefault(self.inputCols)
        ocs = self.getOrDefault(self.outputCols)
        strat = self.getOrDefault(self.strategy)
        if strat not in ("mean", "median"):
            raise ValueError(f"strategy must be mean|median, got {strat!r}")
        if len(ics) != len(ocs):
            raise ValueError("inputCols and outputCols must align")
        surrogates = {}
        for c in ics:
            good = [float(r[c]) for r in dataset.collect()
                    if r[c] is not None
                    and not (isinstance(r[c], float) and r[c] != r[c])]
            if not good:
                raise ValueError(f"column {c!r} has no non-missing values")
            surrogates[c] = (float(np.mean(good)) if strat == "mean"
                             else float(np.median(good)))
        return ImputerModel(surrogates, ics, ocs)
