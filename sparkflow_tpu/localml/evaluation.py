"""Evaluators: the subset the reference examples use
(``MulticlassClassificationEvaluator`` with accuracy,
``examples/simple_dnn.py:71-74``)."""

from __future__ import annotations

import numpy as np

from .param import Param, Params, TypeConverters, keyword_only, HasLabelCol, HasPredictionCol


class MulticlassClassificationEvaluator(HasLabelCol, HasPredictionCol):
    metricName = Param(Params._dummy(), "metricName", "metric name",
                       typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol="label", predictionCol="prediction",
                 metricName="f1"):
        super().__init__()
        self._setDefault(labelCol="label", predictionCol="prediction", metricName="f1")
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def isLargerBetter(self) -> bool:
        return True  # accuracy / f1 both improve upward

    def evaluate(self, dataset) -> float:
        label_col = self.getOrDefault(self.labelCol)
        pred_col = self.getOrDefault(self.predictionCol)
        metric = self.getOrDefault(self.metricName)
        y = np.array([float(r[label_col]) for r in dataset.collect()])
        p = np.array([float(r[pred_col]) for r in dataset.collect()])
        if metric == "accuracy":
            return float((y == p).mean()) if len(y) else 0.0
        if metric == "f1":  # weighted f1
            classes = np.unique(np.concatenate([y, p]))
            f1s, weights = [], []
            for c in classes:
                tp = float(((p == c) & (y == c)).sum())
                fp = float(((p == c) & (y != c)).sum())
                fn = float(((p != c) & (y == c)).sum())
                prec = tp / (tp + fp) if tp + fp else 0.0
                rec = tp / (tp + fn) if tp + fn else 0.0
                f1s.append(2 * prec * rec / (prec + rec) if prec + rec else 0.0)
                weights.append(float((y == c).sum()))
            return float(np.average(f1s, weights=weights)) if weights else 0.0
        raise ValueError(f"unsupported metric {metric!r}")


class BinaryClassificationEvaluator(HasLabelCol, HasPredictionCol):
    """areaUnderROC / areaUnderPR over a score column
    (pyspark.ml.evaluation.BinaryClassificationEvaluator). The score column
    (``rawPredictionCol``) may hold floats or vectors — for vectors the last
    component is the positive-class score, matching how sparkflow models
    emit probabilities (reference ``ml_util.py:74-81``)."""

    rawPredictionCol = Param(Params._dummy(), "rawPredictionCol",
                             "score column",
                             typeConverter=TypeConverters.toString)
    metricName = Param(Params._dummy(), "metricName", "metric name",
                       typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, rawPredictionCol="rawPrediction", labelCol="label",
                 metricName="areaUnderROC"):
        super().__init__()
        self._setDefault(rawPredictionCol="rawPrediction", labelCol="label",
                         metricName="areaUnderROC")
        self._set(**self._input_kwargs)

    def isLargerBetter(self) -> bool:
        return True  # both AUC metrics improve upward

    @staticmethod
    def _score(v) -> float:
        arr = np.atleast_1d(np.asarray(
            v.toArray() if hasattr(v, "toArray") else v, dtype=float))
        return float(arr[-1])

    def evaluate(self, dataset) -> float:
        label_col = self.getOrDefault(self.labelCol)
        score_col = self.getOrDefault(self.rawPredictionCol)
        metric = self.getOrDefault(self.metricName)
        rows = dataset.collect()
        y = np.array([float(r[label_col]) for r in rows])
        s = np.array([self._score(r[score_col]) for r in rows])
        if len(y) == 0 or len(np.unique(y)) < 2:
            return 0.0
        order = np.argsort(-s, kind="stable")
        y, s = y[order], s[order]
        tp = np.cumsum(y == 1)
        fp = np.cumsum(y == 0)
        # one curve point per DISTINCT score threshold (keep the last
        # cumulative count in each tie group) — otherwise tied scores make
        # the metric row-order-dependent; with collapsed ties the trapezoid
        # gives ties half credit (Mann-Whitney), matching Spark/sklearn
        last_of_group = np.concatenate([s[1:] != s[:-1], [True]])
        tp, fp = tp[last_of_group], fp[last_of_group]
        P, N = tp[-1], fp[-1]
        tpr = np.concatenate([[0.0], tp / P])
        fpr = np.concatenate([[0.0], fp / N])
        # np.trapezoid is numpy>=2 only; np.trapz its 1.x name
        _trapz = getattr(np, "trapezoid", None) or np.trapz
        if metric == "areaUnderROC":
            return float(_trapz(tpr, fpr))
        if metric == "areaUnderPR":
            prec = np.concatenate([[1.0], tp / np.maximum(tp + fp, 1)])
            rec = np.concatenate([[0.0], tp / P])
            return float(_trapz(prec, rec))
        raise ValueError(f"unsupported metric {metric!r}")


class RegressionEvaluator(HasLabelCol, HasPredictionCol):
    """rmse (default) / mse / mae / r2 over a numeric prediction column
    (pyspark.ml.evaluation.RegressionEvaluator)."""

    metricName = Param(Params._dummy(), "metricName", "metric name",
                       typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, predictionCol="prediction", labelCol="label",
                 metricName="rmse"):
        super().__init__()
        self._setDefault(predictionCol="prediction", labelCol="label",
                         metricName="rmse")
        self._set(**self._input_kwargs)

    def isLargerBetter(self) -> bool:
        # errors shrink toward better; r2 grows
        return self.getOrDefault(self.metricName) == "r2"

    def evaluate(self, dataset) -> float:
        label_col = self.getOrDefault(self.labelCol)
        pred_col = self.getOrDefault(self.predictionCol)
        metric = self.getOrDefault(self.metricName)
        rows = dataset.collect()
        y = np.array([float(r[label_col]) for r in rows])
        p = np.array([float(r[pred_col]) for r in rows])
        if len(y) == 0:
            return 0.0
        err = y - p
        if metric == "mse":
            return float(np.mean(err ** 2))
        if metric == "rmse":
            return float(np.sqrt(np.mean(err ** 2)))
        if metric == "mae":
            return float(np.mean(np.abs(err)))
        if metric == "r2":
            ss_tot = float(np.sum((y - y.mean()) ** 2))
            ss_res = float(np.sum(err ** 2))
            return 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
        raise ValueError(f"unsupported metric {metric!r}")
