"""MNIST CNN via Pipeline.fit — translation of the reference's
``examples/cnn_example.py``. This is the headline benchmark config
(BASELINE.md: ≥5x reference throughput on TPU)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

from sparkflow_tpu import nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.tensorflow_async import SparkAsyncDL
from sparkflow_tpu.compat import USING_PYSPARK

if USING_PYSPARK:
    from pyspark.sql import SparkSession
    from pyspark.ml.feature import VectorAssembler, OneHotEncoder
    from pyspark.ml.pipeline import Pipeline
    from pyspark.sql.functions import rand
else:
    from sparkflow_tpu.localml import (LocalSession as SparkSession,
                                       VectorAssembler, OneHotEncoder, Pipeline)
    from sparkflow_tpu.localml.sql import functions
    rand = functions.rand

from simple_dnn import load_df


def cnn_model():
    x = nn.placeholder([None, 784], name='x')
    y = nn.placeholder([None, 10], name='y')
    xr = nn.reshape(x, shape=[-1, 28, 28, 1])
    conv1 = nn.conv2d(xr, 32, 5, activation='relu')
    conv1 = nn.max_pooling2d(conv1, 2, 2)
    conv2 = nn.conv2d(conv1, 64, 3, activation='relu')
    conv2 = nn.max_pooling2d(conv2, 2, 2)
    fc1 = nn.flatten(conv2)
    out = nn.dense(fc1, 10)
    z = nn.argmax(out, 1, name='out')
    loss = nn.softmax_cross_entropy(y, out)
    return loss


if __name__ == '__main__':
    # a wedged TPU relay must not hang the demo: probe the
    # backend and fall back to CPU (same guard bench.py uses)
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()
    spark = SparkSession.builder \
        .appName("examples") \
        .master('local[4]').config('spark.driver.memory', '4g') \
        .getOrCreate()

    df = load_df(spark)
    mg = build_graph(cnn_model)
    va = VectorAssembler(inputCols=df.columns[1:785], outputCol='features')
    encoded = OneHotEncoder(inputCol='_c0', outputCol='labels', dropLast=False)

    spark_model = SparkAsyncDL(
        inputCol='features',
        tensorflowGraph=mg,
        tfInput='x:0',
        tfLabel='y:0',
        tfOptimizer='adam',
        miniBatchSize=300,
        miniStochasticIters=-1,
        shufflePerIter=True,
        iters=2 if os.environ.get("SPARKFLOW_TPU_SMOKE") else 50,
        partitions=4,
        tfLearningRate=.0001,
        predictionCol='predicted',
        labelCol='labels',
        verbose=1
    )

    p = Pipeline(stages=[va, encoded, spark_model]).fit(df)
    p.write().overwrite().save("cnn")
