"""Unified observability: spans, step-phase stats, metric exporters.

- :mod:`~sparkflow_tpu.obs.spans` — ``Span``/``Tracer``: nested host-side
  timing with Chrome-trace / JSONL export and cross-thread propagation.
- :mod:`~sparkflow_tpu.obs.stepstats` — ``StepStats``: per-step phase
  breakdown (transfer / compile / step / metrics / checkpoint) + derived
  throughput and MFU gauges for ``Trainer.fit``.
- :mod:`~sparkflow_tpu.obs.exporters` — ``prometheus_text`` exposition of
  the whole metrics registry and the ``MemoryWatcher`` device-memory
  sampler.

See ``docs/observability.md`` for the end-to-end walkthrough.
"""

from .spans import Span, Tracer, current_tracer, default_tracer, span
from .stepstats import StepStats
from .exporters import MemoryWatcher, prometheus_name, prometheus_text

__all__ = [
    "Span", "Tracer", "current_tracer", "default_tracer", "span",
    "StepStats",
    "MemoryWatcher", "prometheus_name", "prometheus_text",
]
