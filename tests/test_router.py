"""Fleet-scale serving: health-gated router, circuit breakers, admission,
hedging, result cache, and chaos-tested failover.

Pins the PR's acceptance criterion directly: with 3 replicas under sustained
load, killing and restarting one replica produces zero client-visible
failures (the router retries/reroutes), and every routed response echoes the
originating ``X-Request-Id`` end to end.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.obs.spans import Tracer
from sparkflow_tpu.resilience import faults
from sparkflow_tpu.resilience.lifecycle import ServerState
from sparkflow_tpu.serving import (BreakerState, CircuitBreaker,
                                   InferenceEngine, InferenceServer,
                                   Membership, ResultCache, RouterServer,
                                   ServingClient, ServingError, TokenBucket)

IN, OUT = "x:0", "out/BiasAdd:0"


def mlp_graph():
    x = nn.placeholder([None, 4], name="x")
    h = nn.dense(x, 3, activation="relu")
    out = nn.dense(h, 2, name="out")
    nn.mean_squared_error(x, out)


@pytest.fixture(scope="module")
def graph_json():
    return build_graph(mlp_graph)


@pytest.fixture(scope="module")
def weights():
    rs = np.random.RandomState(0)
    return [rs.randn(4, 3).astype(np.float32),
            rs.randn(3).astype(np.float32),
            rs.randn(3, 2).astype(np.float32),
            rs.randn(2).astype(np.float32)]


@pytest.fixture(scope="module")
def manual(weights):
    def fwd(x):
        h = np.maximum(np.asarray(x) @ weights[0] + weights[1], 0.0)
        return h @ weights[2] + weights[3]
    return fwd


@pytest.fixture(scope="module")
def make_engine(graph_json, weights):
    def make():
        return InferenceEngine(graph_json, weights, input_name=IN,
                               output_name=OUT, max_batch=16)
    return make


class SlowEngine:
    """Stub engine whose predict sleeps — the straggler replica."""
    max_batch = 16
    _multi = False
    _in_shapes = [(4,)]

    def __init__(self, delay_s=0.4):
        self.delay_s = delay_s

    def predict(self, x):
        time.sleep(self.delay_s)
        return np.asarray(x)[:, :2]

    def stats(self):
        return {}


# -- circuit breaker ---------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_consecutive_failures():
    clk = FakeClock()
    br = CircuitBreaker(failure_threshold=3, recovery_s=5.0, clock=clk)
    assert br.state is BreakerState.CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state is BreakerState.CLOSED and br.allow()
    br.record_failure()
    assert br.state is BreakerState.OPEN
    assert not br.allow()
    assert br.ejections == 1


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(failure_threshold=2, clock=FakeClock())
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state is BreakerState.CLOSED  # never two in a row


def test_breaker_half_open_single_trial_then_close_or_reopen():
    clk = FakeClock()
    br = CircuitBreaker(failure_threshold=1, recovery_s=2.0, clock=clk)
    br.record_failure()
    assert br.state is BreakerState.OPEN and not br.allow()
    clk.t = 2.5
    assert br.allow()          # the single half-open trial
    assert br.state is BreakerState.HALF_OPEN
    assert not br.allow()      # second caller must NOT sneak through
    br.record_failure()        # trial failed -> re-open for another window
    assert br.state is BreakerState.OPEN and not br.allow()
    clk.t = 5.0
    assert br.allow()
    br.record_success()        # trial passed -> closed, traffic resumes
    assert br.state is BreakerState.CLOSED and br.allow()


def test_breaker_trip_forces_open():
    br = CircuitBreaker(failure_threshold=100, clock=FakeClock())
    br.trip()
    assert br.state is BreakerState.OPEN and not br.allow()


# -- token bucket / cache ----------------------------------------------------

def test_token_bucket_sheds_then_refills():
    clk = FakeClock()
    bucket = TokenBucket(rate=10.0, burst=2.0, clock=clk)
    assert bucket.try_acquire()
    assert bucket.try_acquire()
    assert not bucket.try_acquire()   # burst spent, no time has passed
    clk.t = 0.1                       # 10/s * 0.1s = 1 token back
    assert bucket.try_acquire()
    assert not bucket.try_acquire()


def test_result_cache_lru_and_counters():
    cache = ResultCache(max_entries=2)
    k1, k2, k3 = (ResultCache.key(b) for b in (b"a", b"b", b"c"))
    assert cache.get(k1) is None
    cache.put(k1, {"predictions": [1]})
    cache.put(k2, {"predictions": [2]})
    assert cache.get(k1) == {"predictions": [1]}   # refreshes k1's recency
    cache.put(k3, {"predictions": [3]})            # evicts k2, not k1
    assert cache.get(k2) is None
    assert cache.get(k1) is not None
    assert cache.stats() == {"entries": 2, "hits": 2, "misses": 2}


# -- membership --------------------------------------------------------------

def test_membership_picks_least_loaded_and_respects_gates():
    m = Membership(["http://127.0.0.1:1", "http://127.0.0.1:2",
                    "http://127.0.0.1:3"], probe_interval_s=60.0)
    a, b, c = m.replicas
    m.begin_dispatch(a)
    m.begin_dispatch(a)
    m.begin_dispatch(b)
    assert m.pick() is c                      # least loaded wins
    assert m.pick(exclude=[c]) is b           # then next-least
    c.breaker.trip()
    assert m.pick() is b                      # ejected replica skipped
    m.eject(b, "draining")
    assert m.pick() is a                      # unhealthy replica skipped
    m.eject(a)
    assert m.pick() is None                   # nobody left
    assert m.healthy_count() == 0
    m.stop()


def test_membership_snapshot_and_gauges():
    from sparkflow_tpu.utils.metrics import Metrics
    metrics = Metrics()
    m = Membership(["http://127.0.0.1:1", "http://127.0.0.1:2"],
                   probe_interval_s=60.0, metrics=metrics)
    m.record_failure(m.replicas[0], "test")
    m.publish_gauges()
    g = metrics.gauges()
    assert g["router/replica0/error_rate"] == 1.0
    assert g["router/replica0/healthy"] == 1.0   # breaker still closed
    assert g["router/replica1/error_rate"] == 0.0
    rows = m.snapshot()
    assert [r["url"] for r in rows] == ["http://127.0.0.1:1",
                                        "http://127.0.0.1:2"]
    assert rows[0]["failures"] == 1 and rows[0]["breaker"] == "closed"
    m.stop()


def test_generate_pick_prefers_kv_headroom():
    """Generate dispatch ranks replicas by decode KV headroom, not queue
    depth: page-/slot-starved replicas sort last (still dispatchable — the
    replica's own 503 is the real backpressure), unknown headroom after any
    known-positive one. Predict picks are untouched."""
    m = Membership(["http://127.0.0.1:1", "http://127.0.0.1:2",
                    "http://127.0.0.1:3"], probe_interval_s=60.0)
    ra, rb, rc = m.replicas
    ra.decode_pages_free, ra.decode_free_slots = 0, 2    # page-starved
    rb.decode_pages_free, rb.decode_free_slots = 10, 1
    rc.decode_pages_free, rc.decode_free_slots = 40, 3
    assert m.pick(signal="generate") is rc               # most headroom
    assert m.pick(exclude=[rc], signal="generate") is rb
    assert m.pick(exclude=[rb, rc], signal="generate") is ra  # last resort
    rc.queue_depth = 50
    rb.queue_depth = 1   # strict predict order: break the equal-load tie
    assert m.pick(signal="generate") is rc  # queue depth is not the signal
    assert m.pick(signal="predict") is ra   # predict ranking unchanged
    rb.decode_pages_free = -1               # unknown sorts after known
    assert m.pick(exclude=[rc], signal="generate") is rb  # but before starved
    m.stop()


def test_page_starved_replica_keeps_predict_loses_generate(make_engine):
    """End to end: a replica whose decode pool is exhausted stops receiving
    /v1/generate traffic from the router but keeps serving /v1/predict."""
    import jax
    from sparkflow_tpu.models.registry import (build_registry_spec,
                                               model_from_json)
    from sparkflow_tpu.serving import ContinuousBatcher, DecodeEngine
    spec = build_registry_spec("transformer_lm", vocab_size=61, hidden=32,
                               num_layers=2, num_heads=4, mlp_dim=64,
                               max_len=32, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    engines = [DecodeEngine(model, params, num_slots=2, page_size=8, seed=0)
               for _ in range(2)]
    cbs = [ContinuousBatcher(e, max_queue=8) for e in engines]
    servers = [InferenceServer(make_engine(), generate_batcher=cb,
                               max_delay_ms=1.0).start() for cb in cbs]
    # starve replica 0's decode plane before the router ever probes it:
    # every slot (and its page reservation) is occupied, nothing decodes
    for slot in range(2):
        engines[0].kv.alloc(slot, 1, engines[0].max_seq_len)
    pre = [e.stats()["prefills"] for e in engines]
    router = RouterServer([s.url for s in servers], probe_interval_s=0.05,
                          dispatch_retries=2).start()
    try:
        m = router.membership
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if (m.replicas[0].decode_free_slots == 0
                    and m.replicas[1].decode_free_slots > 0):
                break
            time.sleep(0.02)
        else:
            pytest.fail("probes never harvested the decode headroom")
        assert m.pick(signal="generate") is m.replicas[1]
        assert m.pick(signal="predict") is m.replicas[0]
        cli = ServingClient(router.url, timeout=60)
        for _ in range(3):
            r = cli.generate([3, 1, 4], max_new_tokens=4)
            assert r["num_tokens"] == 4
        assert engines[0].stats()["prefills"] == pre[0]  # starved: bypassed
        assert engines[1].stats()["prefills"] == pre[1] + 3
        out = cli.predict(np.zeros((2, 4), np.float32))  # predict still up
        assert out.shape == (2, 2)
    finally:
        router.stop()
        for cb in cbs:
            cb.close()
        for s in servers:
            s.stop()


# -- replica /healthz load signal (satellite) --------------------------------

def test_replica_healthz_reports_queue_depth_and_in_flight(make_engine):
    with InferenceServer(make_engine(), max_delay_ms=1.0) as srv:
        health = ServingClient(srv.url).healthz()
        assert health["queue_depth"] == 0
        assert health["in_flight"] == 0
        assert health["status"] == "ok"


# -- client keep-alive + per-request timeout (satellite) ---------------------

def test_client_reuses_keepalive_connection(make_engine):
    with InferenceServer(make_engine(), max_delay_ms=1.0) as srv:
        client = ServingClient(srv.url)
        client.healthz()
        assert len(client._pool._idle) == 1
        conn = client._pool._idle[0]
        client.healthz()
        client.predict(np.zeros((2, 4), np.float32))
        assert client._pool._idle[0] is conn   # same socket, three calls
        client.close()
        assert client._pool._idle == []


def test_client_per_request_timeout():
    with InferenceServer(SlowEngine(0.5), max_delay_ms=0.0) as srv:
        srv._httpd.handle_error = lambda *a: None  # quiet the torn writes
        client = ServingClient(srv.url, retries=0)
        with pytest.raises(OSError):
            client.predict(np.zeros((2, 4), np.float32), timeout_s=0.05)
        out = client.predict(np.zeros((2, 4), np.float32), timeout_s=5.0)
        assert out.shape == (2, 2)


# -- router end to end -------------------------------------------------------

@pytest.fixture()
def fleet(make_engine):
    servers = [InferenceServer(make_engine(), max_delay_ms=1.0).start()
               for _ in range(3)]
    router = RouterServer([s.url for s in servers], probe_interval_s=0.1,
                          recovery_s=0.5, dispatch_retries=4).start()
    yield router, servers
    router.stop()
    for s in servers:
        if s.lifecycle.state is not ServerState.STOPPED:
            s.stop()


def test_router_parity_and_request_id_echo(fleet, manual, rng):
    router, _servers = fleet
    client = ServingClient(router.url)
    x = rng.randn(5, 4).astype(np.float32)
    np.testing.assert_allclose(client.predict(x), manual(x),
                               rtol=1e-4, atol=1e-4)
    full = client.predict_full(x, request_id="rid-router-1")
    assert full["request_id"] == "rid-router-1"
    assert full["x_request_id_header"] == "rid-router-1"
    assert "timing_ms" in full          # the replica's decomposition rides
    assert full["rows"] == 5            # through the router untouched


def test_router_healthz_lists_fleet(fleet):
    router, _servers = fleet
    health = ServingClient(router.url).healthz()
    assert health["status"] == "ok" and health["role"] == "router"
    assert health["healthy_replicas"] == 3
    assert len(health["replicas"]) == 3
    assert all(r["breaker"] == "closed" for r in health["replicas"])


def test_router_400_passes_through_without_retry(fleet):
    router, _servers = fleet
    client = ServingClient(router.url, retries=0)
    with pytest.raises(ServingError) as exc_info:
        client.predict(np.zeros((2, 9), np.float32))  # wrong feature dim
    assert exc_info.value.status == 400
    assert exc_info.value.code == "bad_request"
    metrics = ServingClient(router.url).metrics()
    assert metrics["counters"].get("router/rerouted", 0) == 0


def test_router_admission_token_bucket_sheds(make_engine):
    with InferenceServer(make_engine(), max_delay_ms=1.0) as srv:
        with RouterServer([srv.url], probe_interval_s=60.0,
                          admission_rate=0.001, admission_burst=1.0) as router:
            client = ServingClient(router.url, retries=0)
            assert client.predict(np.zeros((1, 4), np.float32)).shape == (1, 2)
            with pytest.raises(ServingError) as exc_info:
                client.predict(np.zeros((1, 4), np.float32))
            assert exc_info.value.status == 503
            assert exc_info.value.code == "queue_full"
            assert exc_info.value.retry_after is not None
            m = ServingClient(router.url).metrics()
            assert m["counters"]["router/admission_rejections"] == 1


def test_router_sheds_on_inflight_cap(make_engine):
    with InferenceServer(make_engine(), max_delay_ms=1.0) as srv:
        with RouterServer([srv.url], probe_interval_s=60.0,
                          max_inflight=0) as router:
            client = ServingClient(router.url, retries=0)
            with pytest.raises(ServingError) as exc_info:
                client.predict(np.zeros((1, 4), np.float32))
            assert exc_info.value.status == 503
            assert exc_info.value.code == "queue_full"


def test_router_result_cache_hit_skips_replicas(fleet, rng):
    router, _servers = fleet
    router.cache = ResultCache(max_entries=8)
    client = ServingClient(router.url)
    x = rng.randn(2, 4).astype(np.float32)
    first = client.predict_full(x, request_id="miss-1")
    assert "cache" not in first
    second = client.predict_full(x, request_id="hit-1")
    assert second["cache"] == "hit"
    assert second["request_id"] == "hit-1"   # id is per-request, not cached
    assert second["predictions"] == first["predictions"]
    assert router.cache.stats()["hits"] == 1


def test_router_reroutes_on_injected_dispatch_failure(fleet, manual, rng):
    router, _servers = fleet
    client = ServingClient(router.url, retries=0)
    x = rng.randn(3, 4).astype(np.float32)
    with faults.inject("replica.predict", fail_calls=[0]) as spec:
        out = client.predict(x)
    np.testing.assert_allclose(out, manual(x), rtol=1e-4, atol=1e-4)
    assert spec.failures == 1
    m = ServingClient(router.url).metrics()
    assert m["counters"]["router/rerouted"] >= 1


def test_router_dispatch_fault_surfaces_as_500(fleet):
    router, _servers = fleet
    client = ServingClient(router.url, retries=0)
    with faults.inject("router.dispatch", fail_calls=[0]):
        with pytest.raises(ServingError) as exc_info:
            client.predict(np.zeros((1, 4), np.float32))
    assert exc_info.value.status == 500
    assert exc_info.value.code == "internal"


def test_router_all_replicas_down_returns_structured_503(make_engine):
    srv = InferenceServer(make_engine(), max_delay_ms=1.0).start()
    router = RouterServer([srv.url], probe_interval_s=0.05,
                          dispatch_retries=1,
                          failure_threshold=1).start()
    try:
        srv.kill()
        time.sleep(0.2)  # let the prober notice
        client = ServingClient(router.url, retries=0)
        with pytest.raises(ServingError) as exc_info:
            client.predict(np.zeros((1, 4), np.float32))
        assert exc_info.value.status == 503
        assert exc_info.value.code in ("no_healthy_replicas", "draining")
        assert exc_info.value.retry_after is not None
    finally:
        router.stop()


def test_router_spans_carry_request_id(make_engine):
    tracer = Tracer()
    with InferenceServer(make_engine(), max_delay_ms=1.0) as srv:
        with RouterServer([srv.url], probe_interval_s=60.0,
                          tracer=tracer) as router:
            ServingClient(router.url).predict_full(
                np.zeros((1, 4), np.float32), request_id="span-rid")
    names = {}
    for sp in tracer.spans():
        names.setdefault(sp.name, sp)
    req = names.get("router/request")
    assert req is not None and req.args["request_id"] == "span-rid"
    dispatch = names.get("router/dispatch")
    assert dispatch is not None and dispatch.parent_id is not None


def test_router_prometheus_exposes_per_replica_gauges(fleet):
    router, _servers = fleet
    client = ServingClient(router.url)
    client.predict(np.zeros((1, 4), np.float32))
    text = client.metrics_prometheus()
    assert "router_replica0_healthy 1.0" in text
    assert "router_replica1_ejected 0.0" in text
    assert "router_replica2_error_rate" in text
    assert "router_requests" in text


def test_router_hedges_around_straggler_replica(make_engine, manual, rng):
    slow = InferenceServer(SlowEngine(0.6), max_delay_ms=0.0).start()
    slow._httpd.handle_error = lambda *a: None  # hedge losers tear sockets
    fast = InferenceServer(make_engine(), max_delay_ms=1.0).start()
    router = RouterServer([slow.url, fast.url], probe_interval_s=60.0,
                          hedge=True, hedge_delay_ms=50.0,
                          dispatch_retries=1).start()
    try:
        client = ServingClient(router.url)
        x = rng.randn(2, 4).astype(np.float32)
        t0 = time.perf_counter()
        out = client.predict(x)        # primary -> slow (index 0), hedged
        elapsed = time.perf_counter() - t0
        np.testing.assert_allclose(out, manual(x), rtol=1e-4, atol=1e-4)
        assert elapsed < 0.55          # did NOT wait out the straggler
        m = ServingClient(router.url).metrics()
        assert m["counters"]["router/hedges"] >= 1
        assert m["counters"]["router/hedge_wins"] >= 1
    finally:
        router.stop()
        fast.stop()
        slow.kill()                    # its worker is mid-sleep; don't drain


# -- drain under load (satellite) --------------------------------------------

def test_drain_under_load_sigterm_ejects_and_reroutes(make_engine, manual):
    """SIGTERM one replica mid-burst: every in-flight request completes, the
    router ejects it on the Draining 503, and retried requests land on the
    survivor — zero client-visible failures."""
    victim = InferenceServer(make_engine(), max_delay_ms=1.0).start()
    survivor = InferenceServer(make_engine(), max_delay_ms=1.0).start()
    assert victim.install_signal_handlers()
    router = RouterServer([victim.url, survivor.url], probe_interval_s=0.1,
                          dispatch_retries=4).start()
    errors, done = [], []

    def worker(k):
        client = ServingClient(router.url, retries=0)
        local = np.random.RandomState(k)
        for j in range(10):
            x = local.randn(1 + j % 3, 4).astype(np.float32)
            try:
                np.testing.assert_allclose(client.predict(x), manual(x),
                                           rtol=1e-4, atol=1e-4)
                done.append(1)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
        client.close()

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.05)                       # burst is in flight
        os.kill(os.getpid(), signal.SIGTERM)   # real preemption signal
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        assert len(done) == 40
        deadline = time.time() + 5
        while (victim.lifecycle.state is not ServerState.DRAINING
               and time.time() < deadline):
            time.sleep(0.02)
        assert victim.lifecycle.state is ServerState.DRAINING
        health = ServingClient(router.url).healthz()
        assert health["healthy_replicas"] >= 1
        victim_row = next(r for r in health["replicas"]
                          if r["url"] == victim.url)
        assert not victim_row["healthy"]       # ejected from rotation
    finally:
        router.stop()
        survivor.stop()
        victim.stop()                          # also restores the handler


# -- the pinned acceptance test ----------------------------------------------

def test_chaos_fleet_kill_restart_zero_client_failures(make_engine, manual):
    """3 replicas under sustained load; one is hard-killed mid-burst and
    later restarted on the same port. Every request must succeed (router
    retries absorb the failure) and every response must echo its
    originating X-Request-Id end to end."""
    servers = [InferenceServer(make_engine(), max_delay_ms=1.0).start()
               for _ in range(3)]
    victim_port = servers[0].port
    router = RouterServer([s.url for s in servers], probe_interval_s=0.1,
                          recovery_s=0.3, dispatch_retries=5).start()
    errors, echoes = [], []
    stop_load = threading.Event()

    def worker(k):
        client = ServingClient(router.url, retries=0)
        local = np.random.RandomState(1000 + k)
        for j in range(14):
            rid = f"chaos-{k}-{j}"
            x = local.randn(1 + j % 4, 4).astype(np.float32)
            try:
                full = client.predict_full(x, request_id=rid,
                                           timeout_s=30.0)
                np.testing.assert_allclose(np.asarray(full["predictions"]),
                                           manual(x), rtol=1e-4, atol=1e-4)
                echoes.append((rid, full["request_id"],
                               full["x_request_id_header"]))
            except Exception as exc:  # noqa: BLE001
                errors.append((rid, exc))
        client.close()

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(6)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)
        servers[0].kill()                               # SIGKILL semantics
        time.sleep(0.3)
        servers[0] = InferenceServer(make_engine(), port=victim_port,
                                     max_delay_ms=1.0).start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        stop_load.set()
        # zero client-visible failures: the router absorbed the kill
        assert not errors, f"{len(errors)} failed, first: {errors[:3]}"
        assert len(echoes) == 6 * 14
        # every response echoed its originating request id, body and header
        for rid, body_rid, header_rid in echoes:
            assert body_rid == rid and header_rid == rid
        # the restarted replica rejoins the rotation
        deadline = time.time() + 10
        health = None
        while time.time() < deadline:
            health = ServingClient(router.url).healthz()
            if health["healthy_replicas"] == 3:
                break
            time.sleep(0.1)
        assert health is not None and health["healthy_replicas"] == 3, health
        m = ServingClient(router.url).metrics()
        assert m["counters"]["router/http_200"] >= 6 * 14
    finally:
        router.stop()
        for s in servers:
            if s.lifecycle.state is not ServerState.STOPPED:
                s.stop()


# -- graftcheck keeps the router's shared state clean ------------------------

def test_router_lock_lint_is_clean():
    """GC-L301/302/303 over the router's lock-guarded membership and
    counter state: the fleet layer must satisfy the same concurrency
    conventions graftcheck enforces on the rest of the serving stack."""
    from sparkflow_tpu.analysis.locks import lint_paths
    base = os.path.join(os.path.dirname(__file__), "..", "sparkflow_tpu",
                        "serving")
    files = [os.path.join(base, f)
             for f in ("router.py", "membership.py", "client.py",
                       "server.py", "batcher.py")]
    findings = lint_paths(files)
    assert findings == [], [str(f) for f in findings]
