"""Structured training metrics (replaces the reference's print-based logging,
``sparkflow/HogwildSparkModel.py:94-98`` — SURVEY.md §5 "observability").

A process-local registry of counters/gauges/timings/histograms with JSONL
export and an optional per-step callback fan-out. Cheap enough to leave on:
recording is a dict update; device syncs only happen where the caller already
has a value. Histograms (``observe``/``percentile``) back the serving-side
latency metrics (p50/p95/p99) and are bounded by a reservoir cap so a
long-lived server never grows without limit.

Four value kinds, four write paths:

- ``scalar(name, v, step)`` — a time series (loss curves); every point kept.
- ``incr(name)``            — a monotone counter (requests served).
- ``gauge(name, v)``        — last-value-wins (queue depth, memory in use);
                              no history, one float per name.
- ``observe(name, v)``      — a distribution (latencies); reservoir-sampled.

Serving handlers record from many threads, so every read-modify-write —
including ``scalar``'s default-step computation and the listener snapshot —
happens under one registry lock. Listeners themselves are invoked *outside*
the lock (a listener that records back into the registry must not deadlock).
Prometheus text exposition of the whole registry lives in
:mod:`sparkflow_tpu.obs.exporters`.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# Per-histogram sample cap. Beyond it, reservoir sampling keeps a uniform
# sample of the whole stream (percentiles stay unbiased) instead of the
# unbounded append a months-long serving process would otherwise pay for.
HISTOGRAM_RESERVOIR = 4096


class _Histogram:
    """Reservoir-sampled value distribution with exact count/min/max/sum."""

    __slots__ = ("samples", "count", "total", "vmin", "vmax", "_rng")

    def __init__(self, seed: int = 0):
        self.samples: List[float] = []
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)
        if len(self.samples) < HISTOGRAM_RESERVOIR:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < HISTOGRAM_RESERVOIR:
                self.samples[j] = value

    def percentile(self, q: float) -> float:
        """Linear-interpolated q-th percentile (q in [0, 100]) of the
        reservoir sample."""
        if not self.samples:
            raise ValueError("empty histogram")
        s = sorted(self.samples)
        if len(s) == 1:
            return s[0]
        pos = (q / 100.0) * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "sum": self.total,
                "mean": self.total / self.count if self.count else 0.0,
                "min": self.vmin, "max": self.vmax,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}


class Metrics:
    def __init__(self):
        self._scalars: Dict[str, List[tuple]] = defaultdict(list)
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, Tuple[float, float]] = {}  # name -> (v, ts)
        self._hists: Dict[str, _Histogram] = {}
        self._listeners: List[Callable[[str, float, int], None]] = []
        self._lock = threading.Lock()

    def scalar(self, name: str, value: float, step: Optional[int] = None) -> None:
        value = float(value)
        with self._lock:
            # the default step is "next index in this series" — a
            # read-modify-write that must not race with another recorder
            if step is None:
                step = len(self._scalars[name])
            self._scalars[name].append((step, value, time.time()))
            listeners = tuple(self._listeners)
        # fan out outside the lock: a listener recording back into this
        # registry (e.g. mirroring losses into a gauge) must not deadlock
        for fn in listeners:
            fn(name, value, step)

    def incr(self, name: str, amount: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += amount

    def gauge(self, name: str, value: float) -> None:
        """Last-value-wins instantaneous reading (queue depth, bytes in
        use). Unlike ``scalar`` it keeps no history — the natural shape for
        sampled state, and what Prometheus expects of a gauge."""
        with self._lock:
            self._gauges[name] = (float(value), time.time())

    def observe(self, name: str, value: float) -> None:
        """Record one observation into the ``name`` histogram (latencies,
        batch sizes, fill ratios — anything whose distribution matters more
        than its last value)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(seed=len(self._hists))
            h.add(float(value))

    def percentile(self, name: str, q: float) -> float:
        """q-th percentile (q in [0, 100]) of histogram ``name``."""
        with self._lock:
            if name not in self._hists:
                raise KeyError(f"no histogram named {name!r}")
            return self._hists[name].percentile(q)

    def percentiles(self, name: str,
                    qs: Sequence[float] = (50, 95, 99)) -> Dict[str, float]:
        """{'p50': ..., 'p95': ..., 'p99': ...} for histogram ``name``."""
        return {f"p{g:g}": self.percentile(name, g) for g in qs}

    def subscribe(self, fn: Callable[[str, float, int], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def series(self, name: str) -> List[tuple]:
        with self._lock:
            return list(self._scalars.get(name, []))

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {name: v for name, (v, _) in self._gauges.items()}

    def histograms(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {name: h.summary() for name, h in self._hists.items()
                    if h.count}

    def _snapshot(self):
        """One consistent view of every table (single lock acquisition, so
        summary/JSONL export can't interleave with concurrent recorders)."""
        with self._lock:
            scalars = {name: list(pts) for name, pts in self._scalars.items()}
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {name: h.summary() for name, h in self._hists.items()
                     if h.count}
        return scalars, counters, gauges, hists

    def summary(self) -> Dict[str, Any]:
        scalars, counters, gauges, hists = self._snapshot()
        out: Dict[str, Any] = {"counters": counters}
        for name, pts in scalars.items():
            vals = [v for _, v, _ in pts]
            out[name] = {"last": vals[-1], "min": min(vals), "max": max(vals),
                         "count": len(vals)}
        if gauges:
            out["gauges"] = {name: v for name, (v, _) in gauges.items()}
        if hists:
            out["histograms"] = hists
        return out

    def dump_jsonl(self, path: str) -> None:
        scalars, counters, gauges, hists = self._snapshot()
        with open(path, "w") as f:
            for name, pts in scalars.items():
                for step, value, ts in pts:
                    f.write(json.dumps({"name": name, "step": step,
                                        "value": value, "ts": ts}) + "\n")
            for name, value in counters.items():
                f.write(json.dumps({"name": name, "counter": value}) + "\n")
            for name, (value, ts) in gauges.items():
                f.write(json.dumps({"name": name, "gauge": value,
                                    "ts": ts}) + "\n")
            for name, hist in hists.items():
                f.write(json.dumps({"name": name, "histogram": hist}) + "\n")

    def reset(self) -> None:
        with self._lock:
            self._scalars.clear()
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


default_metrics = Metrics()


class timer:
    """``with timer('stage'):`` records wall seconds into the registry."""

    def __init__(self, name: str, metrics: Optional[Metrics] = None):
        self.name = name
        self.metrics = metrics or default_metrics

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.scalar(f"time/{self.name}", time.perf_counter() - self._t0)
        return False
