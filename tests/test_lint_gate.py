"""The graftcheck CI gate: the FULL static pass (every GC family — AST
lint, jaxpr-free sharding checks, lock discipline, lock-order graph,
policy parity, resource lifecycles) over the repo's own source +
examples must report ZERO findings.

This is the tier-1 twin of ``make lint-graft-strict``: a regression that
introduces a lock-order cycle, an unguarded shared field, a leaked pool
checkout, or an uncleaned per-entity gauge namespace fails CI here, with
the finding rendered in the assertion message.

Also pins the gate's mechanics: the CLI exits nonzero on any finding and
zero on a clean tree, and ``--baseline`` / ``--write-baseline`` let a
repo adopt the linter incrementally without suppressing NEW findings.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkflow_tpu.analysis import cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DEFECT = '''
class ConnectionPool:
    def acquire(self): ...
    def release(self, conn, reuse=True): ...

class Client:
    def __init__(self):
        self.pool = ConnectionPool()

    def bad(self, flag):
        conn, reused = self.pool.acquire()
        if flag:
            return None
        self.pool.release(conn)
        return flag
'''

_SECOND_DEFECT = '''
import threading

def orphan():
    t = threading.Thread(target=print)
    t.start()
'''


def test_repo_full_static_pass_clean():
    paths = [os.path.join(REPO, "sparkflow_tpu"),
             os.path.join(REPO, "examples")]
    findings = cli.run_static(paths)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path, capsys):
    (tmp_path / "leaky.py").write_text(_DEFECT)
    rc = cli.main([str(tmp_path), "--no-trace", "--format", "json"])
    out = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
           if ln.strip()]
    assert rc == 1
    assert [f["rule"] for f in out] == ["GC-X601"]

    (tmp_path / "leaky.py").write_text(_DEFECT.replace(
        "        if flag:\n            return None\n", ""))
    assert cli.main([str(tmp_path), "--no-trace"]) == 0


def test_baseline_roundtrip(tmp_path, capsys):
    (tmp_path / "leaky.py").write_text(_DEFECT)
    baseline = str(tmp_path / "graftcheck-baseline.jsonl")

    # adopt: snapshot today's findings, exit 0
    assert cli.main([str(tmp_path), "--no-trace",
                     "--write-baseline", baseline]) == 0
    capsys.readouterr()
    with open(baseline) as fh:
        lines = [json.loads(ln) for ln in fh if ln.strip()]
    assert [ln["rule"] for ln in lines] == ["GC-X601"]

    # known findings are filtered: the gate stays green...
    assert cli.main([str(tmp_path), "--no-trace",
                     "--baseline", baseline]) == 0
    capsys.readouterr()

    # ...but a NEW finding still fails, and only the new one is shown
    (tmp_path / "orphan.py").write_text(_SECOND_DEFECT)
    rc = cli.main([str(tmp_path), "--no-trace",
                   "--baseline", baseline, "--format", "json"])
    out = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
           if ln.strip()]
    assert rc == 1
    assert [f["rule"] for f in out] == ["GC-X603"]


def test_baseline_is_line_insensitive(tmp_path, capsys):
    # shifting the file (new imports above) must not invalidate the
    # baseline: keys are (rule, path, message), not line numbers
    (tmp_path / "leaky.py").write_text(_DEFECT)
    baseline = str(tmp_path / "b.jsonl")
    assert cli.main([str(tmp_path), "--no-trace",
                     "--write-baseline", baseline]) == 0
    (tmp_path / "leaky.py").write_text("import os\nimport sys\n" + _DEFECT)
    assert cli.main([str(tmp_path), "--no-trace",
                     "--baseline", baseline]) == 0
    capsys.readouterr()


def test_make_target_runs_full_pass():
    # the Makefile gate must lint BOTH trees and hard-fail on findings
    # (json format: exit 1 kills make on any finding)
    with open(os.path.join(REPO, "Makefile")) as fh:
        mk = fh.read()
    assert "lint-graft-strict:" in mk
    line = next(ln for ln in mk.splitlines()
                if "sparkflow_tpu.analysis" in ln and "--format json" in ln)
    assert "sparkflow_tpu examples" in line
