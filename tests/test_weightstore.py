"""Live weight publication: versioned store, hot swap, canary gate, chaos.

Covers the PR's acceptance criteria directly: crash-consistent publish
(a torn publish — crash between manifest and rename — is invisible to
readers), checksum-verified loads with automatic fallback past corrupt
versions, rollback quarantine, watcher-driven hot swap that is bitwise
identical to a cold start with zero retraces, the DecodeEngine's deferred
token-boundary swap, the canary health gate (error-rate / NaN / latency)
with store rollback, the Trainer/ElasticParamStore ``publish_to`` hooks,
and the static gates (GC-L301/302/303 lock lint, lock-order graph, GC-R402
lockset race check) over the new code.
"""

import json
import os
import shutil
import threading

import numpy as np
import pytest

import jax

import sparkflow_tpu.nn as nn
from sparkflow_tpu.analysis import lockgraph, locks, racecheck
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.models.registry import build_registry_spec, model_from_json
from sparkflow_tpu.resilience import faults
from sparkflow_tpu.serving import (CanaryController, DecodeEngine,
                                   InferenceEngine, WeightStore,
                                   WeightStoreError, WeightWatcher)
from sparkflow_tpu.serving.membership import Replica
from sparkflow_tpu.trainer import Trainer
from sparkflow_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IN, OUT = "x:0", "out/BiasAdd:0"


def mlp_graph():
    x = nn.placeholder([None, 4], name="x")
    h = nn.dense(x, 3, activation="relu")
    out = nn.dense(h, 2, name="out")
    nn.mean_squared_error(x, out)


@pytest.fixture(scope="module")
def graph_json():
    return build_graph(mlp_graph)


def _mlp_weights(seed):
    rs = np.random.RandomState(seed)
    return [rs.randn(4, 3).astype(np.float32),
            rs.randn(3).astype(np.float32),
            rs.randn(3, 2).astype(np.float32),
            rs.randn(2).astype(np.float32)]


def _mlp_tree(graph_json, seed):
    """The canonical params pytree for the MLP graph — the standard layout
    a trainer publishes (a flat list's leaf order differs from the tree's
    sorted order, so stores feeding engine templates publish trees)."""
    from sparkflow_tpu.graphdef import list_to_params
    from sparkflow_tpu.models import model_from_json
    return list_to_params(model_from_json(graph_json), _mlp_weights(seed))


def _bitwise(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(fa) == len(fb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb))


# -- store: publish / load / verify ------------------------------------------


def test_publish_load_roundtrip(tmp_path):
    store = WeightStore(str(tmp_path))
    w1, w2 = _mlp_weights(0), _mlp_weights(1)
    assert store.publish(w1) == 1
    assert store.publish(w2) == 2
    assert store.all_versions() == [1, 2]
    assert store.latest_version() == 2
    v, got = store.load(like=w2)
    assert v == 2 and _bitwise(got, w2)
    v, got = store.load(version=1, like=w1)
    assert v == 1 and _bitwise(got, w1)
    assert store.verify_version(1) and store.verify_version(2)


def test_empty_store_loads_none(tmp_path):
    store = WeightStore(str(tmp_path))
    assert store.load() is None
    assert store.latest_version() is None


def test_version_regression_raises(tmp_path):
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0), version=5)
    with pytest.raises(WeightStoreError, match="monotone"):
        store.publish(_mlp_weights(1), version=3)
    with pytest.raises(WeightStoreError, match="monotone"):
        store.publish(_mlp_weights(1), version=5)  # republish is not a thing
    assert store.publish(_mlp_weights(1)) == 6  # auto continues past it
    assert store.latest_version() == 6


def test_shape_drift_rejected_at_load(tmp_path):
    # the shapes-unchanged contract: a published tree that drifts in shape
    # must fail the template check, not be discovered as a retrace
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    bad_template = _mlp_weights(0)
    bad_template[0] = np.zeros((5, 3), np.float32)
    with pytest.raises(WeightStoreError, match="shapes must be unchanged"):
        store.load(version=1, like=bad_template)


def test_gc_keeps_newest(tmp_path):
    store = WeightStore(str(tmp_path), keep=2)
    for s in range(4):
        store.publish(_mlp_weights(s))
    assert store.all_versions() == [3, 4]
    assert store.load(like=_mlp_weights(0))[0] == 4


# -- store: chaos battery -----------------------------------------------------


def test_torn_publish_invisible(tmp_path):
    """Crash in the window between manifest write and the atomic rename:
    the pointer stays on the previous version and no reader ever sees a
    half-written v_<n>."""
    store = WeightStore(str(tmp_path))
    w1 = _mlp_weights(0)
    store.publish(w1)
    with faults.inject("weights.publish_commit", fail_calls=[0]):
        with pytest.raises(faults.InjectedFault):
            store.publish(_mlp_weights(1))
    assert store.all_versions() == [1]
    assert store.latest_version() == 1
    v, got = store.load(like=w1)
    assert v == 1 and _bitwise(got, w1)
    # and the next publish proceeds cleanly onto version 2
    assert store.publish(_mlp_weights(2)) == 2


def test_sigkill_tmp_dir_never_read(tmp_path):
    """A SIGKILL mid-publish (no exception handler runs) leaves a _tmp_*
    dir behind; readers never mistake it for a version and the next
    publisher is unaffected."""
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    leftover = os.path.join(str(tmp_path), "_tmp_v2_99999")
    os.makedirs(leftover)
    with open(os.path.join(leftover, "weights.npz"), "wb") as f:
        f.write(b"partial garbage")
    assert store.all_versions() == [1]
    assert store.latest_version() == 1
    assert store.publish(_mlp_weights(1)) == 2


def test_corrupt_weight_file_falls_back(tmp_path):
    """Bit-rot in the newest version's weights: verification fails and the
    default load falls back to the newest verifiable version; an explicit
    load of the corrupt version raises."""
    store = WeightStore(str(tmp_path))
    w1 = _mlp_weights(0)
    store.publish(w1)
    store.publish(_mlp_weights(1))
    faults.corrupt_latest_weights(str(tmp_path), mode="flip")
    assert not store.verify_version(2)
    v, got = store.load(like=w1)
    assert v == 1 and _bitwise(got, w1)
    with pytest.raises(WeightStoreError, match="torn or corrupt"):
        store.load(version=2, like=w1)


def test_truncated_manifest_falls_back(tmp_path):
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    store.publish(_mlp_weights(1))
    faults.corrupt_latest_weights(str(tmp_path), mode="manifest")
    assert not store.verify_version(2)
    assert store.load(like=_mlp_weights(0))[0] == 1


def test_torn_latest_json_pointer_scans_dirs(tmp_path):
    """An unreadable latest.json is only a pointer loss: discovery falls
    back to scanning version dirs and still serves the newest one."""
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    store.publish(_mlp_weights(1))
    faults.corrupt_latest_weights(str(tmp_path), mode="latest_json")
    assert store.latest_version() == 2
    assert store.load(like=_mlp_weights(0))[0] == 2


def test_restart_onto_newest_verifiable(tmp_path):
    """The replica-restart path: a FRESH store handle (new process) over a
    directory whose newest version is corrupt starts on the newest
    verifiable one, skipping the bad version by checksum alone."""
    store = WeightStore(str(tmp_path))
    w2 = _mlp_weights(1)
    store.publish(_mlp_weights(0))
    store.publish(w2)
    store.publish(_mlp_weights(2))
    faults.corrupt_latest_weights(str(tmp_path), mode="flip")  # damages v3
    fresh = WeightStore(str(tmp_path))
    v, got = fresh.load(like=w2)
    assert v == 2 and _bitwise(got, w2)


def test_rollback_quarantines_version(tmp_path):
    store = WeightStore(str(tmp_path))
    w1 = _mlp_weights(0)
    store.publish(w1)
    store.publish(_mlp_weights(1))
    assert store.rollback(bad_version=2) == 1
    assert store.latest_version() == 1
    assert store.quarantined() == {2}
    # v2 is intact on disk but never offered again, even by fallback
    v, got = store.load(like=w1)
    assert v == 1 and _bitwise(got, w1)
    # the next publish moves PAST the quarantined number (monotone)
    assert store.publish(_mlp_weights(2)) == 3
    assert store.load(like=w1)[0] == 3


def test_rollback_with_nothing_good_left(tmp_path):
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    assert store.rollback(bad_version=1) is None
    assert store.latest_version() is None


def test_all_versions_corrupt_raises(tmp_path):
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    faults.corrupt_latest_weights(str(tmp_path), mode="flip")
    with pytest.raises(WeightStoreError, match="no loadable weights"):
        store.load(like=_mlp_weights(0))


# -- InferenceEngine hot swap -------------------------------------------------


def test_engine_swap_parity_and_zero_retrace(graph_json):
    """The swapped engine's predictions are bitwise those of an engine
    cold-started on the new weights, with zero steady-state retraces and
    zero fallback compiles — the AOT executables are reused as-is."""
    w_old, w_new = _mlp_weights(0), _mlp_weights(7)
    eng = InferenceEngine(graph_json, w_old, input_name=IN, output_name=OUT,
                          max_batch=8)
    cold = InferenceEngine(graph_json, w_new, input_name=IN, output_name=OUT,
                           max_batch=8)
    x = np.random.RandomState(3).randn(5, 4).astype(np.float32)
    eng.predict(x)  # old weights serving
    assert eng.swap_params(w_new, version=1) is True
    assert eng.serving_version() == 1
    np.testing.assert_array_equal(np.asarray(eng.predict(x)),
                                  np.asarray(cold.predict(x)))
    st = eng.stats()
    assert st["swaps"] == 1 and st["serving_version"] == 1
    assert st["steady_traces"] == 0 and st["fallback_compiles"] == 0


def test_engine_swap_shape_mismatch_rejected(graph_json):
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    bad = _mlp_weights(1)
    bad[2] = np.zeros((3, 5), np.float32)  # widened output layer
    with pytest.raises(Exception):  # shape validation (engine or loader)
        eng.swap_params(bad)
    assert eng.serving_version() == 0  # still on ctor weights


def test_engine_swap_fault_keeps_last_good(graph_json):
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    with faults.inject("engine.swap", fail_calls=[0]):
        with pytest.raises(faults.InjectedFault):
            eng.swap_params(_mlp_weights(1))
    assert eng.serving_version() == 0
    x = np.zeros((2, 4), np.float32)
    assert np.isfinite(np.asarray(eng.predict(x))).all()


# -- WeightWatcher ------------------------------------------------------------


def test_watcher_swaps_on_publish(graph_json, tmp_path):
    store = WeightStore(str(tmp_path))
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    cold = InferenceEngine(graph_json, _mlp_weights(9), input_name=IN,
                           output_name=OUT, max_batch=4)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.01)
    assert watcher.poll_once() is False  # nothing published yet
    store.publish(_mlp_tree(graph_json, 9))
    assert watcher.poll_once() is True
    assert watcher.serving_version() == 1
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(eng.predict(x)),
                                  np.asarray(cold.predict(x)))
    # idempotent: the same version is not re-pulled
    assert watcher.poll_once() is False
    assert watcher.stats()["swaps"] == 1


def test_watcher_keeps_last_good_on_corrupt_publish(graph_json, tmp_path):
    """A corrupt publish is a counter and a log line on the replica —
    never a serving error. The next good publish swaps normally."""
    store = WeightStore(str(tmp_path))
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.01)
    store.publish(_mlp_tree(graph_json, 1))
    assert watcher.poll_once() is True and eng.serving_version() == 1
    store.publish(_mlp_tree(graph_json, 2))
    faults.corrupt_latest_weights(str(tmp_path), mode="flip")  # damages v2
    assert watcher.poll_once() is False
    st = watcher.stats()
    assert st["pull_failures"] == 1 and st["failed_versions"] == [2]
    assert eng.serving_version() == 1  # last-good kept
    x = np.zeros((2, 4), np.float32)
    assert np.isfinite(np.asarray(eng.predict(x))).all()
    store.publish(_mlp_tree(graph_json, 3))  # v3, good
    assert watcher.poll_once() is True
    assert eng.serving_version() == 3


def test_watcher_follows_rollback_down(graph_json, tmp_path):
    """Rollback is just a pointer move to a LOWER version: watchers follow
    it and replicas revert."""
    store = WeightStore(str(tmp_path))
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.01)
    store.publish(_mlp_tree(graph_json, 1))
    store.publish(_mlp_tree(graph_json, 2))
    assert watcher.poll_once() is True and eng.serving_version() == 2
    store.rollback(bad_version=2)
    assert watcher.poll_once() is True
    assert eng.serving_version() == 1


def test_watcher_swap_fault_retries_next_poll(graph_json, tmp_path):
    store = WeightStore(str(tmp_path))
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.01)
    store.publish(_mlp_tree(graph_json, 1))
    with faults.inject("engine.swap", fail_calls=[0]):
        assert watcher.poll_once() is False
    assert watcher.stats()["swap_failures"] == 1
    assert eng.serving_version() == 0
    # the target stays unclaimed, so the next poll retries and lands it
    assert watcher.poll_once() is True
    assert eng.serving_version() == 1


def test_watcher_background_thread_swaps(graph_json, tmp_path):
    store = WeightStore(str(tmp_path))
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.02).start()
    try:
        store.publish(_mlp_tree(graph_json, 1))
        deadline = 100
        while eng.serving_version() != 1 and deadline:
            deadline -= 1
            threading.Event().wait(0.02)
        assert eng.serving_version() == 1
    finally:
        watcher.stop()
    assert watcher._thread is None


def test_watcher_rejects_non_swappable_engine(tmp_path):
    watcher = WeightWatcher(WeightStore(str(tmp_path)))
    with pytest.raises(TypeError, match="swap_params"):
        watcher.attach(object())


# -- DecodeEngine deferred swap ----------------------------------------------


VOCAB = 31


@pytest.fixture(scope="module")
def lm():
    spec = build_registry_spec("transformer_lm", vocab_size=VOCAB, hidden=16,
                               num_layers=2, num_heads=2, mlp_dim=32,
                               max_len=32, dropout=0.0)
    model = model_from_json(spec)
    params = model.init(jax.random.PRNGKey(0))
    params2 = model.init(jax.random.PRNGKey(1))
    return model, params, params2


def test_decode_swap_waits_for_token_boundary(lm):
    """A swap requested mid-request defers: admissions hold, the active
    request keeps decoding OLD weights to completion, and the swap lands at
    the drained boundary. Post-swap output is bitwise a cold start's on the
    new weights (the prefix cache cannot leak old-version K/V)."""
    model, p1, p2 = lm
    eng = DecodeEngine(model, p1, num_slots=2, page_size=8, seed=0)
    info = eng.prefill([5, 2, 8], max_new_tokens=4, temperature=0.0)
    toks = [info["token"]]
    assert eng.swap_params(p2, version=1) is False  # active slot: deferred
    st = eng.stats()
    assert st["pending_swap"] and st["serving_version"] == 0
    assert eng.can_admit(3, 2) is False  # admissions hold while pending
    while len(toks) < 4:
        toks.extend(eng.step().get(info["slot"], []))
    eng.release(info["slot"])
    assert eng.maybe_swap() is True  # drained: the swap lands
    assert eng.serving_version() == 1
    assert eng.can_admit(3, 2) is True
    # post-swap parity vs a cold engine on the new weights
    cold = DecodeEngine(model, p2, num_slots=2, page_size=8, seed=0)
    out_a = _greedy(eng, [5, 2, 8], 4)
    out_b = _greedy(cold, [5, 2, 8], 4)
    assert out_a == out_b
    assert eng.stats()["steady_traces"] == 0


def _greedy(eng, prompt, n):
    info = eng.prefill(list(prompt), max_new_tokens=n, temperature=0.0)
    toks = [info["token"]]
    while len(toks) < n:
        toks.extend(eng.step().get(info["slot"], []))
    eng.release(info["slot"])
    return toks


def test_decode_swap_immediate_when_idle(lm):
    model, p1, p2 = lm
    eng = DecodeEngine(model, p1, num_slots=2, page_size=8, seed=0)
    assert eng.swap_params(p2, version=3) is True
    assert eng.serving_version() == 3
    assert not eng.stats()["pending_swap"]
    assert _greedy(eng, [1, 2], 3) == _greedy(
        DecodeEngine(model, p2, num_slots=2, page_size=8, seed=0), [1, 2], 3)


def test_decode_watcher_nudges_deferred_swap(lm, tmp_path):
    """poll_once() nudges maybe_swap() first, so a deferred decode swap
    lands on the next poll after the engine drains — without waiting for a
    new admission to trigger it."""
    model, p1, p2 = lm
    store = WeightStore(str(tmp_path))
    eng = DecodeEngine(model, p1, num_slots=2, page_size=8, seed=0)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.01)
    info = eng.prefill([4, 4], max_new_tokens=3, temperature=0.0)
    store.publish(p2)
    # the watcher hands the version off (True); the ENGINE defers it, so
    # the serving version stays 0 until the drained boundary
    assert watcher.poll_once() is True
    assert eng.stats()["pending_swap"] and watcher.serving_version() == 0
    toks = [info["token"]]
    while len(toks) < 3:
        toks.extend(eng.step().get(info["slot"], []))
    eng.release(info["slot"])
    assert watcher.poll_once() is False  # no new version, but the nudge...
    assert eng.serving_version() == 1    # ...applies the pending swap
    assert watcher.serving_version() == 1


# -- canary health gate -------------------------------------------------------


def _feed(ctl, version, n, ok=True, latency_ms=1.0, nan=False):
    for _ in range(n):
        ctl.observe(version, ok=ok, latency_ms=latency_ms, nan=nan)


def test_canary_promotes_healthy_version():
    ctl = CanaryController(min_requests=10)
    _feed(ctl, 1, 20)           # incumbent baseline
    _feed(ctl, 2, 10)           # healthy canary
    st = ctl.stats()
    assert st["incumbent"] == 2 and st["canary"] is None
    assert st["promotions"] == 1 and st["rollbacks"] == 0


def test_canary_error_rate_rollback_repoints_store(tmp_path):
    store = WeightStore(str(tmp_path))
    store.publish(_mlp_weights(0))
    store.publish(_mlp_weights(1))
    ctl = CanaryController(min_requests=10, error_rate_margin=0.05,
                           store=store)
    _feed(ctl, 1, 20)                      # clean incumbent
    _feed(ctl, 2, 7)                       # canary: 3/10 errors
    _feed(ctl, 2, 3, ok=False)
    st = ctl.stats()
    assert st["rollbacks"] == 1 and 2 in st["quarantined"]
    assert st["canary"] is None and st["incumbent"] == 1
    # the gate repointed the store, so every watcher reverts too
    assert store.latest_version() == 1
    assert store.quarantined() == {2}


def test_canary_nan_instant_rollback():
    ctl = CanaryController(min_requests=50)
    _feed(ctl, 1, 5)
    ctl.observe(2, ok=True, latency_ms=1.0, nan=True)
    st = ctl.stats()
    assert st["rollbacks"] == 1 and 2 in st["quarantined"]
    assert st["versions"][2]["requests"] == 1  # well before min_requests


def test_canary_latency_rollback():
    ctl = CanaryController(min_requests=10, latency_factor=2.0,
                           latency_floor_ms=1.0)
    _feed(ctl, 1, 30, latency_ms=2.0)
    _feed(ctl, 2, 10, latency_ms=50.0)  # 25x the incumbent p95
    st = ctl.stats()
    assert st["rollbacks"] == 1 and 2 in st["quarantined"]


def test_canary_quarantined_version_takes_zero_traffic():
    ctl = CanaryController(min_requests=5)
    reps = [Replica("http://h:1", 0), Replica("http://h:2", 1),
            Replica("http://h:3", 2)]
    versions = {0: 1, 1: 1, 2: 2}
    vof = lambda r: versions[r.index]
    _feed(ctl, 1, 10)
    _feed(ctl, 2, 5, ok=False)  # canary fails its gate
    assert 2 in ctl.stats()["quarantined"]
    for _ in range(50):
        picked = ctl.filter_replicas(list(reps), vof)
        assert all(vof(r) == 1 for r in picked)  # v2 replicas never offered
    # observations against a quarantined version are dropped, not counted
    before = ctl.stats()["versions"][2]["requests"]
    ctl.observe(2, ok=True, latency_ms=1.0)
    assert ctl.stats()["versions"][2]["requests"] == before
    # an all-quarantined candidate list yields [] (503 beats bad weights)
    assert ctl.filter_replicas([reps[2]], vof) == []


def test_canary_fraction_splits_preference():
    ctl = CanaryController(min_requests=10 ** 6, canary_fraction=0.5, seed=7)
    reps = [Replica("http://h:1", 0), Replica("http://h:2", 1)]
    versions = {0: 1, 1: 2}
    vof = lambda r: versions[r.index]
    first = {1: 0, 2: 0}
    for _ in range(200):
        first[vof(ctl.filter_replicas(list(reps), vof)[0])] += 1
    # both orders occur; the canary leads roughly canary_fraction of picks
    assert 40 <= first[2] <= 160


def test_canary_gauges_published():
    m = Metrics()
    ctl = CanaryController(min_requests=10, metrics=m)
    _feed(ctl, 1, 5)
    _feed(ctl, 2, 3)
    ctl.publish_gauges()
    g = m.summary()["gauges"]
    assert g["serving/version1/requests"] == 5.0
    assert g["serving/version2/requests"] == 3.0
    assert g["serving/canary/incumbent"] == 1.0
    assert g["serving/canary/version"] == 2.0


# -- trainer / elastic publication -------------------------------------------


def _clf_graph():
    x = nn.placeholder([None, 10], name="x")
    y = nn.placeholder([None, 2], name="y")
    h = nn.dense(x, 8, activation="relu")
    out = nn.dense(h, 2, name="out")
    nn.softmax_cross_entropy(y, out)


@pytest.fixture(scope="module")
def clf_data():
    rs = np.random.RandomState(0)
    X = rs.randn(64, 10).astype(np.float32)
    lbl = (X @ rs.randn(10) > 0).astype(int)
    return X, np.eye(2)[lbl].astype(np.float32)


def test_trainer_publishes_on_cadence(tmp_path, clf_data):
    """publish_every=2 over 4 epochs publishes versions [1, 2] and the
    final published tree is bitwise the fit's result params — what a
    WeightWatcher would hand every serving replica."""
    X, Y = clf_data
    store = WeightStore(str(tmp_path))
    tr = Trainer(build_graph(_clf_graph), "x:0", "y:0", iters=4,
                 mini_batch_size=32, publish_to=store, publish_every=2)
    res = tr.fit(X, Y)
    assert store.all_versions() == [1, 2]
    v, got = store.load(like=res.params)
    assert v == 2 and _bitwise(got, res.params)


def test_trainer_publishes_at_fit_end(tmp_path, clf_data):
    """publish_to without publish_every: one publish of the final weights
    (the fused multi-epoch path included)."""
    X, Y = clf_data
    d = str(tmp_path / "end")
    tr = Trainer(build_graph(_clf_graph), "x:0", "y:0", iters=3,
                 mini_batch_size=32, publish_to=d)
    res = tr.fit(X, Y)
    store = WeightStore(d)
    assert store.all_versions() == [1]
    v, got = store.load(like=res.params)
    assert v == 1 and _bitwise(got, res.params)


def test_elastic_store_publishes_on_accepted_pushes(tmp_path, clf_data):
    """strategy='elastic_dp' threads publish_to/publish_every into the
    ElasticParamStore: every Nth ACCEPTED push lands a verifiable version."""
    X, Y = clf_data
    d = str(tmp_path / "elastic")
    tr = Trainer(build_graph(_clf_graph), "x:0", "y:0", iters=2,
                 mini_batch_size=32, strategy="elastic_dp",
                 elastic={"replicas": 2}, publish_to=d, publish_every=2)
    res = tr.fit(X, Y)
    store = WeightStore(d)
    assert store.all_versions(), "no versions published from elastic fit"
    v, got = store.load(like=res.params)
    assert v == store.latest_version()
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(got))


def test_publish_failure_never_fails_training(tmp_path, clf_data,
                                              monkeypatch):
    X, Y = clf_data
    store = WeightStore(str(tmp_path))

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(store, "publish", boom)
    tr = Trainer(build_graph(_clf_graph), "x:0", "y:0", iters=2,
                 mini_batch_size=32, publish_to=store, publish_every=1)
    res = tr.fit(X, Y)  # must complete despite every publish failing
    assert res.stop_reason == "completed"
    assert np.isfinite(res.losses).all()


# -- static gates -------------------------------------------------------------


@pytest.mark.parametrize("fname", ["weightstore.py", "engine.py",
                                   "router.py"])
def test_lock_lint_clean(fname):
    """GC-L301/302/303: every shared-state write in the weight-publication
    code happens under the owning lock."""
    path = os.path.join(REPO, "sparkflow_tpu", "serving", fname)
    findings = locks.lint_file(path)
    bad = [f for f in findings
           if f.rule in ("GC-L301", "GC-L302", "GC-L303")]
    assert not bad, "\n".join(f"{f.rule}: {f.message}" for f in bad)


def test_lock_graph_sees_weightstore_and_stays_acyclic():
    """The lock-order graph knows the new locks and the whole-package graph
    stays cycle-free — the watcher takes engine locks only via calls made
    OUTSIDE its own lock, so no watcher→engine edge can close a cycle."""
    g = lockgraph.build_graph([os.path.join(REPO, "sparkflow_tpu")])
    known = set(g.node_ctor)
    assert "sparkflow_tpu.serving.weightstore.WeightStore._lock" in known
    assert "sparkflow_tpu.serving.weightstore.WeightWatcher._lock" in known
    assert "sparkflow_tpu.serving.router.CanaryController._lock" in known
    sccs = [c for c in lockgraph._sccs(g.edges) if len(c) > 1]
    assert sccs == [], f"lock-order cycle: {sccs}"
    fs = lockgraph.lint_paths([os.path.join(REPO, "sparkflow_tpu")])
    assert fs == [], "\n" + "\n".join(f.render() for f in fs)


def test_swap_path_race_clean_under_lockset_detector(graph_json, tmp_path):
    """GC-R402: hammer predict + swap_params from concurrent threads with
    the engine's swap-guarded fields instrumented — the double-buffered
    swap discipline holds under the dynamic lockset detector."""
    store = WeightStore(str(tmp_path))
    eng = InferenceEngine(graph_json, _mlp_weights(0), input_name=IN,
                          output_name=OUT, max_batch=4)
    watcher = WeightWatcher(store, [eng], poll_interval_s=0.001)
    x = np.zeros((2, 4), np.float32)
    with racecheck.RaceTracker() as tracker:
        racecheck.instrument_object(
            eng, fields=("_params", "_serving_version", "_swaps"))
        stop = threading.Event()

        def serve():
            while not stop.is_set():
                eng.predict(x)

        def publish_and_poll():
            for s in range(1, 6):
                store.publish(_mlp_tree(graph_json, s))
                watcher.poll_once()

        t = threading.Thread(target=serve)
        t.start()
        try:
            publish_and_poll()
        finally:
            stop.set()
            t.join()
    tracker.assert_clean()
    assert eng.serving_version() == 5
