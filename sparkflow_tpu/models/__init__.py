"""Model zoo: registry models usable anywhere a graph-DSL model is.

Two model spec formats travel through the framework as JSON strings (the wire
format the Estimator's ``tensorflowGraph`` Param carries):

1. graph-DSL specs (``sparkflow-tpu-graph``) built by ``build_graph`` — arbitrary
   user models, executed by :class:`sparkflow_tpu.graphdef.GraphModel`;
2. registry specs (``sparkflow-tpu-model``) naming a model family + config —
   the zoo below, hand-written functional JAX with TPU sharding rules
   (tensor-parallel PartitionSpecs, ring/flash attention).

``model_from_json`` dispatches on the format marker; everything downstream
(Trainer, predict_func, model_loader) is format-agnostic.

Families: ``mlp``, ``cnn``, ``autoencoder`` (graph-DSL preset builders mirroring
the reference examples), ``transformer_classifier`` / ``transformer_lm`` (BERT
-class encoder, flash/ring attention, TP/SP shardings), ``resnet50`` (CIFAR/
ImageNet residual network, stateless norm), ``rnn_classifier`` / ``rnn_lm``
(LSTM/GRU via lax.scan, fused gate matmuls).
"""

from .registry import model_from_json, register_model, build_registry_spec
from . import presets
from .transformer import TransformerClassifier, TransformerLM
from .moe import MoETransformerLM
from .resnet import ResNet
from .rnn import RNNClassifier, RNNLM

__all__ = [
    "model_from_json", "register_model", "build_registry_spec", "presets",
    "TransformerClassifier", "TransformerLM", "MoETransformerLM", "ResNet",
    "RNNClassifier", "RNNLM",
]
