"""Elastic autoscaler: the self-healing fleet contracts.

Four layers, mirroring the policy/transport split:

- **fake-clock policy units** — :func:`policies.scale_decision`'s priority
  order (replace > below-min > up > down > hold), hysteresis band edges,
  per-direction cooldowns, overshoot-proportional step, min/max bounds,
  and the idle-victim preference of :func:`policies.scale_down_order` are
  a pinned decision table;
- **sim-driven dynamics** — the SAME policy inside ``FleetSimulator``: a
  2x load step recovers tail latency with a bounded number of scale-up
  decisions, a chaos kill is replaced, idle trailing load drains the
  zero-inflight victim (byte-identical determinism throughout);
- **live control loop** — :class:`Autoscaler` + :class:`ReplicaManager`
  over fake process handles: crash reaping -> replacement within one
  tick, fault-injected ``autoscaler.spawn`` bounded by ``RetryPolicy``,
  ``autoscaler.drain`` fired on scale-down, ``autoscaler/*`` gauges;
- **real-subprocess e2e** — spawn/drain/crash-replace against actual OS
  processes and signals (stdlib HTTP stubs, no jax import cost).

Plus the static gates: the policy module stays GC-S501-pure and the new
transport modules stay GC-L30x lock-clean.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from sparkflow_tpu.analysis import locks, policy_lint
from sparkflow_tpu.resilience import faults
from sparkflow_tpu.serving import coldstart
from sparkflow_tpu.resilience.retry import RetryExhausted, RetryPolicy
from sparkflow_tpu.serving import policies
from sparkflow_tpu.serving.autoscaler import Autoscaler, ReplicaManager
from sparkflow_tpu.serving.membership import Membership
from sparkflow_tpu.serving.policies import (AutoscalerState, ReplicaView,
                                            ScaleTargets, scale_decision,
                                            scale_down_order)
from sparkflow_tpu.sim import (CostModel, FleetSimulator, ReplicaSpec,
                               SimAutoscaler, synthetic_trace)
from sparkflow_tpu.utils.metrics import Metrics


def view(i, **kw):
    return ReplicaView(index=i, **kw)


def healthy_fleet(n, **kw):
    kw.setdefault("decode_free_slots", 4)
    kw.setdefault("decode_pages_free", 100)
    return [view(i, **kw) for i in range(n)]


T = ScaleTargets(min_replicas=1, max_replicas=8, queue_wait_high_ms=200.0,
                 queue_wait_low_ms=50.0, up_cooldown_s=10.0,
                 down_cooldown_s=60.0, max_step_up=2)
S0 = AutoscalerState(desired=2)


# -- policy units (fake clock) ----------------------------------------------


def test_replace_beats_everything_and_bypasses_cooldowns():
    views = healthy_fleet(3)
    views[1] = view(1, healthy=False, probe_misses=T.dead_after_misses)
    # heavily overloaded AND inside both cooldowns: replacement still wins
    st = AutoscalerState(desired=3, last_up_t=99.0, last_down_t=99.0)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=999.0)
    assert act.kind == policies.SCALE_REPLACE
    assert act.targets == (1,) and act.count == 1
    assert act.state == st  # replacement is not growth: state untouched


def test_replace_applies_even_at_max_replicas():
    t = ScaleTargets(min_replicas=1, max_replicas=3)
    views = healthy_fleet(3)
    views[0] = view(0, healthy=False, probe_misses=t.dead_after_misses)
    act = scale_decision(views, t, S0, now=0.0)
    assert act.kind == policies.SCALE_REPLACE and act.targets == (0,)


def test_single_probe_miss_is_debounced_not_dead():
    # one failed probe = most likely a saturated replica, not a dead one:
    # it leaves rotation but is NOT replaced (killing it would amplify
    # the very overload that slowed the probe)
    views = healthy_fleet(3)
    views[1] = view(1, healthy=False, probe_misses=1)
    st = AutoscalerState(desired=3, last_up_t=99.0, last_down_t=99.0)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=100.0)
    assert act.kind == policies.SCALE_HOLD
    # the suspect still counts as presumed capacity: no below-min spawn
    t = ScaleTargets(min_replicas=3, max_replicas=8)
    act = scale_decision(views, t, st, now=100.0, queue_wait_p95_ms=100.0)
    assert act.kind == policies.SCALE_HOLD
    # threshold crossed: now it is a death and replacement fires
    views[1] = view(1, healthy=False, probe_misses=T.dead_after_misses)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=100.0)
    assert act.kind == policies.SCALE_REPLACE and act.targets == (1,)


def test_unmanaged_replica_is_never_killed_or_deregistered():
    # an unmanaged (founding-fleet) replica past the death threshold is
    # presumed gone but never a replace target — there is no process to
    # respawn; the below-min rule refills the fleet AROUND it, and the
    # record re-admits if its probe recovers
    views = healthy_fleet(3)
    views[0] = view(0, healthy=False, probe_misses=99, managed=False)
    st = AutoscalerState(desired=3, last_up_t=99.0, last_down_t=99.0)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=100.0)
    assert act.kind != policies.SCALE_REPLACE
    t = ScaleTargets(min_replicas=3, max_replicas=8)
    act = scale_decision(views, t, st, now=100.0, queue_wait_p95_ms=100.0)
    assert act.kind == policies.SCALE_UP and act.count == 1
    assert "below min_replicas" in act.reason


def test_scale_down_victim_is_managed_only():
    # the idle unmanaged replica would top scale_down_order, but electing
    # it would burn the down-cooldown on an inapplicable drain: the
    # victim must be the best MANAGED candidate
    views = [view(0, managed=False, decode_free_slots=4,
                  decode_pages_free=100),
             view(1, inflight=2, decode_free_slots=4,
                  decode_pages_free=100)]
    st = AutoscalerState(desired=2)
    act = scale_decision(views, T, st, now=1000.0, queue_wait_p95_ms=1.0)
    assert act.kind == policies.SCALE_DOWN and act.targets == (1,)
    # an all-unmanaged fleet above min holds instead of deciding a no-op
    views = [view(0, managed=False), view(1, managed=False)]
    act = scale_decision(views, T, st, now=1000.0, queue_wait_p95_ms=1.0)
    assert act.kind == policies.SCALE_HOLD


def test_below_min_scales_up_without_cooldown():
    t = ScaleTargets(min_replicas=3, max_replicas=8, up_cooldown_s=10.0)
    st = AutoscalerState(desired=3, last_up_t=99.5)  # mid up-cooldown
    act = scale_decision(healthy_fleet(1), t, st, now=100.0)
    assert act.kind == policies.SCALE_UP and act.count == 2
    assert act.state.desired == 3 and act.state.last_up_t == 100.0


def test_up_requires_high_band_and_respects_cooldown():
    views = healthy_fleet(2)
    # inside the band: hold
    act = scale_decision(views, T, S0, now=100.0, queue_wait_p95_ms=100.0)
    assert act.kind == policies.SCALE_HOLD
    # above the band but still cooling down from the last up: hold
    st = AutoscalerState(desired=2, last_up_t=95.0)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=300.0)
    assert act.kind == policies.SCALE_HOLD and "cooldown" in act.reason
    # cooldown expired: up
    act = scale_decision(views, T, st, now=106.0, queue_wait_p95_ms=300.0)
    assert act.kind == policies.SCALE_UP and act.count == 1
    assert act.state.last_up_t == 106.0 and act.state.desired == 3


def test_up_step_proportional_to_overshoot_and_capped():
    views = healthy_fleet(2)
    # 2.5x the band edge = one extra band-width of overshoot -> step 2
    act = scale_decision(views, T, S0, now=100.0, queue_wait_p95_ms=500.0)
    assert act.kind == policies.SCALE_UP and act.count == 2
    # absurd overshoot is still capped by max_step_up
    act = scale_decision(views, T, S0, now=100.0, queue_wait_p95_ms=9000.0)
    assert act.count == T.max_step_up
    # and by max_replicas
    t = ScaleTargets(max_replicas=3, max_step_up=4)
    act = scale_decision(views, t, S0, now=100.0, queue_wait_p95_ms=9000.0)
    assert act.count == 1
    # at max: hold, however overloaded
    t2 = ScaleTargets(max_replicas=2)
    act = scale_decision(views, t2, S0, now=100.0, queue_wait_p95_ms=9000.0)
    assert act.kind == policies.SCALE_HOLD


def test_starvation_scales_up_without_wait_signal():
    # an empty histogram (wait=None) must not mask page exhaustion
    views = [view(0, decode_free_slots=0, decode_pages_free=0),
             view(1, decode_free_slots=0, decode_pages_free=50)]
    act = scale_decision(views, T, S0, now=100.0, queue_wait_p95_ms=None)
    assert act.kind == policies.SCALE_UP and "starved" in act.reason


def test_down_gated_on_both_direction_cooldowns_and_min_floor():
    views = healthy_fleet(3)
    # below the low band, but a recent UP also blocks the down path —
    # shrinking right after growing is the oscillation the band prevents
    st = AutoscalerState(desired=3, last_up_t=90.0, last_down_t=0.0)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=10.0)
    assert act.kind == policies.SCALE_HOLD and "down-cooldown" in act.reason
    # both cooldowns expired: down by exactly one
    st = AutoscalerState(desired=3, last_up_t=0.0, last_down_t=0.0)
    act = scale_decision(views, T, st, now=100.0, queue_wait_p95_ms=10.0)
    assert act.kind == policies.SCALE_DOWN and act.count == 1
    assert act.state.desired == 2 and act.state.last_down_t == 100.0
    # at the floor: hold forever, however idle
    t = ScaleTargets(min_replicas=3)
    act = scale_decision(views, t, st, now=100.0, queue_wait_p95_ms=0.0)
    assert act.kind == policies.SCALE_HOLD


def test_idle_fleet_with_no_signal_scales_down():
    # wait=None (no samples yet) counts as idle for the down path
    st = AutoscalerState(desired=2)
    act = scale_decision(healthy_fleet(2), T, st, now=1000.0,
                         queue_wait_p95_ms=None)
    assert act.kind == policies.SCALE_DOWN


def test_scale_down_order_prefers_idle_then_highest_index():
    views = [view(0, inflight=0, queue_depth=0),
             view(1, inflight=3, queue_depth=1),
             view(2, inflight=0, queue_depth=2),
             view(3, inflight=0, queue_depth=0)]
    order = scale_down_order(views)
    # zero-inflight zero-queue first; ties break to the HIGHEST index
    # (latest addition leaves first); the busy replica drains last
    assert order == [3, 0, 2, 1]
    act = scale_decision(views, T, AutoscalerState(desired=4), now=1000.0,
                         queue_wait_p95_ms=1.0)
    assert act.kind == policies.SCALE_DOWN and act.targets == (3,)


def test_scale_policy_is_pure_s501():
    findings = policy_lint.lint_file(policies.__file__)
    assert findings == [], "\n".join(f"{f.rule}: {f.message}"
                                     for f in findings)


# -- sim-driven dynamics ----------------------------------------------------


def sim_fleet(n, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("pages_total", 512)
    return [ReplicaSpec(**kw) for _ in range(n)]


def run_autoscaled(tr, n=6, autoscaler=None, **kw):
    kw.setdefault("mode", "generate")
    kw.setdefault("seed", 0)
    return FleetSimulator(sim_fleet(n), tr, CostModel.from_bench_notes(),
                         autoscaler=autoscaler, **kw).run()


def test_sim_step_response_recovers_with_bounded_decisions():
    tr = synthetic_trace(1200, seed=11, rate_rps=60.0, burst_factor=4.0,
                         session_fraction=0.0)
    asc = SimAutoscaler(
        targets=ScaleTargets(min_replicas=1, max_replicas=6,
                             queue_wait_high_ms=150.0,
                             queue_wait_low_ms=30.0,
                             up_cooldown_s=1.0, down_cooldown_s=8.0,
                             max_step_up=2),
        initial=1, decide_interval_s=0.5, spawn_delay_s=0.5)
    small = run_autoscaled(tr, n=6,
                           autoscaler=SimAutoscaler(
                               targets=ScaleTargets(min_replicas=1,
                                                    max_replicas=1),
                               initial=1, decide_interval_s=0.5))
    scaled = run_autoscaled(tr, n=6, autoscaler=asc)
    assert scaled.completed + scaled.rejected == 1200
    # capacity actually arrived...
    assert scaled.scale_ups >= 1
    assert scaled.final_fleet_size > 1
    # ...in a bounded number of decisions (not thrash): never more
    # decisions than it takes to walk min -> max in max_step_up strides
    assert scaled.scale_ups <= 10
    # and the tail is measurably better than the pinned-1 fleet's
    assert scaled.latency_p95_ms < 0.7 * small.latency_p95_ms


def test_sim_autoscaler_is_deterministic():
    tr = synthetic_trace(400, seed=5, rate_rps=40.0, session_fraction=0.0)
    asc = SimAutoscaler(targets=ScaleTargets(min_replicas=1, max_replicas=4,
                                             up_cooldown_s=1.0,
                                             down_cooldown_s=5.0),
                        initial=1, decide_interval_s=0.5)
    a = run_autoscaled(tr, n=4, autoscaler=asc)
    b = run_autoscaled(tr, n=4, autoscaler=asc)
    assert a.digest == b.digest
    assert (a.scale_ups, a.scale_downs, a.replacements) == \
        (b.scale_ups, b.scale_downs, b.replacements)


def test_sim_chaos_kill_is_replaced():
    tr = synthetic_trace(800, seed=7, rate_rps=60.0, session_fraction=0.0)
    span = tr[-1].arrival_s
    asc = SimAutoscaler(targets=ScaleTargets(min_replicas=2, max_replicas=4,
                                             up_cooldown_s=1.0,
                                             down_cooldown_s=30.0),
                        initial=2, decide_interval_s=0.5,
                        spawn_delay_s=0.5)
    rep = run_autoscaled(tr, n=4, autoscaler=asc,
                         chaos=[(span * 0.4, 0, "down")],
                         record_events=True)
    assert rep.replacements >= 1
    assert rep.completed + rep.rejected == 800
    ev = "\n".join(rep.events)
    assert "scale replace r0" in ev and "spawned r" in ev


def test_sim_scale_down_drains_idle_victim():
    # load that ends early, then a long idle tail: the fleet must shrink
    # back toward min and the drained replica must finish its work first
    tr = synthetic_trace(300, seed=9, rate_rps=80.0, session_fraction=0.0)
    asc = SimAutoscaler(targets=ScaleTargets(min_replicas=1, max_replicas=4,
                                             queue_wait_high_ms=100.0,
                                             queue_wait_low_ms=40.0,
                                             up_cooldown_s=0.5,
                                             down_cooldown_s=2.0),
                        initial=3, decide_interval_s=0.5)
    rep = run_autoscaled(tr, n=4, autoscaler=asc, record_events=True)
    assert rep.scale_downs >= 1
    assert rep.completed + rep.rejected == 300
    ev = "\n".join(rep.events)
    assert "scale_down_complete" in ev
    # nothing was lost to a drain: every request completed or was an
    # admission-path reject, never a mid-flight kill from scale-down
    assert rep.completed == 300 - rep.rejected


def test_sim_below_min_does_not_reorder_pending_spawns():
    # initial < min with a spawn delay spanning several decide intervals:
    # the deficit must be ordered ONCE (booting spares count as live
    # capacity), not re-ordered every tick until the spawns land
    tr = synthetic_trace(100, seed=3, rate_rps=20.0, session_fraction=0.0)
    asc = SimAutoscaler(targets=ScaleTargets(min_replicas=3,
                                             max_replicas=6),
                        initial=1, decide_interval_s=0.5,
                        spawn_delay_s=3.0)
    rep = run_autoscaled(tr, n=6, autoscaler=asc)
    assert rep.scale_ups == 1
    assert rep.final_fleet_size == 3


# -- membership elasticity --------------------------------------------------


def test_register_assigns_never_recycled_index():
    mem = Membership(["http://127.0.0.1:1", "http://127.0.0.1:2"],
                     metrics=Metrics())
    r2 = mem.register("http://127.0.0.1:3")
    assert r2.index == 2
    mem.deregister(r2)
    r3 = mem.register("http://127.0.0.1:4")
    assert r3.index == 3  # identity not reused even after deregister
    assert [r.index for r in mem.replicas] == [0, 1, 3]


def test_deregister_drops_gauges_and_rotation():
    metrics = Metrics()
    mem = Membership(["http://127.0.0.1:1", "http://127.0.0.1:2"],
                     metrics=metrics)
    for r in mem.replicas:
        r.healthy = True
    mem.publish_gauges()
    assert any(k.startswith("router/replica0/") for k in metrics.gauges())
    victim = mem.replicas[0]
    mem.deregister(victim)
    # the ghost's gauges are gone, the survivor's stay
    assert not any(k.startswith("router/replica0/")
                   for k in metrics.gauges())
    assert any(k.startswith("router/replica1/") for k in metrics.gauges())
    # and it can never be picked again
    for _ in range(8):
        assert mem.pick() is not victim
    # idempotent: a second deregister is a no-op
    mem.deregister(victim)
    assert len(mem.replicas) == 1


def test_views_matches_view_of():
    mem = Membership(["http://127.0.0.1:1"], metrics=Metrics())
    mem.replicas[0].healthy = True
    (v,) = mem.views(now=0.0)
    assert v == mem.view_of(mem.replicas[0], 0.0)


def test_probe_misses_accumulate_and_reset_on_recovery():
    mem = Membership(["http://127.0.0.1:1"], metrics=Metrics())
    r = mem.replicas[0]
    mem.probe_all()        # nothing listens on the port: miss
    mem.probe_all()
    assert not r.healthy and r.probe_misses == 2
    (v,) = mem.views(now=0.0)
    assert v.probe_misses == 2 and not v.healthy
    # a green probe re-admits AND clears the miss streak
    r.probe_client.healthz = (
        lambda timeout_s=None: {"status": "ok", "queue_depth": 0})
    mem.probe_all()
    assert r.healthy and r.probe_misses == 0


# -- live control loop (fake processes) -------------------------------------


class FakeProc:
    """Popen-shaped handle the manager can terminate/kill/reap."""

    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        self.rc = 0

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        if self.rc is None:
            raise RuntimeError("still running")
        return self.rc


class FakeFleet:
    """A Membership + no-health-wait ReplicaManager over FakeProcs."""

    def __init__(self, **rm_kw):
        self.metrics = Metrics()
        self.membership = Membership(["http://127.0.0.1:1"],
                                     metrics=self.metrics)
        self.membership.deregister(self.membership.replicas[0])
        self.ports = iter(range(9100, 9200))
        self.procs = {}

        fleet = self

        class _RM(ReplicaManager):
            def _wait_healthy(self, url, proc):
                return  # fake servers are born healthy

        def launcher(port):
            p = FakeProc()
            fleet.procs[port] = p
            return p

        rm_kw.setdefault("retry", RetryPolicy(max_attempts=3, base_s=0.0,
                                              jitter=0.0))
        self.manager = _RM(launcher, membership=self.membership,
                           port_factory=lambda: next(self.ports), **rm_kw)

    def mark_all_healthy(self):
        for r in self.membership.replicas:
            r.healthy = True
            r.probe_misses = 0

    def proc_of(self, replica):
        return self.manager._managed[replica.index].proc


def make_autoscaler(fleet, wait_box, **targets_kw):
    targets_kw.setdefault("min_replicas", 2)
    targets_kw.setdefault("max_replicas", 4)
    targets_kw.setdefault("up_cooldown_s", 0.0)
    targets_kw.setdefault("down_cooldown_s", 0.0)
    return Autoscaler(fleet.membership, fleet.manager,
                      targets=ScaleTargets(**targets_kw),
                      metrics=fleet.metrics,
                      queue_wait_signal=lambda: wait_box[0])


def test_autoscaler_full_lifecycle_and_gauges():
    fleet = FakeFleet()
    wait = [None]
    a = make_autoscaler(fleet, wait)

    # below min: spawn up to the floor without any signal
    act = a.tick()
    assert act.kind == policies.SCALE_UP
    assert len(fleet.membership.replicas) == 2
    fleet.mark_all_healthy()

    # overload: grow
    wait[0] = 900.0
    act = a.tick()
    assert act.kind == policies.SCALE_UP
    assert len(fleet.membership.replicas) == 4
    fleet.mark_all_healthy()

    # crash: reaped and replaced within ONE tick, not a probe cycle
    victim = fleet.manager.managed()[0]
    fleet.proc_of(victim).rc = -9
    wait[0] = 100.0
    act = a.tick()
    assert act.kind == policies.SCALE_REPLACE
    assert a.replacements == 1
    assert len(fleet.membership.replicas) == 4
    assert victim.index not in {r.index for r in fleet.membership.replicas}
    fleet.mark_all_healthy()

    # idle: shrink by one, draining (SIGTERM path) the victim
    wait[0] = 1.0
    act = a.tick()
    assert act.kind == policies.SCALE_DOWN
    assert a.drains == 1
    assert len(fleet.membership.replicas) == 3

    g = fleet.metrics.gauges()
    assert g["autoscaler/replicas"] == 3.0
    assert g["autoscaler/target"] == 3.0
    assert g["autoscaler/spawns"] == 5.0
    assert g["autoscaler/drains"] == 1.0
    assert g["autoscaler/replacements"] == 1.0
    assert g["autoscaler/last_decision"] == 2.0  # down


def test_spawn_fault_is_retry_bounded():
    fleet = FakeFleet()
    # first attempt fails, retry succeeds: the fleet still comes up
    with faults.inject("autoscaler.spawn", fail_calls=[0]) as spec:
        replica = fleet.manager.spawn()
    assert spec.calls == 2 and spec.failures == 1
    assert replica in fleet.membership.replicas
    # every attempt fails: bounded exhaustion, not a hang
    with faults.inject("autoscaler.spawn", fail_calls=[0, 1, 2]):
        with pytest.raises(RetryExhausted):
            fleet.manager.spawn()
    # the failed spawn registered nothing
    assert len(fleet.membership.replicas) == 1


def test_spawn_failure_retried_next_tick():
    fleet = FakeFleet()
    wait = [None]
    a = make_autoscaler(fleet, wait, min_replicas=1)
    with faults.inject("autoscaler.spawn", fail_calls=[0, 1, 2]):
        a.tick()  # below-min spawn exhausts its retries
    assert a.spawn_failures == 1
    assert len(fleet.membership.replicas) == 0
    a.tick()  # faults gone: the next tick converges to min
    assert len(fleet.membership.replicas) == 1


def test_drain_fires_fault_point_and_reaps_clean_exit():
    fleet = FakeFleet()
    r = fleet.manager.spawn()
    fleet.mark_all_healthy()
    with faults.inject("autoscaler.drain", fail_calls=[]) as spec:
        fleet.manager.drain(r)
    assert spec.calls == 1
    assert fleet.membership.replicas == []
    assert fleet.manager.managed_count == 0
    # a drained process got SIGTERM, not SIGKILL
    assert next(iter(fleet.procs.values())).terminated


def test_reap_reports_exits_without_acting():
    fleet = FakeFleet()
    a_r = fleet.manager.spawn()
    b_r = fleet.manager.spawn()
    fleet.proc_of(b_r).rc = 1
    dead = fleet.manager.reap()
    assert [(r.index, rc) for r, rc in dead] == [(b_r.index, 1)]
    # reap is an observation: the record stays managed for the tick loop
    assert fleet.manager.owns(b_r) and fleet.manager.owns(a_r)


# -- real-subprocess e2e ----------------------------------------------------


_REPLICA_STUB = textwrap.dedent("""\
    import json, os, signal, sys
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class H(BaseHTTPRequestHandler):
        def _reply(self):
            body = json.dumps({"status": "ok", "queue_depth": 0}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        do_GET = do_POST = _reply
        def log_message(self, *a):
            pass

    signal.signal(signal.SIGTERM, lambda *a: os._exit(0))
    srv = ThreadingHTTPServer(("127.0.0.1", int(sys.argv[1])), H)
    srv.serve_forever()
""")


def stub_launcher(port):
    return subprocess.Popen([sys.executable, "-c", _REPLICA_STUB, str(port)])


@pytest.fixture
def live_fleet():
    metrics = Metrics()
    mem = Membership(["http://127.0.0.1:1"], metrics=metrics,
                     probe_interval_s=0.1)
    mem.deregister(mem.replicas[0])
    rm = ReplicaManager(stub_launcher, membership=mem,
                        retry=RetryPolicy(max_attempts=2, base_s=0.1),
                        health_timeout_s=20.0, drain_timeout_s=5.0,
                        poll_interval_s=0.05)
    try:
        yield mem, rm, metrics
    finally:
        rm.stop_all(kill=True)
        mem.stop()


def test_subprocess_spawn_drain_and_crash_replace(live_fleet):
    mem, rm, metrics = live_fleet
    wait = [None]
    a = Autoscaler(mem, rm,
                   targets=ScaleTargets(min_replicas=2, max_replicas=3,
                                        up_cooldown_s=0.0,
                                        down_cooldown_s=0.0),
                   metrics=metrics, queue_wait_signal=lambda: wait[0])

    # spawn to the floor: two real processes, both probed healthy
    act = a.tick()
    assert act.kind == policies.SCALE_UP
    assert len(mem.replicas) == 2
    assert all(r.healthy for r in mem.replicas)

    # SIGKILL one replica out from under the fleet: one tick reaps the
    # exit code and a real replacement process comes up healthy
    victim = rm.managed()[0]
    victim_proc = rm._managed[victim.index].proc
    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10.0)
    act = a.tick()
    assert act.kind == policies.SCALE_REPLACE
    assert a.replacements == 1
    assert len(mem.replicas) == 2
    assert victim.index not in {r.index for r in mem.replicas}
    assert all(r.healthy for r in mem.replicas)
    # the dead replica's gauges went with it
    mem.publish_gauges()
    assert not any(k.startswith(f"router/replica{victim.index}/")
                   for k in metrics.gauges())

    # scale down: SIGTERM drain, process really exits (its handler does a
    # clean exit 0), record and membership row both gone
    survivor = rm.managed()[0]
    survivor_proc = rm._managed[survivor.index].proc
    rm.drain(survivor)
    assert rm.managed_count == 1
    assert len(mem.replicas) == 1
    assert survivor_proc.poll() == 0


def test_subprocess_spawn_survives_first_port_failure(live_fleet):
    mem, rm, _ = live_fleet
    with faults.inject("autoscaler.spawn", fail_calls=[0]) as spec:
        replica = rm.spawn()
    assert spec.calls == 2
    assert replica.healthy


# -- cold-start store: shared-manifest locking -------------------------------


def test_coldstart_manifest_lock_lifecycle(tmp_path):
    store = coldstart.ExecutableStore(str(tmp_path))
    with store._manifest_lock():
        assert os.path.exists(store._lock_path)
    assert not os.path.exists(store._lock_path)
    # a lock left by a crashed writer is broken, not waited out forever
    with open(store._lock_path, "w") as fh:
        fh.write("0")
    old = time.time() - 120.0
    os.utime(store._lock_path, (old, old))
    with store._manifest_lock():
        assert os.path.exists(store._lock_path)
    assert not os.path.exists(store._lock_path)


def test_coldstart_save_runs_manifest_rmw_under_lock(tmp_path, monkeypatch):
    # scale-smoke boots several replicas against one shared store: the
    # manifest read-modify-write must hold the lock, or concurrent
    # first-boots silently drop each other's entries (last writer wins)
    monkeypatch.setattr(
        coldstart, "_serialize_api",
        lambda: (lambda compiled: (compiled, None, None), None))
    store = coldstart.ExecutableStore(str(tmp_path))
    monkeypatch.setattr(store, "_fingerprint", lambda: "test-env")
    locked_during_write = []
    real_write = store._write_manifest

    def spying_write(manifest):
        locked_during_write.append(os.path.exists(store._lock_path))
        real_write(manifest)

    monkeypatch.setattr(store, "_write_manifest", spying_write)
    assert store.save("a", b"payload-a")
    assert store.save("b", b"payload-b")
    assert locked_during_write == [True, True]
    assert store.keys() == ["a", "b"]


# -- static gates -----------------------------------------------------------


SERVING_DIR = os.path.join(os.path.dirname(__file__), os.pardir,
                           "sparkflow_tpu", "serving")


@pytest.mark.parametrize("fname", ["autoscaler.py", "coldstart.py",
                                   "membership.py"])
def test_lock_lint_clean(fname):
    findings = locks.lint_file(os.path.join(SERVING_DIR, fname))
    bad = [f for f in findings
           if f.rule in ("GC-L301", "GC-L302", "GC-L303")]
    assert not bad, "\n".join(f"{f.rule}: {f.message}" for f in bad)
