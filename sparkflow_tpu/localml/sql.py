"""Row / RDD / DataFrame / session: the ``pyspark.sql`` subset sparkflow touches.

The reference drives training through ``df.rdd.map``, ``coalesce``,
``foreachPartition`` and inference through ``rdd.mapPartitions(...).toDF()``
(``sparkflow/tensorflow_async.py:90-99,290-291``; ``HogwildSparkModel.py:259``).
This local engine keeps those exact call shapes over in-process lists, with
logical partitions standing in for Spark executors — the multi-device mesh is
the real parallelism substrate underneath.
"""

from __future__ import annotations

import csv as _csv
import random as _random
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np


class Row:
    """Named-field record, pyspark-Row-compatible (attr + item access, asDict)."""

    __slots__ = ("__fields__", "__values__")

    def __init__(self, **kwargs):
        object.__setattr__(self, "__fields__", list(kwargs.keys()))
        object.__setattr__(self, "__values__", list(kwargs.values()))

    def asDict(self) -> Dict[str, Any]:
        return dict(zip(self.__fields__, self.__values__))

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.__values__[key]
        try:
            return self.__values__[self.__fields__.index(key)]
        except ValueError:
            raise KeyError(key)

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            return self.__values__[self.__fields__.index(name)]
        except ValueError:
            raise AttributeError(name)

    def __contains__(self, key):
        return key in self.__fields__

    def __len__(self):
        return len(self.__values__)

    def __iter__(self):
        return iter(self.__values__)

    def __eq__(self, other):
        return isinstance(other, Row) and self.asDict() == other.asDict()

    def __repr__(self):
        kv = ", ".join(f"{f}={v!r}" for f, v in zip(self.__fields__, self.__values__))
        return f"Row({kv})"


def _slice(items: List[Any], n: int) -> List[List[Any]]:
    n = max(1, min(n, len(items)) if items else 1)
    base, extra = divmod(len(items), n)
    out, i = [], 0
    for k in range(n):
        size = base + (1 if k < extra else 0)
        out.append(items[i:i + size])
        i += size
    return out


class RDD:
    """A list with logical partitions; mirrors the RDD methods sparkflow uses."""

    def __init__(self, items: List[Any], num_partitions: int = 1):
        self.items = list(items)
        self.num_partitions = max(1, num_partitions)

    # -- transforms ---------------------------------------------------------

    def map(self, f: Callable) -> "RDD":
        return RDD([f(x) for x in self.items], self.num_partitions)

    def mapPartitions(self, f: Callable) -> "RDD":
        out: List[Any] = []
        for part in _slice(self.items, self.num_partitions):
            out.extend(f(iter(part)))
        return RDD(out, self.num_partitions)

    def foreachPartition(self, f: Callable) -> None:
        for part in _slice(self.items, self.num_partitions):
            f(iter(part))

    def coalesce(self, n: int) -> "RDD":
        return RDD(self.items, min(self.num_partitions, max(1, n)))

    def persist(self, *_a) -> "RDD":
        return self  # local lists are always materialized

    def unpersist(self, *_a) -> "RDD":
        return self

    def repartition(self, n: int) -> "RDD":
        items = list(self.items)
        _random.Random(17).shuffle(items)
        return RDD(items, max(1, n))

    # -- actions ------------------------------------------------------------

    def collect(self) -> List[Any]:
        return list(self.items)

    def toLocalIterator(self) -> Iterator[Any]:
        """Partition-by-partition generator (pyspark's streaming action: the
        driver holds one partition at a time, never the whole dataset)."""
        for part in _slice(self.items, self.num_partitions):
            for x in part:
                yield x

    def count(self) -> int:
        return len(self.items)

    def getNumPartitions(self) -> int:
        return self.num_partitions

    def toDF(self, schema: Optional[Sequence[str]] = None) -> "DataFrame":
        if not self.items:
            return DataFrame([], list(schema) if schema else [])
        rows = [x if isinstance(x, Row) else Row(**x) if isinstance(x, dict)
                else Row(**{c: v for c, v in zip(schema, x)}) for x in self.items]
        return DataFrame(rows, rows[0].__fields__, self.num_partitions)


class _RandOrder:
    """Sentinel returned by functions.rand() for orderBy-shuffles."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = seed


class functions:
    @staticmethod
    def rand(seed: Optional[int] = None) -> _RandOrder:
        return _RandOrder(seed)


class DataFrame:
    """Immutable list-of-Rows table with logical partitions."""

    def __init__(self, rows: List[Row], columns: List[str], num_partitions: int = 4):
        self._rows = rows
        self.columns = list(columns)
        self.num_partitions = max(1, num_partitions)

    @property
    def rdd(self) -> RDD:
        return RDD(self._rows, self.num_partitions)

    def select(self, *cols) -> "DataFrame":
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        rows = [Row(**{c: r[c] for c in cols}) for r in self._rows]
        return DataFrame(rows, list(cols), self.num_partitions)

    def withColumn(self, name: str, values: Sequence[Any]) -> "DataFrame":
        """localml extension: attach a computed column (no Column expressions)."""
        rows = [Row(**{**r.asDict(), name: v}) for r, v in zip(self._rows, values)]
        cols = self.columns + ([name] if name not in self.columns else [])
        return DataFrame(rows, cols, self.num_partitions)

    def orderBy(self, *exprs) -> "DataFrame":
        rows = list(self._rows)
        if exprs and isinstance(exprs[0], _RandOrder):
            _random.Random(exprs[0].seed).shuffle(rows)
        elif exprs:
            rows.sort(key=lambda r: tuple(r[c] for c in exprs))
        return DataFrame(rows, self.columns, self.num_partitions)

    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(self._rows, self.columns, max(1, n))

    def coalesce(self, n: int) -> "DataFrame":
        return DataFrame(self._rows, self.columns,
                         min(self.num_partitions, max(1, n)))

    def collect(self) -> List[Row]:
        return list(self._rows)

    def take(self, n: int) -> List[Row]:
        return self._rows[:n]

    def first(self) -> Optional[Row]:
        return self._rows[0] if self._rows else None

    def count(self) -> int:
        return len(self._rows)

    def show(self, n: int = 20) -> None:
        print(" | ".join(self.columns))
        for r in self._rows[:n]:
            print(" | ".join(str(r[c]) for c in self.columns))

    def __repr__(self):
        return f"DataFrame[{', '.join(self.columns)}] ({len(self._rows)} rows)"


class _CsvReader:
    def __init__(self, session):
        self._session = session
        self._options: Dict[str, Any] = {}

    def option(self, key: str, value) -> "_CsvReader":
        self._options[str(key).lower()] = value
        return self

    def csv(self, path: str) -> DataFrame:
        infer = str(self._options.get("inferschema", "false")).lower() == "true"
        header = str(self._options.get("header", "false")).lower() == "true"
        rows: List[Row] = []
        with open(path, newline="") as f:
            reader = _csv.reader(f)
            cols: Optional[List[str]] = None
            for rec in reader:
                if cols is None:
                    cols = rec if header else [f"_c{i}" for i in range(len(rec))]
                    if header:
                        continue
                vals = [_parse(v) if infer else v for v in rec]
                rows.append(Row(**dict(zip(cols, vals))))
        return DataFrame(rows, cols or [], self._session._default_parallelism)


def _parse(s: str):
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


class _SessionBuilder:
    def __init__(self):
        self._conf: Dict[str, Any] = {}
        self._master = "local[1]"

    def appName(self, name: str) -> "_SessionBuilder":
        self._conf["app.name"] = name
        return self

    def master(self, m: str) -> "_SessionBuilder":
        self._master = m
        return self

    def config(self, key: str, value) -> "_SessionBuilder":
        self._conf[key] = value
        return self

    def getOrCreate(self) -> "LocalSession":
        par = 1
        if self._master.startswith("local["):
            spec = self._master[6:-1]
            par = 4 if spec == "*" else int(spec)
        return LocalSession(self._conf, par)


class LocalSession:
    """Stands in for SparkSession: createDataFrame + read.csv."""

    builder = None  # set below (class property pattern like SparkSession.builder)

    def __init__(self, conf: Optional[Dict[str, Any]] = None, parallelism: int = 4):
        self.conf = conf or {}
        self._default_parallelism = parallelism

    @property
    def read(self) -> _CsvReader:
        return _CsvReader(self)

    def createDataFrame(self, data, schema: Optional[Sequence[str]] = None) -> DataFrame:
        rows: List[Row] = []
        for item in data:
            if isinstance(item, Row):
                rows.append(item)
            elif isinstance(item, dict):
                rows.append(Row(**item))
            else:  # tuple/list + schema
                if schema is None:
                    raise ValueError("schema required for tuple data")
                rows.append(Row(**dict(zip(schema, item))))
        cols = list(schema) if schema else (rows[0].__fields__ if rows else [])
        return DataFrame(rows, cols, self._default_parallelism)

    def stop(self):
        pass


class _BuilderAccessor:
    def __get__(self, obj, objtype=None):
        return _SessionBuilder()


LocalSession.builder = _BuilderAccessor()
