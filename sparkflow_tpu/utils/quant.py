"""int8 weight quantization for TPU inference.

A serving-side capability beyond the reference (which serves f32 through
``tf.Session``, ``sparkflow/ml_util.py:65-73``): quantize a trained params
tree to symmetric per-output-channel int8 and serve it through the same
``apply``/``predict_func`` paths. Two modes, both TPU-motivated:

- ``weight_only``: kernels stored int8 + per-channel f32 scale, dequantized
  to the compute dtype at the matmul. Halves the weight HBM traffic vs
  bf16 (4x vs f32) — the win for bandwidth-bound serving — with activations
  untouched, so accuracy loss is just the 8-bit weight rounding.
- ``dynamic``: activations additionally quantized per-row at runtime
  (dynamic absmax), and the matmul runs int8 x int8 -> int32 on the MXU's
  int8 path (2x the bf16 peak on v5e: 394 TOPS) before rescaling by
  ``row_scale x channel_scale``.

Quantization happens AFTER training/deserialization, on the serving side
(``quantize_params``); the stored model stays full-precision, so the wire
format (weights JSON / npz) and training are untouched.

The quantized tree swaps each selected ``kernel`` leaf for
``kernel_q8`` (int8) + ``kernel_scale`` (f32 per output channel); the
graphdef ``dense``/``conv2d`` evals check for the ``_q8`` form, so the whole
GraphModel serving surface (predict_func, SparkAsyncDLModel.transform,
predict_in_chunks) serves quantized trees unchanged. Conv kernels always
serve weight-only (int8 conv dot-generals lower poorly; the dequant fuses
into the conv anyway).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

MODES = ("weight_only", "dynamic")


def quantize_tensor(w, axis: int = -1):
    """Symmetric per-channel int8: returns ``(q8, scale)`` with
    ``q8 * scale ~= w``; ``scale`` keeps ``w``'s rank with size-1 axes
    everywhere except ``axis`` (broadcasts back without reshapes)."""
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(a for a in range(w.ndim) if a != (axis % w.ndim))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_tensor(q8, scale, dtype=jnp.float32):
    return (q8.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(x, q8, scale):
    """``x @ dequant(q8)`` with the contraction in int8 x int8 -> int32.

    ``x`` [..., K] float; ``q8`` [K, N] int8; ``scale`` [1, N] (or [N]) f32.
    Activations quantize per-row (dynamic absmax over K). The int32
    accumulator rescales by ``row_scale * channel_scale``.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)        # [..., 1]
    xs = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, q8, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                        # [..., N]
    return acc.astype(jnp.float32) * xs * jnp.reshape(scale, (1,) * (acc.ndim - 1) + (-1,))


def quantized_dense(x, layer_params, mode: str = "weight_only",
                    compute_dtype=None, prefix: str = "kernel"):
    """Dense matmul over a possibly-quantized layer dict. Returns None when
    the layer is NOT quantized (caller runs its normal path). The mode is a
    property of the serving model (``quant_mode``), not the tree — the same
    quantized tree serves either mode. ``prefix`` selects the kernel within
    a multi-projection layer dict (e.g. 'qkv_kernel' in a transformer
    block); the bias is looked up as the matching ``*bias`` name."""
    if not isinstance(layer_params, dict) or f"{prefix}_q8" not in layer_params:
        return None
    q8 = layer_params[f"{prefix}_q8"]
    scale = layer_params[f"{prefix}_scale"]
    if mode == "dynamic" and q8.ndim == 2:
        y = int8_matmul(x, q8, scale)
    else:
        k = dequantize_tensor(q8, scale,
                              compute_dtype or jnp.result_type(x, jnp.float32))
        y = jnp.matmul(x.astype(k.dtype), k)
    bias_name = prefix[:-6] + "bias"  # 'kernel' -> 'bias', 'o_kernel' -> 'o_bias'
    if bias_name in layer_params:
        y = y + layer_params[bias_name].astype(y.dtype)
    return y


def quantize_for_serving(model, params, mode: str = "weight_only",
                         min_size: int = 4096):
    """Shared implementation behind the model families'
    ``quantize_for_serving``: validate, set the model's ``quant_mode``,
    return the quantized tree (``quantize_params`` warns if nothing
    matched)."""
    if mode not in MODES:
        raise ValueError(f"quant mode must be one of {MODES}, got {mode!r}")
    model.quant_mode = mode
    return quantize_params(params, min_size=min_size)


def _is_quantizable_kernel(path_leaf: str, arr) -> bool:
    # 'kernel' (graphdef dense/conv2d, the classifier head) or the
    # transformer family's named projections ('qkv_kernel', 'o_kernel',
    # 'fc1_kernel', ...); 2-D matmul or 4-D conv kernels
    return ((path_leaf == "kernel" or path_leaf.endswith("_kernel"))
            and getattr(arr, "ndim", 0) in (2, 4))


def quantize_params(params: Dict[str, Dict[str, Any]],
                    min_size: int = 4096) -> Dict[str, Dict[str, Any]]:
    """Quantize every dense/conv ``kernel`` leaf with >= ``min_size`` elements
    (small layers aren't worth the rounding) in a nested-dict params tree —
    the shape both GraphModel and the registry models use. Non-kernel leaves
    (biases, norms, embeddings) pass through untouched.

    The quantized tree is mode-agnostic; the serving model's ``quant_mode``
    ('weight_only' | 'dynamic') picks the matmul path. Conv kernels always
    serve weight-only.

    Warns when NO leaf quantized — naming conventions the matcher doesn't
    know (e.g. TF1 graphs with variables named 'W'/'weights', or everything
    under ``min_size``) would otherwise silently serve full precision while
    the caller believes it's int8. The warning lives HERE so every entry
    point (quantize_for_serving, the estimator's serving-side
    _cached_quantized_params) gets it.
    """

    def qlayer(layer):
        if not isinstance(layer, dict):
            return layer
        out = {}
        for name, arr in layer.items():
            if isinstance(arr, dict):
                out[name] = qlayer(arr)
                continue
            size = int(np_size(arr))
            if _is_quantizable_kernel(name, arr) and size >= min_size:
                q8, scale = quantize_tensor(arr, axis=-1)  # per out-channel
                out[f"{name}_q8"] = q8
                out[f"{name}_scale"] = scale
            else:
                out[name] = arr
        return out

    q = {k: qlayer(v) for k, v in params.items()}

    def _count_q8(d):
        return sum(_count_q8(v) if isinstance(v, dict)
                   else int(isinstance(k, str) and k.endswith("_q8"))
                   for k, v in d.items())

    if _count_q8(q) == 0:
        import logging
        logging.getLogger(__name__).warning(
            "quantize_params: no kernel leaf quantized — every matmul/conv "
            "kernel is either below min_size=%d elements or not named "
            "'kernel'/'*_kernel' (e.g. raw TF1 variables named "
            "'W'/'weights'); serving will run FULL PRECISION", min_size)
    return q


def np_size(arr) -> int:
    try:
        return int(arr.size)
    except Exception:
        import numpy as np

        return int(np.asarray(arr).size)
