"""Custom TPU kernels (pallas) and their portable fallbacks.

The reference delegates all kernels to the TF 1.x C++ runtime (SURVEY.md §2.2);
here XLA compiles almost everything, and the hot ops that benefit from manual
scheduling are hand-written pallas kernels with jnp fallbacks for CPU tests:

- :func:`flash_attention` — fused online-softmax attention (no S x S
  materialization in HBM)
- :func:`ring_attention`  — sequence-parallel attention over an ``sp`` mesh
  axis: K/V shards rotate around the ICI ring while softmax statistics merge
  blockwise, giving O(S/n) memory per device for arbitrarily long sequences
- :func:`ring_flash_attention` — the same ring, but each visiting block runs
  the pallas flash kernel (device-local operands inside shard_map) and blocks
  merge exactly via the kernel's saved logsumexp
"""

from .attention import (attention_reference, flash_attention,
                        paged_attention, paged_attention_reference,
                        paged_attention_verify,
                        paged_attention_verify_reference,
                        ring_attention, ring_flash_attention)

__all__ = ["flash_attention", "ring_attention", "ring_flash_attention",
           "attention_reference", "paged_attention",
           "paged_attention_reference", "paged_attention_verify",
           "paged_attention_verify_reference"]
