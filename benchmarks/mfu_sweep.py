"""MFU diagnosis sweep for the BERT-base seq-512 train step (TPU).

Isolates the suspected non-matmul costs one at a time and prints one JSON
line per variant so the MFU gap (BENCH_r02 estimated ~24% on v5e) can be
attributed instead of guessed at:

- batch size (16 / 32 / 64 / 128): MXU utilization rises with larger
  effective matmul M-dims until HBM pressure bites
- dropout off vs on: how much of the step is threefry mask generation
  (24 [B,S,H]-sized bernoulli draws per step) + the where-multiply
- rbg vs threefry dropout keys: the hardware PRNG costs a fraction of
  threefry's VPU work; typed keys carry their impl through split/bernoulli
- attention off the pallas kernel (force_xla): whether flash is winning
  or losing vs XLA's fused attention at seq 512
- flash block_q x block_k variants at seq 512

Usage: python benchmarks/mfu_sweep.py [--quick]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

QUICK = "--quick" in sys.argv


def main():
    from sparkflow_tpu.utils.hw import ensure_live_backend
    ensure_live_backend()

    import jax
    import jax.numpy as jnp
    import optax

    from sparkflow_tpu.models import build_registry_spec, model_from_json
    from sparkflow_tpu.optimizers import build_optimizer
    from sparkflow_tpu.utils.flops import (device_peak_flops, mfu,
                                           transformer_train_step_flops)

    on_tpu = jax.default_backend() == "tpu"
    if QUICK or not on_tpu:
        cfg = dict(vocab_size=1000, hidden=128, num_layers=2, num_heads=4,
                   mlp_dim=256, max_len=128)
    else:
        cfg = dict(vocab_size=30522, hidden=768, num_layers=12, num_heads=12,
                   mlp_dim=3072, max_len=512)
    compute_dtype = "bfloat16" if on_tpu else None
    peak = device_peak_flops()
    rs = np.random.RandomState(0)
    n_steps = 2 if QUICK else 8

    def measure(B, dropout, rng_impl="threefry2x32", force_xla_attn=False,
                block_q=None, block_k=None):
        from sparkflow_tpu.ops.attention import force_xla_attention
        import contextlib

        m = model_from_json(
            build_registry_spec("transformer_classifier", num_classes=2,
                                dropout=dropout, **cfg),
            compute_dtype=compute_dtype)
        if block_q or block_k:
            # pin the flash tile sizes via a wrapper around _attention
            from sparkflow_tpu.ops import attention as A

            def patched(q, k, v, mask, causal):
                return A.flash_attention(q, k, v, causal=causal, kv_mask=mask,
                                         block_q=block_q, block_k=block_k)
            m._attention = patched
        opt = build_optimizer("adam", 1e-4, None)

        def key(i):
            return jax.random.key(i, impl=rng_impl)

        params = m.init(jax.random.PRNGKey(0))
        state = opt.init(params)

        ctx = force_xla_attention() if force_xla_attn else contextlib.nullcontext()

        with ctx:
            @jax.jit
            def step(params, state, ids, y, rng):
                def lf(p):
                    return m.loss_vector(p, {"input_ids": ids, "y": y},
                                         train=True, rng=rng).mean()
                loss, g = jax.value_and_grad(lf)(params)
                u, state2 = opt.update(g, state, params)
                return optax.apply_updates(params, u), state2, loss

            def batch(i):
                return (jnp.asarray(rs.randint(0, cfg["vocab_size"],
                                               (B, cfg["max_len"])), jnp.int32),
                        jnp.asarray(np.eye(2)[rs.randint(0, 2, B)], jnp.float32))

            ids, y = batch(0)
            params, state, loss = step(params, state, ids, y, key(0))
            jax.block_until_ready(params)
            from sparkflow_tpu.ops.attention import last_attention_path
            attn_path = last_attention_path()  # what actually traced
            t0 = time.perf_counter()
            for i in range(n_steps):
                ids, y = batch(i + 1)
                params, state, loss = step(params, state, ids, y, key(i + 1))
            jax.block_until_ready(params)
        dt = (time.perf_counter() - t0) / n_steps
        fl = transformer_train_step_flops(
            B, cfg["max_len"], cfg["hidden"], cfg["num_layers"],
            cfg["mlp_dim"], num_classes=2)
        rec = {"batch": B, "dropout": dropout, "rng": rng_impl,
               # the path flash_attention ACTUALLY traced, not the requested
               # one: a tile-rule fallback must not misattribute the delta
               "attn": attn_path,
               "requested": ("xla" if force_xla_attn else
                             f"pallas{block_q or ''}x{block_k or ''}"),
               "ms_per_step": round(dt * 1e3, 1),
               "examples_per_sec": round(B / dt, 1),
               "tflops_per_sec": round(fl / dt / 1e12, 2)}
        u = mfu(fl / dt, peak)
        if u is not None:
            rec["mfu"] = round(u, 4)
        print(json.dumps(rec), flush=True)
        return dt

    B0 = 8 if QUICK else 32
    if "--trace" in sys.argv:
        # one profiled measurement for hotspot attribution (open the
        # resulting trace in Perfetto / tensorboard)
        from sparkflow_tpu.utils.tracing import trace
        with trace("/tmp/mfu_trace"):
            measure(B0, dropout=0.1)
        print(json.dumps({"trace_written": "/tmp/mfu_trace"}), flush=True)
        return
    # batch ladder (the first lever)
    for B in ((4, 8) if QUICK else (16, 32, 64, 128)):
        try:
            measure(B, dropout=0.1)
        except Exception as e:  # OOM at the top end is informative, not fatal
            print(json.dumps({"batch": B, "error": str(e)[:200]}), flush=True)
    # dropout cost: off entirely, then cheap hardware PRNG
    measure(B0, dropout=0.0)
    measure(B0, dropout=0.1, rng_impl="rbg")
    # attention path: XLA blockwise vs pallas, plus tile variants
    measure(B0, dropout=0.1, force_xla_attn=True)
    if not QUICK:
        for bq, bk in ((256, 512), (512, 256), (256, 256)):
            measure(B0, dropout=0.1, block_q=bq, block_k=bk)


if __name__ == "__main__":
    main()
