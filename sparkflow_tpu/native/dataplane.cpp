// sparkflow-tpu native dataplane: batch assembly queue + fast CSV loader.
//
// Role in the framework: the host-side data runtime between the Spark/localml
// row world and the TPU's fixed-shape batch world. The reference's equivalent
// work happened in Python per partition (iterate rows, np.asarray, slice
// batches — sparkflow/ml_util.py handle_features/handle_feed_dict); here a
// C++ worker thread assembles padded, masked, optionally shuffled batches
// into a preallocated ring of buffers while Python (and the TPU) stay busy —
// host batch prep overlaps device compute, and the GIL is released for the
// whole ingest path.
//
// Exposed C ABI (ctypes-bound in sparkflow_tpu/utils/data.py):
//   sfq_create / sfq_push / sfq_finish / sfq_pop / sfq_destroy   (batch queue)
//   sf_csv_load / sf_free                                        (CSV matrix)
//
// Build: g++ -O3 -march=native -shared -fPIC dataplane.cpp -o libsfdata.so -lpthread

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Batch queue
// ---------------------------------------------------------------------------

struct Batch {
  std::vector<float> x, y, mask;
  int64_t n_real = 0;
};

struct SfQueue {
  int64_t batch_size, row_dim, label_dim, capacity;
  bool shuffle;
  uint64_t seed;

  std::mutex mu;
  std::condition_variable cv_push, cv_pop, cv_idle;
  std::vector<Batch> ring;
  int64_t head = 0, tail = 0, count = 0;  // ready batches
  int64_t inflight = 0;                   // threads inside push/pop/finish
  bool finished = false;                  // producer done
  bool closed = false;                    // consumer destroyed

  // staging area for incoming rows (one batch worth)
  std::vector<float> stage_x, stage_y;
  int64_t staged = 0;
  std::mt19937_64 rng;
  std::vector<int64_t> perm;  // per-batch shuffle permutation
};

static void emit_batch(SfQueue* q) {
  // assemble the staged rows into a ready batch (pad + mask [+ shuffle]),
  // caller holds the lock
  Batch b;
  const int64_t B = q->batch_size, D = q->row_dim, L = q->label_dim;
  b.x.assign(B * D, 0.0f);
  if (L > 0) b.y.assign(B * L, 0.0f);
  b.mask.assign(B, 0.0f);
  b.n_real = q->staged;

  q->perm.resize(q->staged);
  for (int64_t i = 0; i < q->staged; ++i) q->perm[i] = i;
  if (q->shuffle) {
    for (int64_t i = q->staged - 1; i > 0; --i) {
      int64_t j = (int64_t)(q->rng() % (uint64_t)(i + 1));
      std::swap(q->perm[i], q->perm[j]);
    }
  }
  for (int64_t i = 0; i < q->staged; ++i) {
    const int64_t src = q->perm[i];
    std::memcpy(&b.x[i * D], &q->stage_x[src * D], D * sizeof(float));
    if (L > 0) std::memcpy(&b.y[i * L], &q->stage_y[src * L], L * sizeof(float));
    b.mask[i] = 1.0f;
  }
  q->staged = 0;

  q->ring[q->tail] = std::move(b);
  q->tail = (q->tail + 1) % q->capacity;
  q->count += 1;
  q->cv_pop.notify_one();
}

SfQueue* sfq_create(int64_t batch_size, int64_t row_dim, int64_t label_dim,
                    int64_t capacity, int shuffle, uint64_t seed) {
  if (batch_size <= 0 || row_dim <= 0 || capacity <= 0) return nullptr;
  auto* q = new SfQueue();
  q->batch_size = batch_size;
  q->row_dim = row_dim;
  q->label_dim = label_dim;
  q->capacity = capacity;
  q->shuffle = shuffle != 0;
  q->seed = seed;
  q->rng.seed(seed);
  q->ring.resize(capacity);
  q->stage_x.resize(batch_size * row_dim);
  if (label_dim > 0) q->stage_y.resize(batch_size * label_dim);
  return q;
}

// RAII guard counting threads inside queue operations so sfq_destroy can
// drain before freeing (prevents use-after-free on the mutex/cvs when a
// blocked producer wakes during teardown). Construct with the lock held.
struct InflightGuard {
  SfQueue* q;
  explicit InflightGuard(SfQueue* qq) : q(qq) { q->inflight++; }
  ~InflightGuard() {
    q->inflight--;
    if (q->inflight == 0) q->cv_idle.notify_all();
  }
};

// Push n rows (x: n*row_dim floats, y: n*label_dim floats or null).
// Blocks when the ring is full. Returns rows accepted, -1 on error/closed.
int64_t sfq_push(SfQueue* q, const float* x, const float* y, int64_t n) {
  if (!q || n < 0) return -1;
  int64_t done = 0;
  while (done < n) {
    std::unique_lock<std::mutex> lk(q->mu);
    InflightGuard guard(q);
    if (q->closed) return -1;
    const int64_t room = q->batch_size - q->staged;
    const int64_t take = std::min(room, n - done);
    std::memcpy(&q->stage_x[q->staged * q->row_dim], &x[done * q->row_dim],
                take * q->row_dim * sizeof(float));
    if (q->label_dim > 0 && y)
      std::memcpy(&q->stage_y[q->staged * q->label_dim],
                  &y[done * q->label_dim], take * q->label_dim * sizeof(float));
    q->staged += take;
    done += take;
    if (q->staged == q->batch_size) {
      q->cv_push.wait(lk, [q] { return q->count < q->capacity || q->closed; });
      if (q->closed) return -1;
      emit_batch(q);
    }
  }
  return done;
}

// Producer is done: flush the partial batch (padded+masked) and mark EOF.
void sfq_finish(SfQueue* q) {
  if (!q) return;
  std::unique_lock<std::mutex> lk(q->mu);
  InflightGuard guard(q);
  if (q->staged > 0 && !q->closed) {
    q->cv_push.wait(lk, [q] { return q->count < q->capacity || q->closed; });
    if (!q->closed) emit_batch(q);
  }
  q->finished = true;
  q->cv_pop.notify_all();
}

// Pop one ready batch into caller buffers. Returns n_real rows (>0), 0 on EOF,
// -1 on error/closed. Blocks until a batch or EOF.
int64_t sfq_pop(SfQueue* q, float* x_out, float* y_out, float* mask_out) {
  if (!q || !x_out || !mask_out) return -1;
  std::unique_lock<std::mutex> lk(q->mu);
  InflightGuard guard(q);
  q->cv_pop.wait(lk, [q] { return q->count > 0 || q->finished || q->closed; });
  if (q->closed) return -1;
  if (q->count == 0) return 0;  // finished and drained
  Batch& b = q->ring[q->head];
  std::memcpy(x_out, b.x.data(), b.x.size() * sizeof(float));
  if (q->label_dim > 0 && y_out)
    std::memcpy(y_out, b.y.data(), b.y.size() * sizeof(float));
  std::memcpy(mask_out, b.mask.data(), b.mask.size() * sizeof(float));
  q->head = (q->head + 1) % q->capacity;
  q->count -= 1;
  q->cv_push.notify_one();
  return b.n_real;
}

// Mark closed and wake every blocked producer/consumer. Does NOT free — the
// binding calls this first, waits for its own threads to return from the C
// calls, then calls sfq_destroy. Safe to call repeatedly.
void sfq_close(SfQueue* q) {
  if (!q) return;
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->cv_push.notify_all();
  q->cv_pop.notify_all();
}

void sfq_destroy(SfQueue* q) {
  if (!q) return;
  {
    std::unique_lock<std::mutex> lk(q->mu);
    q->closed = true;
    q->cv_push.notify_all();
    q->cv_pop.notify_all();
    // drain: wait until every thread inside push/pop/finish has left (their
    // waits re-check predicates that now include `closed` and return)
    q->cv_idle.wait(lk, [q] { return q->inflight == 0; });
  }
  delete q;
}

// ---------------------------------------------------------------------------
// Fast numeric CSV loader (MNIST-style dense numeric files)
// ---------------------------------------------------------------------------

// Parses a numeric CSV into a row-major float32 matrix. Returns the matrix
// (malloc'd; free with sf_free), sets *rows_out/*cols_out. nullptr on error.
float* sf_csv_load(const char* path, int64_t* rows_out, int64_t* cols_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  const long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  char* buf = (char*)std::malloc(size + 1);
  if (!buf || std::fread(buf, 1, size, f) != (size_t)size) {
    std::fclose(f);
    std::free(buf);
    return nullptr;
  }
  std::fclose(f);
  buf[size] = '\0';

  std::vector<float> vals;
  vals.reserve(size / 3);
  int64_t cols = 0, rows = 0;
  int64_t cur_cols = 0;
  const char* p = buf;
  const char* end = buf + size;
  while (p < end) {
    char* next = nullptr;
    float v = std::strtof(p, &next);
    if (next == p) {  // no parse progress: skip one char (handles stray text)
      if (*p == '\n') {
        if (cur_cols > 0) {
          if (cols == 0) cols = cur_cols;
          if (cur_cols != cols) { std::free(buf); return nullptr; }
          rows++;
          cur_cols = 0;
        }
      }
      p++;
      continue;
    }
    vals.push_back(v);
    cur_cols++;
    p = next;
    while (p < end && (*p == ',' || *p == ' ' || *p == '\r')) p++;
    if (p < end && *p == '\n') {
      if (cols == 0) cols = cur_cols;
      if (cur_cols != cols) { std::free(buf); return nullptr; }
      rows++;
      cur_cols = 0;
      p++;
    }
  }
  if (cur_cols > 0) {  // last line without newline
    if (cols == 0) cols = cur_cols;
    if (cur_cols != cols) { std::free(buf); return nullptr; }
    rows++;
  }
  std::free(buf);

  float* out = (float*)std::malloc(vals.size() * sizeof(float));
  if (!out) return nullptr;
  std::memcpy(out, vals.data(), vals.size() * sizeof(float));
  *rows_out = rows;
  *cols_out = cols;
  return out;
}

void sf_free(void* p) { std::free(p); }

}  // extern "C"
