"""Feature transformers: the ``pyspark.ml.feature`` subset the reference examples
use (``VectorAssembler``, ``OneHotEncoder``, ``Normalizer`` — see reference
``examples/simple_dnn.py:40-41``, ``examples/autoencoder_example.py:26-27``)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .base import Transformer
from .linalg import DenseVector, SparseVector, Vectors, vector_to_array
from .param import Param, Params, TypeConverters, keyword_only, HasInputCol, HasOutputCol
from .sql import DataFrame, Row


class VectorAssembler(Transformer, HasInputCol, HasOutputCol):
    """Concatenates numeric / vector columns into one DenseVector column."""

    inputCols = Param(Params._dummy(), "inputCols", "input column names",
                      typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, inputCols=None, outputCol=None):
        super().__init__()
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getInputCols(self) -> List[str]:
        return self.getOrDefault(self.inputCols)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_cols = self.getInputCols()
        out_col = self.getOrDefault(self.outputCol)
        rows = []
        for r in dataset.collect():
            parts = [vector_to_array(r[c]) for c in in_cols]
            vec = Vectors.dense(np.concatenate(parts))
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class OneHotEncoder(Transformer, HasInputCol, HasOutputCol):
    """Category index -> one-hot sparse vector (pyspark 2.x OneHotEncoder
    semantics: transform-only; vector size inferred as max(index)+1; dropLast
    drops the final category — the reference uses ``dropLast=False``,
    ``examples/simple_dnn.py:41``)."""

    dropLast = Param(Params._dummy(), "dropLast", "drop the last category",
                     typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, dropLast=True):
        super().__init__()
        self._setDefault(dropLast=True)
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getDropLast(self) -> bool:
        return self.getOrDefault(self.dropLast)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        drop_last = self.getDropLast()
        values = [int(r[in_col]) for r in dataset.collect()]
        size = (max(values) + 1) if values else 0
        if drop_last:
            size -= 1
        rows = []
        for r, v in zip(dataset.collect(), values):
            if v < size:
                vec = SparseVector(size, [v], [1.0])
            else:  # dropped last category encodes as all-zeros
                vec = SparseVector(size, [], [])
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)


class Normalizer(Transformer, HasInputCol, HasOutputCol):
    """Scale each vector to unit p-norm (reference autoencoder example uses
    p=1.0, ``examples/autoencoder_example.py:27``)."""

    p = Param(Params._dummy(), "p", "norm order", typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, p=2.0):
        super().__init__()
        self._setDefault(p=2.0)
        kwargs = self._input_kwargs
        self._set(**kwargs)

    def getP(self) -> float:
        return self.getOrDefault(self.p)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.outputCol)
        p = self.getP()
        rows = []
        for r in dataset.collect():
            arr = vector_to_array(r[in_col])
            norm = np.linalg.norm(arr, ord=p)
            vec = Vectors.dense(arr / norm if norm > 0 else arr)
            rows.append(Row(**{**r.asDict(), out_col: vec}))
        cols = dataset.columns + ([out_col] if out_col not in dataset.columns else [])
        return DataFrame(rows, cols, dataset.num_partitions)
