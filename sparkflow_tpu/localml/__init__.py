"""localml: a pyspark-ml-compatible local engine.

The reference framework's public surface is the Spark ML API — ``Estimator`` /
``Model`` / ``Pipeline`` with ``Param``s (``sparkflow/tensorflow_async.py``). This
package provides an API-compatible local implementation of the subset sparkflow
uses, so the TPU framework runs standalone (no JVM, no pyspark install) with the
*same user code*; when pyspark is importable, :mod:`sparkflow_tpu.compat` selects
the real pyspark classes instead and this package is unused.

Implemented subset (names and behavior match pyspark 2.4-3.x where the reference
touches them):

- ``param``:       ``Param``, ``Params``, ``TypeConverters``, ``keyword_only``
- ``base``:        ``Estimator``, ``Transformer``, ``Model``, ``Identifiable``,
                   ``MLReadable``, ``MLWritable``
- ``linalg``:      ``Vectors``, ``DenseVector``, ``SparseVector``
- ``sql``:         ``Row``, ``DataFrame``, ``RDD``, ``LocalSession``, ``functions.rand``
- ``feature``:     ``VectorAssembler``, ``OneHotEncoder``, ``Normalizer``,
                   ``Tokenizer``, ``StopWordsRemover``, ``StringIndexer``,
                   ``StandardScaler``, ``MinMaxScaler``, ``Bucketizer``
- ``pipeline``:    ``Pipeline``, ``PipelineModel``
- ``evaluation``:  ``MulticlassClassificationEvaluator``,
                   ``BinaryClassificationEvaluator``
- ``tuning``:      ``ParamGridBuilder``, ``CrossValidator``,
                   ``TrainValidationSplit``
"""

from .param import Param, Params, TypeConverters, keyword_only
from .base import Estimator, Transformer, Model, Identifiable, MLReadable, MLWritable
from .linalg import Vectors, DenseVector, SparseVector
from .sql import Row, DataFrame, RDD, LocalSession
from .feature import (VectorAssembler, OneHotEncoder, Normalizer,
                      WordpieceEncoder, Tokenizer, StopWordsRemover,
                      StringIndexer, StringIndexerModel,
                      StandardScaler, StandardScalerModel,
                      MinMaxScaler, MinMaxScalerModel, Bucketizer,
                      IndexToString, PCA, PCAModel, Imputer,
                      ImputerModel)
from .pipeline import Pipeline, PipelineModel
from .evaluation import (MulticlassClassificationEvaluator,
                         BinaryClassificationEvaluator,
                         RegressionEvaluator)
from .tuning import (ParamGridBuilder, CrossValidator, CrossValidatorModel,
                     TrainValidationSplit, TrainValidationSplitModel)

__all__ = [
    "Param", "Params", "TypeConverters", "keyword_only",
    "Estimator", "Transformer", "Model", "Identifiable", "MLReadable", "MLWritable",
    "Vectors", "DenseVector", "SparseVector",
    "Row", "DataFrame", "RDD", "LocalSession",
    "VectorAssembler", "WordpieceEncoder", "OneHotEncoder", "Normalizer",
    "Tokenizer", "StopWordsRemover", "StringIndexer", "StringIndexerModel",
    "StandardScaler", "StandardScalerModel", "MinMaxScaler",
    "MinMaxScalerModel", "Bucketizer",
    "Pipeline", "PipelineModel",
    "MulticlassClassificationEvaluator", "BinaryClassificationEvaluator",
    "RegressionEvaluator", "IndexToString", "PCA", "PCAModel",
    "Imputer", "ImputerModel",
    "ParamGridBuilder", "CrossValidator", "CrossValidatorModel",
    "TrainValidationSplit", "TrainValidationSplitModel",
]
