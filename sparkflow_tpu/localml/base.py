"""Estimator/Transformer/Model base classes + save/load, like ``pyspark.ml.base``.

Persistence here is the localml-native path: a directory with ``metadata.json``
naming the class and a dill payload of the instance (the pyspark backend instead
uses the StopWordsRemover carrier trick — see ``sparkflow_tpu/pipeline_util.py``).
"""

from __future__ import annotations

import importlib
import json
import os
import zlib
from typing import Any

import dill

from .param import Identifiable, Params

_FORMAT = "sparkflow-tpu-localml"


class _Writer:
    def __init__(self, instance):
        self.instance = instance
        self._overwrite = False

    def overwrite(self):
        self._overwrite = True
        return self

    def save(self, path: str):
        if os.path.exists(path):
            if not self._overwrite:
                raise IOError(f"path {path} already exists; use .overwrite()")
        os.makedirs(path, exist_ok=True)
        payload = zlib.compress(dill.dumps(self.instance))
        cls = type(self.instance)
        meta = {
            "format": _FORMAT,
            "class": f"{cls.__module__}.{cls.__qualname__}",
            "uid": getattr(self.instance, "uid", None),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)
        with open(os.path.join(path, "stage.dill.z"), "wb") as f:
            f.write(payload)


class _Reader:
    def __init__(self, cls):
        self.cls = cls

    def load(self, path: str):
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("format") != _FORMAT:
            raise IOError(f"{path} is not a {_FORMAT} save")
        with open(os.path.join(path, "stage.dill.z"), "rb") as f:
            obj = dill.loads(zlib.decompress(f.read()))
        return obj


class MLWritable:
    def write(self):
        return _Writer(self)

    def save(self, path: str):
        self.write().save(path)


class MLReadable:
    @classmethod
    def read(cls):
        return _Reader(cls)

    @classmethod
    def load(cls, path: str):
        return cls.read().load(path)


# MLReadable/MLWritable precede Params so user classes can re-list them AFTER
# Identifiable-bearing mixins (the reference's class declarations do exactly
# that: ``class SparkAsyncDLModel(Model, ..., MLReadable, MLWritable,
# Identifiable)``, sparkflow/tensorflow_async.py:51) without C3 conflicts.
class Transformer(MLReadable, MLWritable, Params):
    def _transform(self, dataset):
        raise NotImplementedError

    def transform(self, dataset, params=None):
        if params:
            return self.copy(params)._transform(dataset)
        return self._transform(dataset)


class Estimator(MLReadable, MLWritable, Params):
    def _fit(self, dataset):
        raise NotImplementedError

    def fit(self, dataset, params=None):
        if params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)


class Model(Transformer):
    """A fitted Transformer (pyspark.ml.Model analog)."""
