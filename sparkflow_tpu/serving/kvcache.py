"""Slot-based paged KV-cache manager for autoregressive decode.

Decode serving needs one KV cache per in-flight sequence, but sequences are
ragged (a 20-token chat next to a 2048-token completion) and join/leave the
batch every token. A dense ``[slots, max_len]`` cache would reserve worst-case
memory for every slot; instead the pool is carved into fixed-size **pages**
(``page_size`` tokens each) and each slot owns just the pages its tokens
occupy, listed in a per-slot **page table** — the same indirection OS virtual
memory and vLLM's PagedAttention use. The pallas
:func:`~sparkflow_tpu.ops.paged_attention` kernel consumes the table directly
(scalar-prefetched BlockSpec index maps), so the scattered pages are never
gathered into a contiguous cache on the device.

This class is the **host-side bookkeeper**: free-page list, per-slot tables
and lengths, allocation/append/free at token granularity. The actual K/V
arrays live on-device inside :class:`~sparkflow_tpu.serving.decode.DecodeEngine`'s
donated state pytree; the manager just hands the engine ``page_table`` /
``lengths`` operands each step. The bookkeeping is device-layout-blind: a
page id names the same ``[page_size, heads, head_dim]`` block of every
layer, whether the pool lives on one chip or shards its heads axis over a
tp mesh / its layers axis over a pp mesh — refcounts, the prefix trie and
COW never change when the engine re-lays the pool out.

Admission is reservation-based: :meth:`alloc` checks that the request's
**worst case** (prompt + max_new_tokens) fits in free pages before admitting,
then allocates lazily as tokens arrive (:meth:`append`). A request that was
admitted can therefore never hit out-of-pages mid-generation — backpressure
happens once, at admission, where the batcher can map it to ``QueueFull``.

Unassigned page-table entries point at page 0, a **scratch page** the manager
never hands out: inactive slots' decode writes land there harmlessly and the
kernel's index maps always see valid pool indices.

Shared-prefix caching (copy-on-write page sharing)
--------------------------------------------------
Real decode traffic repeats prompt prefixes — system prompts, few-shot
preambles — and recomputing their K/V per request is the dominant redundant
cost. The manager therefore keeps a **prefix index**: a hash-chained trie of
page-aligned prompt blocks (each key is ``blake2b(parent_key ‖ block_tokens)``,
so a block is only reachable through its exact prefix chain). When
:meth:`alloc` receives the actual prompt *tokens*, it walks the chain and maps
every indexed page straight into the new slot's table — no allocation, no
prefill for those tokens — and returns ``(shared_pages, tokens_saved)``.

Sharing is reference-counted and copy-on-write by construction:

* only **full** pages are shared, and never the page holding the final prompt
  token (cap ``(len(prompt) - 1) // page_size``) — the consumer always
  recomputes at least one suffix token (its first-token logits) and all of
  its writes (suffix prefill and decode appends) land at positions past the
  shared pages, i.e. in private pages. Divergence therefore never mutates a
  shared page; "copy"-on-write degenerates to allocate-on-write.
* :meth:`free` decrements; a page is reclaimed only at refcount 0. Pages that
  are in the prefix index keep their contents after release in a **cached
  tier** (LRU) — still evictable supply for admission, but a later prompt
  with the same prefix revives them for free.
* a page's contents are published to the index by :meth:`commit_prefix` only
  **after** the engine has committed the K/V on device — an alloc-time
  registration would let a concurrent request share pages whose K/V hasn't
  been written yet.
* admission stays exact: a prefix hit reduces the worst-case demand by the
  shared pages (they are mapped, not drawn from the pool), and a shared page
  is never double-reserved — reservations only cover future *private* pages.

The pallas kernel needs zero changes: aliased page-table entries are just two
tables pointing at the same pool index.

Occupancy and fragmentation export as ``serving/kv/*`` gauges, and the
decode-plane summary (occupancy, fragmentation, prefix hit-rate, tokens
saved) additionally exports under ``decode/*`` for fleet dashboards.
"""

from __future__ import annotations

import hashlib
import math
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..utils import metrics as metrics_mod
from ..utils import quant

__all__ = ["PagedKVCache", "OutOfPages"]


class OutOfPages(Exception):
    """Raised by :meth:`PagedKVCache.alloc` when the reservation (worst-case
    pages for the request) does not fit in the free pool — the admission
    signal the continuous batcher turns into backpressure."""


class PagedKVCache:
    """Page bookkeeping for ``num_slots`` concurrent sequences.

    Parameters
    ----------
    num_pages : int
        Total pool pages **including** the reserved scratch page 0; usable
        capacity is ``num_pages - 1`` pages.
    page_size : int
        Tokens per page.
    num_slots : int
        Decode slots (the fixed batch dimension of the decode step).
    max_pages_per_slot : int
        Page-table width — caps any single sequence at
        ``max_pages_per_slot * page_size`` tokens.
    kv_dtype : str
        Device pool element layout — ``"bf16"`` (full precision), ``"int8"``
        or ``"fp8"``. Pure metadata here: page ids, refcounts, COW and the
        prefix trie are byte-layout-blind (aliased table entries gather the
        same quantized rows), so the manager only records the layout for
        capacity accounting (``stats()["kv_dtype"]`` / gauges) and fleet
        headroom comparison.
    kv_bytes_per_page : int, optional
        Device bytes one page costs across all layers (K + V + scales), as
        measured by the engine from the actual pool tensors. Exported so
        routing can compare *byte* headroom across replicas with different
        pool layouts.
    """

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 max_pages_per_slot: int,
                 metrics: Optional[metrics_mod.Metrics] = None,
                 kv_dtype: str = "bf16",
                 kv_bytes_per_page: Optional[int] = None):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2 (page 0 is scratch), "
                             f"got {num_pages}")
        if page_size < 1 or num_slots < 1 or max_pages_per_slot < 1:
            raise ValueError("page_size, num_slots, max_pages_per_slot must "
                             "be >= 1")
        if kv_dtype not in quant.KV_DTYPES:
            raise ValueError(f"kv_dtype must be one of {quant.KV_DTYPES}, "
                             f"got {kv_dtype!r}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_slots = int(num_slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.kv_dtype = kv_dtype
        self.kv_bytes_per_page = (int(kv_bytes_per_page)
                                  if kv_bytes_per_page is not None else None)
        self.metrics = metrics if metrics is not None else metrics_mod.Metrics()
        self._lock = threading.Lock()
        # page 0 is scratch: never allocated, absorbs inactive slots' writes
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._tables = np.zeros((self.num_slots, self.max_pages_per_slot),
                                np.int32)
        self._lengths = np.zeros(self.num_slots, np.int32)
        self._pages_held = np.zeros(self.num_slots, np.int32)
        self._reserved = np.zeros(self.num_slots, np.int32)  # beyond held
        self._active = np.zeros(self.num_slots, bool)
        # prefix sharing state: per-page refcounts; chain-hash -> page for
        # published full prefix blocks; reverse map for deregistration; and
        # the cached tier — refcount-0 pages whose contents are still indexed
        # (LRU order; evicted last, after the plain free list is exhausted)
        self._refcount = np.zeros(self.num_pages, np.int32)
        self._prefix_index: Dict[bytes, int] = {}
        self._page_key: Dict[int, bytes] = {}
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        self._prefix_lookups = 0
        self._prefix_hits = 0
        self._tokens_saved = 0
        self._export_gauges_locked()

    # -- capacity ------------------------------------------------------------

    @staticmethod
    def pages_for(tokens: int, page_size: int) -> int:
        return max(0, math.ceil(tokens / page_size))

    def free_slot(self) -> Optional[int]:
        """Lowest inactive slot index, or None when all slots are busy."""
        with self._lock:
            idle = np.flatnonzero(~self._active)
            return int(idle[0]) if idle.size else None

    def can_admit(self, total_tokens: int,
                  prompt_tokens: Optional[Sequence[int]] = None) -> bool:
        """Whether a sequence whose worst case is ``total_tokens`` (prompt +
        max new tokens) could be admitted right now: a free slot exists and
        the un-reserved evictable pool covers its reservation. With the
        actual ``prompt_tokens``, prefix-index hits are subtracted from the
        demand — the exact mirror of :meth:`alloc`'s accounting."""
        need = self.pages_for(total_tokens, self.page_size)
        if need > self.max_pages_per_slot:
            return False
        with self._lock:
            if not np.any(~self._active):
                return False
            shared = 0
            revived = 0
            if prompt_tokens is not None and not isinstance(
                    prompt_tokens, (int, np.integer)):
                hits = self._lookup_locked(list(prompt_tokens))
                shared = len(hits)
                revived = sum(1 for p in hits if self._refcount[p] == 0)
            return need - shared <= self._avail_locked() - revived

    # -- prefix index --------------------------------------------------------

    def _block_digests(self, tokens: Sequence[int], limit: int) -> List[bytes]:
        """Chained digests of the first ``limit`` full page blocks: block i's
        key commits to every token before it, so equal keys mean equal
        page-aligned prefixes (up to hash collision)."""
        out: List[bytes] = []
        parent = b""
        ps = self.page_size
        for i in range(limit):
            h = hashlib.blake2b(parent, digest_size=16)
            h.update(np.asarray(tokens[i * ps:(i + 1) * ps],
                                np.int64).tobytes())
            parent = h.digest()
            out.append(parent)
        return out

    def _lookup_locked(self, tokens: List[int]) -> List[int]:
        """Longest indexed page chain for ``tokens``'s shareable prefix (full
        pages only, and never the final prompt token's page)."""
        limit = max(0, (len(tokens) - 1) // self.page_size)
        pages: List[int] = []
        for dg in self._block_digests(tokens, limit):
            pid = self._prefix_index.get(dg)
            if pid is None:
                break
            pages.append(pid)
        return pages

    def commit_prefix(self, slot: int, prompt_tokens: Sequence[int]) -> int:
        """Publish ``slot``'s full-page prompt blocks into the prefix index.
        Call only once the K/V for those tokens is committed on device — the
        index is how *other* slots find these pages, so publishing before the
        write would hand out garbage. Returns the number of newly indexed
        pages (already-indexed blocks are skipped)."""
        tokens = [int(t) for t in prompt_tokens]
        with self._lock:
            if not self._active[slot]:
                return 0
            n = min(len(tokens), int(self._lengths[slot]))
            added = 0
            for i, dg in enumerate(self._block_digests(tokens,
                                                       n // self.page_size)):
                pid = int(self._tables[slot, i])
                if pid == 0:
                    break
                if self._prefix_index.get(dg) == pid:
                    continue  # shared from the index in the first place
                if dg in self._prefix_index or pid in self._page_key:
                    continue  # block already published by a concurrent twin
                self._prefix_index[dg] = pid
                self._page_key[pid] = dg
                added += 1
            return added

    def flush_prefix_index(self) -> int:
        """Forget every indexed shared prefix: cached-tier pages (refcount
        0) return to the plain free list; pages still referenced by active
        slots merely lose their index entry, so their holders keep decoding
        but no new request can map them. The weight hot-swap calls this —
        indexed K/V was computed under the OLD weights, and serving it to a
        post-swap prompt would silently mix versions. Returns the number of
        dropped index entries."""
        with self._lock:
            dropped = len(self._prefix_index)
            self._prefix_index.clear()
            self._page_key.clear()
            while self._cached:
                pid, _ = self._cached.popitem(last=False)
                self._free.append(pid)
            self._export_gauges_locked()
            return dropped

    def _avail_locked(self) -> int:
        """Pages available to new demand: the free list plus the evictable
        cached tier, minus outstanding reservations."""
        return (len(self._free) + len(self._cached)
                - int(self._reserved.sum()))

    def _take_page_locked(self) -> int:
        """Draw one page: plain free list first, then evict the LRU cached
        page (dropping its index entry — the prefix is simply forgotten)."""
        if self._free:
            return self._free.pop()
        if self._cached:
            pid, _ = self._cached.popitem(last=False)
            dg = self._page_key.pop(pid, None)
            if dg is not None:
                self._prefix_index.pop(dg, None)
            return pid
        raise OutOfPages("page pool exhausted despite reservation "
                         "(accounting bug)")

    # -- lifecycle -----------------------------------------------------------

    def alloc(self, slot: int, prompt_tokens: Union[int, Sequence[int]],
              total_tokens: int) -> tuple:
        """Claim ``slot`` for a sequence: allocate pages covering the prompt
        now, reserve (but don't allocate) the rest of the worst case so
        :meth:`append` can never fail later. Raises :class:`OutOfPages` when
        the reservation doesn't fit.

        ``prompt_tokens`` may be the prompt length (no sharing — the legacy
        contract) or the actual token sequence, in which case indexed prefix
        pages are mapped into the table instead of allocated. Returns
        ``(shared_pages, tokens_saved)`` — ``(0, 0)`` on a miss or when only
        a length was given."""
        if isinstance(prompt_tokens, (int, np.integer)):
            tokens: Optional[List[int]] = None
            n_prompt = int(prompt_tokens)
        else:
            tokens = [int(t) for t in prompt_tokens]
            n_prompt = len(tokens)
        if n_prompt < 1:
            raise ValueError("prompt_tokens must be >= 1")
        total_tokens = max(int(total_tokens), n_prompt)
        need_now = self.pages_for(n_prompt, self.page_size)
        need_total = self.pages_for(total_tokens, self.page_size)
        if need_total > self.max_pages_per_slot:
            raise OutOfPages(
                f"sequence of {total_tokens} tokens needs {need_total} pages "
                f"> max_pages_per_slot={self.max_pages_per_slot}")
        with self._lock:
            if self._active[slot]:
                raise ValueError(f"slot {slot} is already active")
            shared: List[int] = []
            if tokens is not None:
                shared = self._lookup_locked(tokens)
                self._prefix_lookups += 1
                if shared:
                    self._prefix_hits += 1
            n_shared = len(shared)
            # shared pages are mapped, not drawn, so they leave the demand;
            # cached hits about to be revived leave the evictable supply
            revived = sum(1 for p in shared if self._refcount[p] == 0)
            avail = self._avail_locked() - revived
            if need_total - n_shared > avail:
                self.metrics.incr("serving/kv/alloc_rejections")
                raise OutOfPages(
                    f"need {need_total - n_shared} pages "
                    f"({n_shared} shared), {avail} unreserved free")
            self._tables[slot, :] = 0
            for i, pid in enumerate(shared):
                if self._refcount[pid] == 0:
                    self._cached.pop(pid, None)  # revive from the cached tier
                self._refcount[pid] += 1
                self._tables[slot, i] = pid
            for i in range(n_shared, need_now):
                pid = self._take_page_locked()
                self._refcount[pid] = 1
                self._tables[slot, i] = pid
            self._lengths[slot] = n_prompt
            self._pages_held[slot] = need_now
            self._reserved[slot] = need_total - need_now
            self._active[slot] = True
            saved = n_shared * self.page_size
            self._tokens_saved += saved
            self._export_gauges_locked()
            return n_shared, saved

    def append(self, slot: int, n: int = 1) -> None:
        """Extend ``slot`` by ``n`` tokens, drawing new pages from its
        reservation at page boundaries. Never raises for admitted sequences
        within their reservation."""
        with self._lock:
            if not self._active[slot]:
                raise ValueError(f"slot {slot} is not active")
            for _ in range(n):
                length = int(self._lengths[slot])
                if length % self.page_size == 0:  # first token of a new page
                    held = int(self._pages_held[slot])
                    if held >= self.max_pages_per_slot:
                        raise OutOfPages(
                            f"slot {slot} exceeded max_pages_per_slot="
                            f"{self.max_pages_per_slot}")
                    if self._reserved[slot] <= 0:
                        raise OutOfPages(
                            f"slot {slot} grew past its reservation")
                    pid = self._take_page_locked()
                    self._refcount[pid] = 1
                    self._tables[slot, held] = pid
                    self._pages_held[slot] += 1
                    self._reserved[slot] -= 1
                self._lengths[slot] = length + 1
            self._export_gauges_locked()

    def truncate(self, slot: int, n: int) -> List[tuple]:
        """Roll ``slot`` back to ``n`` tokens (speculative-decode rejection).

        Pages wholly past the new boundary drop one reference — the exact
        release path of :meth:`free`, so shared pages just lose our alias and
        exclusively-held ones return to the pool (cached tier when still
        indexed). Released pages go back into the slot's *reservation*, so a
        later :meth:`append` re-draws them without new admission — accept /
        reject churn is pool-neutral.

        The new tail page is special: if ``n`` is mid-page the slot will keep
        writing into it, and writing a **shared** page would corrupt every
        other reader — so a shared tail is un-aliased through the COW path
        (a fresh private page is drawn and the caller is told to copy the
        contents). Returns a list of ``(src_pid, dst_pid)`` pairs the caller
        must apply to the device pool before the next write; empty in the
        common all-private case. An indexed-but-exclusive tail is instead
        deregistered from the prefix index (its future contents diverge from
        what the index advertises).

        The un-alias draw is not covered by the admission reservation (shared
        pages were mapped, not reserved), so it can pathologically raise
        :class:`OutOfPages` on an exhausted pool. The engine never hits this:
        speculative rollback floors at the first *generated* token, which is
        always past the shared prompt pages.
        """
        n = int(n)
        copies: List[tuple] = []
        with self._lock:
            if not self._active[slot]:
                raise ValueError(f"slot {slot} is not active")
            length = int(self._lengths[slot])
            if not 1 <= n <= length:
                raise ValueError(
                    f"truncate to {n} outside [1, {length}] for slot {slot}")
            if n == length:
                return copies
            held = int(self._pages_held[slot])
            keep = self.pages_for(n, self.page_size)
            for i in range(keep, held):
                pid = int(self._tables[slot, i])
                self._refcount[pid] -= 1
                if self._refcount[pid] <= 0:
                    self._refcount[pid] = 0
                    if pid in self._page_key:
                        self._cached[pid] = None
                        self._cached.move_to_end(pid)
                    else:
                        self._free.append(pid)
                self._tables[slot, i] = 0
            self._pages_held[slot] = keep
            self._reserved[slot] += held - keep
            if n % self.page_size != 0:
                # the tail page will receive this slot's future writes
                pid = int(self._tables[slot, keep - 1])
                if self._refcount[pid] > 1:
                    dst = self._take_page_locked()
                    self._refcount[dst] = 1
                    self._refcount[pid] -= 1
                    self._tables[slot, keep - 1] = dst
                    copies.append((pid, dst))
                    self.metrics.incr("serving/kv/cow_unaliases")
                elif pid in self._page_key:
                    dg = self._page_key.pop(pid)
                    self._prefix_index.pop(dg, None)
            self._lengths[slot] = n
            self.metrics.incr("serving/kv/truncations")
            self._export_gauges_locked()
            return copies

    def free(self, slot: int) -> None:
        """Retire ``slot``: drop one reference from each held page; pages
        reaching refcount 0 return to the pool — straight to the free list,
        or to the cached tier when the prefix index still knows their
        contents. Idempotent."""
        with self._lock:
            if not self._active[slot]:
                return
            held = int(self._pages_held[slot])
            for i in range(held):
                pid = int(self._tables[slot, i])
                self._refcount[pid] -= 1
                if self._refcount[pid] <= 0:
                    self._refcount[pid] = 0
                    if pid in self._page_key:
                        self._cached[pid] = None
                        self._cached.move_to_end(pid)
                    else:
                        self._free.append(pid)
            self._tables[slot, :] = 0
            self._lengths[slot] = 0
            self._pages_held[slot] = 0
            self._reserved[slot] = 0
            self._active[slot] = False
            self._export_gauges_locked()

    # -- device operands -----------------------------------------------------

    def page_tables(self) -> np.ndarray:
        """``[num_slots, max_pages_per_slot]`` int32 — every entry a valid
        pool index (unassigned entries point at scratch page 0)."""
        with self._lock:
            return self._tables.copy()

    def lengths(self) -> np.ndarray:
        """``[num_slots]`` int32 tokens per slot (0 for inactive)."""
        with self._lock:
            return self._lengths.copy()

    def token_rooms(self) -> np.ndarray:
        """``[num_slots]`` int32 — tokens each slot can still append without
        outgrowing its admission reservation (``(held + reserved) * page_size
        - length``; 0 for inactive slots). The speculative decoder clamps its
        per-slot window to this so mid-burst appends never fail."""
        with self._lock:
            room = ((self._pages_held.astype(np.int64) + self._reserved)
                    * self.page_size - self._lengths)
            return np.where(self._active, room, 0).astype(np.int32)

    def active_slots(self) -> np.ndarray:
        with self._lock:
            return np.flatnonzero(self._active)

    def length(self, slot: int) -> int:
        with self._lock:
            return int(self._lengths[slot])

    def refcounts(self) -> np.ndarray:
        """``[num_pages]`` int32 per-page reference counts (scratch page 0
        is always 0)."""
        with self._lock:
            return self._refcount.copy()

    # -- stats ---------------------------------------------------------------

    def _used_frag_locked(self) -> tuple:
        used = int(np.count_nonzero(self._refcount > 0))
        tokens = int(self._lengths.sum())
        # with sharing, logical tokens can exceed distinct-page capacity,
        # so internal fragmentation clamps at 0
        frag = (max(0.0, 1.0 - tokens / (used * self.page_size))
                if used else 0.0)
        return used, tokens, frag

    def _export_gauges_locked(self) -> None:
        usable = self.num_pages - 1
        used, tokens, frag = self._used_frag_locked()
        occ = used / usable if usable else 0.0
        hit_rate = (self._prefix_hits / self._prefix_lookups
                    if self._prefix_lookups else 0.0)
        self.metrics.gauge("serving/kv/pages_total", usable)
        self.metrics.gauge("serving/kv/pages_used", used)
        self.metrics.gauge("serving/kv/pages_cached", len(self._cached))
        self.metrics.gauge("serving/kv/pages_reserved",
                           int(self._reserved.sum()))
        self.metrics.gauge("serving/kv/occupancy", occ)
        self.metrics.gauge("serving/kv/fragmentation", frag)
        self.metrics.gauge("serving/kv/tokens", tokens)
        self.metrics.gauge("serving/kv/slots_active",
                           int(self._active.sum()))
        # decode-plane summary for fleet dashboards (obs exporters render
        # these as decode_* in Prometheus exposition)
        self.metrics.gauge("decode/occupancy", occ)
        self.metrics.gauge("decode/fragmentation", frag)
        self.metrics.gauge("decode/prefix_hit_rate", hit_rate)
        self.metrics.gauge("decode/tokens_saved", self._tokens_saved)
        # quantized-capacity surface: the dtype exports as its KV_DTYPES
        # index so exposition stays numeric (0=bf16, 1=int8, 2=fp8)
        self.metrics.gauge("serving/kv/dtype_code",
                           quant.KV_DTYPES.index(self.kv_dtype))
        if self.kv_bytes_per_page is not None:
            self.metrics.gauge("serving/kv/bytes_per_page",
                               self.kv_bytes_per_page)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            usable = self.num_pages - 1
            used, tokens, frag = self._used_frag_locked()
            return {
                "page_size": self.page_size,
                "pages_total": usable,
                "pages_used": used,
                # evictable supply: plain free pages + cached prefix pages
                "pages_free": len(self._free) + len(self._cached),
                "pages_cached": len(self._cached),
                "pages_reserved": int(self._reserved.sum()),
                "occupancy": used / usable if usable else 0.0,
                "fragmentation": frag,
                "tokens": tokens,
                "slots_active": int(self._active.sum()),
                "num_slots": self.num_slots,
                "prefix_lookups": self._prefix_lookups,
                "prefix_hits": self._prefix_hits,
                "prefix_hit_rate": (self._prefix_hits / self._prefix_lookups
                                    if self._prefix_lookups else 0.0),
                "prefix_blocks_indexed": len(self._prefix_index),
                "tokens_saved": self._tokens_saved,
                "kv_dtype": self.kv_dtype,
                "kv_bytes_per_page": self.kv_bytes_per_page,
            }
