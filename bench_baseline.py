"""Measure the reference-equivalent baseline: single-node Hogwild-style CNN
training throughput on CPU.

The reference (TF 1.10 + Spark 2.4.3) is not installable in this image; two
CPU proxies of its training loop are measured and committed:

1. **TF1-session proxy** (primary — ``measure_tf1``): live ``tf.compat.v1``
   graph + Session, reproducing the reference's ACTUAL cost profile
   (``sparkflow/HogwildSparkModel.py:38-100``, ``ml_util.py:9-28``):

   - worker: full-weight pickle round-trip (the ``GET /parameters`` wire
     work), ``tensorflow_set_weights``-style weight install — fresh
     placeholders + assign ops built on EVERY call (the reference grows its
     graph per batch) — then ONE ``sess.run`` PER TRAINABLE VARIABLE for
     the gradients (``grads[x][0].eval`` in a Python loop: each run re-executes
     the forward), then a full-gradient pickle round-trip (``POST /update``).
   - server: ``apply_gradients`` train_op run with the fed gradients + a
     ``tensorflow_get_weights`` fetch of every variable, per batch
     (``HogwildSparkModel.py:219-240``).
   - loopback HTTP latency excluded, which only favors the baseline.

2. **torch proxy** (kept for continuity with rounds 1-4 — ``measure``):
   same CNN/optimizer/batch with a SINGLE fused backward per batch + the
   pickle wire work. It UNDERSTATES the reference's per-variable-run cost,
   so it is the conservative number.

``vs_baseline`` in bench.py uses the committed torch number (conservative);
the TF number documents the realistic gap. Run once, committed.
"""

import json
import pickle
import time

import numpy as np
import torch
import torch.nn as tnn
import torch.nn.functional as F

torch.manual_seed(0)
torch.set_num_threads(1)  # reference guidance: --executor cores 1 (README.md:209-213)


class RefCNN(tnn.Module):
    """The cnn_example.py model (examples/cnn_example.py:10-22 in reference)."""

    def __init__(self):
        super().__init__()
        self.c1 = tnn.Conv2d(1, 32, 5)
        self.c2 = tnn.Conv2d(32, 64, 3)
        self.fc = tnn.Linear(64 * 5 * 5, 10)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.c1(x)), 2)
        x = F.max_pool2d(F.relu(self.c2(x)), 2)
        return self.fc(torch.flatten(x, 1))


def measure(batch_size=300, n_batches=12):
    model = RefCNN()
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)
    rs = np.random.RandomState(0)
    x = torch.tensor(rs.rand(batch_size, 1, 28, 28), dtype=torch.float32)
    y = torch.tensor(rs.randint(0, 10, batch_size), dtype=torch.long)

    # warmup
    for _ in range(2):
        opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        opt.step()

    t0 = time.perf_counter()
    for _ in range(n_batches):
        # per-batch PS wire work the reference pays (weights down, grads up)
        weights = [p.detach().numpy() for p in model.parameters()]
        _ = pickle.loads(pickle.dumps(weights, -1))
        opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        grads = [p.grad.detach().numpy() for p in model.parameters()]
        _ = pickle.loads(pickle.dumps(grads, -1))
        opt.step()
    wall = time.perf_counter() - t0
    return batch_size * n_batches / wall


def measure_tf1(batch_size=300, n_batches=12):
    """The reference's real per-batch work on live TF1 sessions (see module
    docstring). Worker and server sessions share the process; the wire work
    between them is the pickle both sides paid."""
    import os

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    os.environ.setdefault("OMP_NUM_THREADS", "1")
    import tensorflow as tf

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()

    def build(g):
        """The cnn_example model in raw TF1 ops (tf1.layers is gone under
        Keras 3; explicit get_variable + nn ops build the same network)."""
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 784], name="x")
            y = tf1.placeholder(tf.float32, [None, 10], name="y")
            xr = tf.reshape(x, [-1, 28, 28, 1])
            init = tf1.glorot_uniform_initializer(seed=0)

            def conv(inp, cin, cout, k, name):
                w = tf1.get_variable(f"{name}_w", [k, k, cin, cout],
                                     initializer=init)
                b = tf1.get_variable(f"{name}_b", [cout],
                                     initializer=tf1.zeros_initializer())
                c = tf.nn.relu(tf.nn.bias_add(
                    tf1.nn.conv2d(inp, w, [1, 1, 1, 1], "VALID"), b))
                return tf1.nn.max_pool(c, [1, 2, 2, 1], [1, 2, 2, 1], "VALID")

            h = conv(xr, 1, 32, 5, "c1")
            h = conv(h, 32, 64, 3, "c2")
            flat = tf.reshape(h, [-1, 64 * 5 * 5])
            wd = tf1.get_variable("fc_w", [64 * 5 * 5, 10], initializer=init)
            bd = tf1.get_variable("fc_b", [10],
                                  initializer=tf1.zeros_initializer())
            logits = tf1.nn.xw_plus_b(flat, wd, bd)
            loss = tf.reduce_mean(
                tf.nn.softmax_cross_entropy_with_logits(
                    labels=tf.stop_gradient(y), logits=logits))
            return x, y, loss

    rs = np.random.RandomState(0)
    xb = rs.rand(batch_size, 784).astype(np.float32)
    yb = np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch_size)]

    # worker graph/session: per-variable gradient fetches
    wg = tf1.Graph()
    x, y, loss = build(wg)
    with wg.as_default():
        wvars = tf1.trainable_variables()
        wgrads = tf1.gradients(loss, wvars)
        winit = tf1.global_variables_initializer()
    wsess = tf1.Session(graph=wg,
                        config=tf1.ConfigProto(intra_op_parallelism_threads=1,
                                               inter_op_parallelism_threads=1))
    wsess.run(winit)

    # server graph/session: apply_gradients on FED gradient values
    sg = tf1.Graph()
    _, _, sloss = build(sg)
    with sg.as_default():
        svars = tf1.trainable_variables()
        sgrads = tf1.gradients(sloss, svars)
        train_op = tf1.train.AdamOptimizer(1e-4).apply_gradients(
            list(zip(sgrads, svars)))
        sinit = tf1.global_variables_initializer()
    ssess = tf1.Session(graph=sg,
                        config=tf1.ConfigProto(intra_op_parallelism_threads=1,
                                               inter_op_parallelism_threads=1))
    ssess.run(sinit)
    weights = ssess.run(svars)  # tensorflow_get_weights

    def set_weights(values):
        # tensorflow_set_weights (ml_util.py:16-28): NEW placeholders and
        # assign ops every call — the graph grows per batch, as shipped
        with wg.as_default():
            ops, feed = [], {}
            for var, value in zip(wvars, values):
                ph = tf1.placeholder(var.dtype, shape=value.shape)
                ops.append(var.assign(ph))
                feed[ph] = value
            wsess.run(ops, feed_dict=feed)

    # warmup (compile kernels both sides)
    set_weights(weights)
    _ = [wsess.run(g, {x: xb, y: yb}) for g in wgrads]
    ssess.run(train_op, dict(zip(sgrads, _)))

    t0 = time.perf_counter()
    for _i in range(n_batches):
        served = pickle.loads(pickle.dumps(weights, -1))  # GET /parameters
        set_weights(served)
        gradients = []
        for g in wgrads:  # one sess.run PER VARIABLE (grads[x][0].eval)
            gradients.append(wsess.run(g, {x: xb, y: yb}))
        sent = pickle.loads(pickle.dumps(gradients, -1))  # POST /update
        ssess.run(train_op, feed_dict=dict(zip(sgrads, sent)))
        weights = ssess.run(svars)  # tensorflow_get_weights, per update
    wall = time.perf_counter() - t0
    return batch_size * n_batches / wall


if __name__ == "__main__":
    import os

    eps = round(measure(), 1)
    try:
        tf_eps = round(measure_tf1(), 1)
        tf_how = ("tf.compat.v1 Session proxy of the reference loop: fresh "
                  "assign ops per weight install, ONE sess.run per variable "
                  "for gradients, adam apply_gradients + full weight fetch "
                  "on the server side, pickle wire both ways (batch 300, "
                  "single-thread, loopback HTTP excluded)")
    except Exception as e:  # TF missing/broken: keep the torch number
        tf_eps, tf_how = None, f"tf1 proxy unavailable: {e}"

    # BEST-OF-RUNS, favoring the baseline: merge with the committed file so
    # a re-run on a loaded machine can only RAISE the denominator bench.py
    # divides by (reported speedups stay a floor), never lower it
    path = "BASELINE_MEASURED.json"
    prev = {}
    if os.path.exists(path):
        with open(path) as f:
            prev = json.load(f)
    best = max(eps, prev.get("baseline_examples_per_sec") or 0)
    best_tf = max(tf_eps or 0,
                  prev.get("baseline_tf1_examples_per_sec") or 0) or None
    if tf_eps is None and prev.get("baseline_tf1_examples_per_sec"):
        # this run could not measure TF1 but a committed number exists:
        # carry its provenance forward, don't relabel it with the error
        tf_how = prev.get("how_tf1", tf_how)
    out = {
        "metric": "mnist_cnn_examples_per_sec",
        "baseline_examples_per_sec": best,
        "how": "torch-CPU single-thread proxy of the reference Hogwild loop "
               "(same CNN, adam, batch 300, full pickle weight+grad round-trip "
               "per batch; loopback HTTP latency excluded). CONSERVATIVE: one "
               "fused backward per batch vs the reference's per-variable "
               "sess.runs — see baseline_tf1_examples_per_sec for the "
               "faithful TF-session number. Best-of-runs kept across "
               f"re-measurements (this run: {eps})",
        "baseline_tf1_examples_per_sec": best_tf,
        "how_tf1": tf_how + (f". Best-of-runs kept (this run: {tf_eps})"
                             if tf_eps else ""),
        "policy": "vs_baseline divides by baseline_examples_per_sec (torch, "
                  "best-of-runs) — the highest defensible reference-"
                  "equivalent number, so the reported speedup is a floor",
    }
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
