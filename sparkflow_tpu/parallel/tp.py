"""Tensor-parallel / FSDP sharded training via GSPMD.

Models expose ``param_pspecs()`` (megatron rules for transformers); placing
params with those shardings and jitting the standard step lets XLA partition
every matmul over ``tp`` and insert the all-reduces on ICI. ``fsdp_pspecs``
derives ZeRO-style parameter sharding for any model (shard the largest axis of
every big tensor over ``fsdp``); optimizer state inherits placement from params
because ``optax.init`` is a pure tree op.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import _step_body, make_loss_fn


def filter_pspec(spec: P, mesh: Mesh) -> P:
    """Drop axes the mesh doesn't have (so e.g. megatron 'tp' rules place
    cleanly on an {'ep'}-only or {'dp'}-only mesh as replicated)."""
    def keep(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in mesh.axis_names)
            return kept if kept else None
        return a if a in mesh.axis_names else None

    return P(*(keep(a) for a in spec))


def shard_params(params, mesh: Mesh, pspecs):
    """Place a params pytree onto the mesh per a PartitionSpec pytree; spec
    axes absent from the mesh degrade to replication."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, filter_pspec(s, mesh))),
        params, pspecs,
        is_leaf=lambda x: not isinstance(x, dict))


def fsdp_pspecs(param_specs, axis: str = "fsdp", min_size: int = 2 ** 16):
    """ZeRO-style specs from a model's ``param_specs()``: big tensors shard
    their largest dim over ``axis``; small ones replicate."""
    out = {}
    for lname, pspec in param_specs.items():
        layer = {}
        for pname, (shape, _init) in pspec.items():
            if int(np.prod(shape)) >= min_size and len(shape) >= 1:
                big = int(np.argmax(shape))
                spec = [None] * len(shape)
                spec[big] = axis
                layer[pname] = P(*spec)
            else:
                layer[pname] = P()
        out[lname] = layer
    return out


def make_sharded_train_step(model, optimizer, mesh: Mesh, input_name: str,
                            label_name: Optional[str], dp_axis: str = "dp"):
    """Jitted train step where params carry their own (tp/fsdp) shardings and
    the batch shards over ``dp_axis``. Use together with :func:`shard_params`:

        params = shard_params(model.init(rng), mesh, model.param_pspecs())
        opt_state = optimizer.init(params)           # inherits placement
        step = make_sharded_train_step(model, optimizer, mesh, 'input_ids', 'y')
        params, opt_state, loss = step(params, opt_state, x, y, mask, rng)
    """
    loss_fn = make_loss_fn(model, input_name, label_name)
    from ..core import _sharded_trace_guard
    step = _sharded_trace_guard(_step_body(loss_fn, optimizer), mesh,
                                batch_axis=dp_axis)
    data = NamedSharding(mesh, P(dp_axis))
    repl = NamedSharding(mesh, P())
    return jax.jit(step,
                   in_shardings=(None, None, data, data, data, repl),
                   donate_argnums=(0, 1))


def derive_param_pspecs(model, mesh: Mesh):
    """Parameter PartitionSpecs for training ``model`` on ``mesh``.

    - mesh has ``tp``/``ep`` and the model publishes megatron-style rules
      (``param_pspecs``, transformer/resnet/moe families) -> those rules
      (axes absent from the mesh degrade to replication via
      :func:`filter_pspec` inside :func:`shard_params`);
    - mesh has ``fsdp`` -> ZeRO-style :func:`fsdp_pspecs` derived from the
      model's ``param_specs()`` — works for ANY model incl. the ``nn``-DSL
      graphs (largest dim of every big tensor shards, small ones replicate);
    - otherwise (pure dp) -> ``None``: replicate params, shard the batch.
    """
    has_tp = any(a in mesh.axis_names for a in ("tp", "ep"))
    has_fsdp = "fsdp" in mesh.axis_names
    if has_tp and has_fsdp:
        # auto-composing megatron rules WITH ZeRO sharding needs per-tensor
        # axis assignments no heuristic can guess; refusing beats silently
        # replicating one of the two requested shardings
        raise ValueError(
            "combined tp/ep + fsdp sharding cannot be auto-derived; pass an "
            "explicit PartitionSpec pytree (Trainer(param_sharding=...)) or "
            "drop one of the axes")
    if has_tp and hasattr(model, "param_pspecs"):
        return model.param_pspecs()
    if has_fsdp and hasattr(model, "param_specs"):
        return fsdp_pspecs(model.param_specs(), axis="fsdp")
    return None
