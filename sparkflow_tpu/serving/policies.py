# graftcheck: pure-policy
"""Pure fleet policies: every routing/health/gate *decision*, no transport.

The fleet-scale simulator (:mod:`sparkflow_tpu.sim`) replays million-request
traces against the SAME policy code the live router runs — which is only
sound if the policies are deterministic functions of observed state. This
module is that contract, enforced by graftcheck rule **GC-S501**
(impure-policy): nothing here may read a wall clock, draw randomness, sleep,
or touch sockets/files. Time arrives as a ``now`` argument; randomness
arrives pre-drawn (``prefer_canary`` is a bool the caller rolled); state
arrives as frozen snapshots (:class:`ReplicaView`, :class:`VersionStats`).

The serving plane (``membership.py`` / ``router.py``) and the simulator
(``sim/core.py``) both call these functions — the HTTP stack supplies
``time.monotonic`` snapshots and live counters, the simulator supplies a
virtual clock and modelled replicas, and the decisions are identical by
construction (pinned by the parity tests in ``tests/test_policies.py``).

Decisions covered
-----------------
- :func:`pick_order` / :func:`predict_pick_key` / :func:`generate_pick_key`
  — least-loaded replica ranking, with the least-served tie-break
  (equal-load ties go to the replica with the fewest cumulative dispatches
  instead of always the lowest index — the bias the deterministic replay
  exposed) and the **inflight-debited byte-headroom** generate rule that
  predicts KV exhaustion from stale probe reports before the replica
  sheds (found in sim, confirmed by ``bench.py --sim``).
- :func:`classify_outcome` — what one dispatch outcome means: success,
  eject-and-reroute (draining), reroute-without-breaker (overload),
  breaker-feeding failure (5xx/wire error), or authoritative client error.
- :func:`canary_gate` / :func:`canary_reorder` — the promote/rollback/
  continue verdict over per-version stats and the version-aware reorder of
  a load-sorted candidate list.
- :func:`token_bucket_admit` — the admission refill/spend arithmetic.
- :func:`probe_is_stale` — whether a replica's load report is too old to
  trust (its decision half lives here; reading the clock stays the
  caller's job).
- :func:`scale_decision` / :func:`scale_down_order` — the elastic-fleet
  control law: crash replacement first, then hysteresis-banded scale
  up/down with cooldowns and min/max bounds. The live
  :class:`~sparkflow_tpu.serving.autoscaler.Autoscaler` and the
  simulator's ``SimAutoscaler`` hook run the SAME function, so the
  policy is tuned against deterministic traffic steps before it ever
  spawns a real process.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ReplicaView", "VersionStats", "OUTCOME_SUCCESS", "OUTCOME_EJECT",
    "OUTCOME_REROUTE", "OUTCOME_FAILURE", "OUTCOME_CLIENT_ERROR",
    "GATE_CONTINUE", "GATE_PROMOTE", "GATE_ROLLBACK",
    "predict_pick_key", "generate_pick_key", "pick_order",
    "classify_outcome", "canary_gate", "canary_reorder",
    "token_bucket_admit", "probe_is_stale", "percentile_nearest_rank",
    "ScaleTargets", "AutoscalerState", "ScaleAction",
    "SCALE_HOLD", "SCALE_UP", "SCALE_DOWN", "SCALE_REPLACE",
    "scale_decision", "scale_down_order",
]


@dataclass(frozen=True)
class ReplicaView:
    """Frozen snapshot of one replica's observed state — the ONLY replica
    shape policies see. ``Membership`` builds these under its lock from
    live :class:`~sparkflow_tpu.serving.membership.Replica` records; the
    simulator builds them from modelled replicas."""

    index: int
    healthy: bool = True
    inflight: int = 0
    queue_depth: int = 0
    decode_free_slots: int = -1
    decode_pages_free: int = -1
    kv_bytes_per_page: int = -1
    version: int = -1
    dispatched: int = 0  # cumulative dispatches ever sent to this replica
    # consecutive failed health probes (0 while probes pass). The scaling
    # policy declares a replica dead only past ScaleTargets.dead_after_misses
    # — a single miss is most likely the replica saturated, not gone.
    # Definitive death evidence (exit-code reap, breaker OPEN) is overlaid
    # by the autoscaler as misses >= the threshold.
    probe_misses: int = 0
    # does a supervisor own this replica's process? Unmanaged (founding-
    # fleet) replicas can be routed around but never destroyed, drained,
    # or deregistered by the autoscaler — there is no process handle to
    # respawn, and a transient probe failure must not permanently evict
    # a replica that would re-admit on recovery.
    managed: bool = True

    @property
    def free_kv_bytes(self) -> int:
        """Effective decode byte headroom: pages_free weighted by the
        replica's bytes-per-page (unknown byte figure weights 1, so a fleet
        that never reports bytes ranks by raw pages exactly as before)."""
        if self.decode_pages_free <= 0:
            return self.decode_pages_free
        bpp = self.kv_bytes_per_page if self.kv_bytes_per_page > 0 else 1
        return self.decode_pages_free * bpp


def predict_pick_key(view: ReplicaView) -> Tuple:
    """Sort key for predict dispatch: router-side in-flight, then the
    replica-reported queue depth, then the **least-served** tie-break
    (cumulative dispatches, then index).

    The old tie-break was the bare index: an idle or perfectly balanced
    fleet sent EVERY tied pick to replica 0 — deterministic replay in the
    simulator showed replica 0 absorbing the whole head of each burst
    while the tail idled. Tie-breaking on the cumulative dispatch count is
    self-balancing (the tied replica that has served least wins, and
    serving bumps its count past its peers), deterministic, and — unlike a
    rotating counter — a pure function of the view, so an incremental
    argmin structure (the simulator's lazy heap) only re-keys the one
    replica that changed."""
    return (view.inflight, view.queue_depth, view.dispatched, view.index)


# Pages one live stream is assumed to consume beyond the last probe
# report (the debit below). 32 pages x 16-token pages = a ~512-token
# prompt+completion — the workload median, not the tail; the debit is a
# steering signal, the replica's own admission is the hard limit.
EST_PAGES_PER_STREAM = 32


def generate_pick_key(view: ReplicaView,
                      est_pages_per_stream: int = EST_PAGES_PER_STREAM
                      ) -> Tuple:
    """Sort key for generate (decode) dispatch: least-loaded with
    **inflight-debited byte headroom**.

    Ranks by (starved, inflight, -effective-free-bytes, least-served
    tie) — queue depth is deliberately NOT a generate signal (the decode
    plane's own slot/page figures say more than the predict-plane queue)
    — where the effective headroom debits the *stale* probe report by
    the router's *live* in-flight count:

    ``eff_pages = decode_pages_free - est_pages_per_stream * inflight``

    - ``starved``: zero free pages or slots — or an effective headroom
      debited to <= 0 — sorts last outright (still dispatchable as a
      final resort: the replica's own 503 is the real backpressure).
    - The probe report is up to a probe interval old; every dispatch the
      router sent since then is eating pages the report still shows as
      free. Deterministic trace replay in the simulator showed the
      undebited rule happily piling bursts onto replicas whose pools had
      already paged out, then paying a queue_full reroute storm per
      burst; the debit predicts exhaustion *before* the replica sheds
      (sim: fewer queue_full reroutes and 30-70% lower p95 across
      homogeneous and mixed-pool fleets; confirmed real by
      ``bench.py --sim``).
    - ``-eff_bytes`` (debited pages weighted by the replica's
      ``kv_bytes_per_page``) breaks equal-inflight ties toward the pool
      with the most remaining capacity, so heterogeneous bf16/int8
      fleets fill proportionally.
    - Replicas with unknown headroom (no decode plane probed yet) keep
      their raw figure as the tie value — after known-positive headroom
      at equal load, exactly as before.
    """
    starved = 1 if (view.decode_pages_free == 0
                    or view.decode_free_slots == 0) else 0
    pages = view.decode_pages_free
    if pages > 0:
        eff = pages - est_pages_per_stream * view.inflight
        if eff <= 0:
            starved = 1
        bpp = (view.kv_bytes_per_page if view.kv_bytes_per_page > 0
               else 1)
        eff_bytes = eff * bpp
    else:
        eff_bytes = pages   # unknown (-1) / zero: passthrough, as before
    return (starved, view.inflight, -eff_bytes, view.dispatched,
            view.index)


def pick_order(views: Sequence[ReplicaView], signal: str = "predict"
               ) -> List[int]:
    """Full dispatch preference order (healthy views only) as a list of
    ``view.index`` values, best first. The caller walks it until a breaker
    admits one — breaker state is live/mutable, so consulting it stays
    outside the pure layer."""
    key = generate_pick_key if signal == "generate" else predict_pick_key
    return [v.index for v in sorted((v for v in views if v.healthy),
                                    key=key)]


# -- dispatch-outcome classification -----------------------------------------

OUTCOME_SUCCESS = "success"            # 200: record_success
OUTCOME_EJECT = "eject"                # draining 503: eject now, reroute
OUTCOME_REROUTE = "reroute"            # overload 503: reroute, no breaker
OUTCOME_FAILURE = "failure"            # 5xx / wire error: feed the breaker
OUTCOME_CLIENT_ERROR = "client_error"  # 4xx: authoritative, pass through


def classify_outcome(status: Optional[int], error_code: str = "",
                     wire_error: bool = False) -> str:
    """What one dispatch outcome means for membership/retry bookkeeping.

    ``status`` is the HTTP status (None with ``wire_error=True`` for a
    connection-level failure), ``error_code`` the structured error code
    from the body. The verdicts map 1:1 onto the router's historical
    behavior: draining 503s eject immediately; queue_full 503s reroute
    without feeding the breaker (overloaded, not broken — least-loaded
    pick already steers away); other 5xx and wire errors count against
    the breaker; 4xx is the client's problem."""
    if wire_error:
        return OUTCOME_FAILURE
    if status == 200:
        return OUTCOME_SUCCESS
    if status == 503 and error_code == "draining":
        return OUTCOME_EJECT
    if status == 503:
        return OUTCOME_REROUTE
    if status is None or status >= 500:
        return OUTCOME_FAILURE
    return OUTCOME_CLIENT_ERROR


# -- canary gate -------------------------------------------------------------

GATE_CONTINUE = "continue"
GATE_PROMOTE = "promote"
GATE_ROLLBACK = "rollback"


@dataclass(frozen=True)
class VersionStats:
    """Per-version outcome counters the canary gate judges over."""

    requests: int = 0
    errors: int = 0
    nans: int = 0
    latencies_ms: Tuple[float, ...] = field(default_factory=tuple)

    @property
    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    @property
    def latency_p95(self) -> float:
        return percentile_nearest_rank(self.latencies_ms, 95.0)


def percentile_nearest_rank(samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile matching the canary gate's historical p95
    (``sorted[min(n-1, round(q/100 * (n-1)))]``); 0.0 on no samples."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))]


def canary_gate(canary: VersionStats, incumbent: Optional[VersionStats], *,
                min_requests: int, error_rate_margin: float,
                latency_factor: float, latency_floor_ms: float
                ) -> Tuple[str, str]:
    """Judge a canary version against the incumbent: ``(verdict, reason)``
    where verdict is GATE_CONTINUE / GATE_PROMOTE / GATE_ROLLBACK.

    The order of checks is the contract (pinned by the parity tests):
    any NaN/Inf rolls back instantly; before ``min_requests`` the trial
    continues; an error rate exceeding the incumbent's by more than
    ``error_rate_margin`` rolls back; a latency p95 above
    ``max(latency_floor_ms, latency_factor x incumbent p95)`` rolls back
    (skipped while the incumbent has no latency history); otherwise the
    canary promotes."""
    if canary.nans:
        return GATE_ROLLBACK, "NaN/Inf outputs"
    if canary.requests < min_requests:
        return GATE_CONTINUE, (f"{canary.requests}/{min_requests} "
                               f"requests observed")
    inc_err = incumbent.error_rate if incumbent is not None else 0.0
    err = canary.error_rate
    if err > inc_err + error_rate_margin:
        return GATE_ROLLBACK, (f"error rate {err:.3f} vs incumbent "
                               f"{inc_err:.3f}")
    inc_p95 = incumbent.latency_p95 if incumbent is not None else 0.0
    if inc_p95 > 0.0:
        p95 = canary.latency_p95
        bar = max(latency_floor_ms, latency_factor * inc_p95)
        if p95 > bar:
            return GATE_ROLLBACK, f"latency p95 {p95:.1f}ms > {bar:.1f}ms"
    return GATE_PROMOTE, "healthy at min_requests"


def canary_reorder(indices: Sequence[int], versions: Dict[int, int],
                   canary: Optional[int], quarantined: frozenset,
                   prefer_canary: bool) -> List[int]:
    """Version-aware reorder of a load-sorted candidate list (indices into
    the fleet, best first). Quarantined versions are dropped outright —
    zero post-gate traffic, an all-quarantined fleet yields ``[]`` and the
    router 503s rather than serve bad weights. With a canary under trial,
    ``prefer_canary`` (the caller's pre-drawn ~``canary_fraction`` coin)
    puts the canary group first, else last; relative load order inside
    each group is preserved."""
    live = [i for i in indices if versions.get(i, -1) not in quarantined]
    if canary is None:
        return live
    cgroup = [i for i in live if versions.get(i, -1) == canary]
    rest = [i for i in live if versions.get(i, -1) != canary]
    if not cgroup or not rest:
        return live
    return cgroup + rest if prefer_canary else rest + cgroup


# -- admission ---------------------------------------------------------------

def token_bucket_admit(tokens: float, last: float, now: float, *,
                       rate: float, burst: float, n: float = 1.0
                       ) -> Tuple[bool, float, float]:
    """One token-bucket admission decision: refill from ``last`` to ``now``
    at ``rate`` (capped at ``burst``), spend ``n`` if available. Returns
    ``(admitted, tokens_after, now)`` — the caller stores the last two as
    the bucket's new state under its own lock."""
    tokens = min(burst, tokens + (now - last) * rate)
    if tokens >= n:
        return True, tokens - n, now
    return False, tokens, now


# -- probe staleness ---------------------------------------------------------

def probe_is_stale(last_probe_t: float, now: float,
                   probe_interval_s: float, factor: float = 3.0) -> bool:
    """Is a replica's probed load report too old to trust? True once the
    report is older than ``factor`` probe intervals (a wedged prober must
    not freeze stale 'idle' load figures into the pick forever). A replica
    never probed (``last_probe_t <= 0``) is not stale — optimistic until
    the first report, matching the historical bootstrap behavior."""
    if last_probe_t <= 0.0:
        return False
    return (now - last_probe_t) > factor * probe_interval_s


# -- elastic scaling ----------------------------------------------------------

SCALE_HOLD = "hold"        # inside the band / cooling down: do nothing
SCALE_UP = "up"            # queue wait above the high band: add replicas
SCALE_DOWN = "down"        # queue wait below the low band: drain replicas
SCALE_REPLACE = "replace"  # a replica died: respawn it, bypassing cooldowns


@dataclass(frozen=True)
class ScaleTargets:
    """The autoscaler's tuning knobs — the full control law is a function
    of these plus the observed fleet, so an A/B in the simulator is just
    two ``ScaleTargets`` values replayed over the same trace.

    The hysteresis band ``(queue_wait_low_ms, queue_wait_high_ms)`` is the
    do-nothing region: scale up only above the high edge, down only below
    the low edge. A single threshold oscillates — the capacity added at
    the threshold drops queue wait just below it, which immediately votes
    to scale down again; the band plus per-direction cooldowns is the
    classic damping."""

    min_replicas: int = 1
    max_replicas: int = 8
    queue_wait_high_ms: float = 200.0   # above: under-provisioned
    queue_wait_low_ms: float = 50.0     # below: over-provisioned
    up_cooldown_s: float = 10.0         # min gap between scale-ups
    down_cooldown_s: float = 60.0       # min gap between scale-downs
    max_step_up: int = 2                # replicas added per decision, cap
    starved_fraction_up: float = 0.5    # fleet starvation scale-up trigger
    # consecutive probe misses before an unhealthy view counts as DEAD
    # (replace/refill) rather than SUSPECT (hold). Probe timeouts are most
    # likely exactly when the replica is saturated, so acting on a single
    # miss turns the autoscaler into a load-correlated failure amplifier —
    # it would kill capacity during the overload that made the probe slow.
    dead_after_misses: int = 3


@dataclass(frozen=True)
class AutoscalerState:
    """What the control law remembers between decisions: the current
    desired size and when it last moved in each direction (cooldowns are
    judged against these, so a replacement — which doesn't change
    ``desired`` — never resets them)."""

    desired: int = 1
    last_up_t: float = float("-inf")
    last_down_t: float = float("-inf")


@dataclass(frozen=True)
class ScaleAction:
    """One decision: ``kind`` is SCALE_HOLD/UP/DOWN/REPLACE, ``count`` how
    many replicas to add (up/replace) or drain (down), ``targets`` the
    view indices to act on (dead indices for replace, drain order for
    down, empty for up — the supervisor picks ports), ``state`` the
    successor :class:`AutoscalerState`, ``reason`` a human-readable why."""

    kind: str
    count: int = 0
    targets: Tuple[int, ...] = ()
    state: "AutoscalerState" = field(default_factory=lambda: AutoscalerState())
    reason: str = ""


def scale_down_order(views: Sequence[ReplicaView]) -> List[int]:
    """Drain preference order for scale-down, best victim first: the
    replica with zero in-flight generate slots drains free, a busy decode
    replica drains last (its streams must finish before the process can
    exit, holding the scale-down open). Ranks by (inflight, queue_depth,
    -index) — the index tie-break prefers the HIGHEST index so a fleet
    that scaled 0..n-1 up shrinks from the top, keeping the stable core
    at low indices (and keeping the order deterministic for replay)."""
    return [v.index for v in
            sorted(views, key=lambda v: (v.inflight, v.queue_depth,
                                         -v.index))]


def scale_decision(views: Sequence[ReplicaView], targets: ScaleTargets,
                   state: AutoscalerState, now: float, *,
                   queue_wait_p95_ms: Optional[float] = None) -> ScaleAction:
    """One tick of the elastic-fleet control law. Priority order is the
    contract (pinned by the fake-clock units in ``tests/test_autoscaler.py``):

    1. **Crash replacement** — DEAD managed views are respawned
       immediately, bypassing both cooldowns and (if the fleet is at max)
       the size check: a replacement restores capacity the fleet already
       decided it needs, it is not growth. Dead means *debounced* dead:
       ``probe_misses >= targets.dead_after_misses`` (the autoscaler
       overlays definitive evidence — exit-code reap, breaker OPEN — as
       misses past the threshold). An unhealthy view below the threshold
       is a SUSPECT: it still counts as capacity and nothing is killed —
       a probe timeout is most likely the replica saturated, and killing
       it would amplify the very overload that slowed the probe.
       Unmanaged views are NEVER replace targets (no process handle to
       respawn; a recovered probe re-admits them); one past the threshold
       simply stops counting as capacity, so the below-min rule refills
       the fleet with fresh managed replicas around it.
    2. **Below-min catch-up** — fewer presumed-alive replicas (healthy +
       suspects) than ``min_replicas`` scales up without cooldown (the
       floor is a hard bound, not a preference).
    3. **Scale up** — queue-wait p95 above the high band edge, or a
       ``starved_fraction_up`` share of the live fleet starved (zero free
       decode slots/pages), adds ``ceil``-style capacity: one replica per
       full band-multiple of overshoot, capped at ``max_step_up`` and
       ``max_replicas``, gated on ``up_cooldown_s``.
    4. **Scale down** — queue-wait p95 below the low band edge (and no
       starvation) drains ONE replica per decision — the
       :func:`scale_down_order` victim among MANAGED live views (an
       unmanaged replica cannot be drained, and electing one would burn
       the down-cooldown on a no-op) — gated on ``down_cooldown_s`` since
       the last move in EITHER direction (shrinking right after growing
       is the oscillation the band exists to prevent), floored at
       ``min_replicas``.
    5. **Hold** otherwise.

    ``queue_wait_p95_ms`` is None when the histogram has no samples yet
    (idle fleet): treated as 0 for the down path so an idle oversized
    fleet does shrink, and as no-signal for the up path."""
    threshold = max(1, targets.dead_after_misses)
    live = [v for v in views if v.healthy]
    dead = tuple(v.index for v in views
                 if v.managed and not v.healthy
                 and v.probe_misses >= threshold)
    # unhealthy but under the miss threshold (either ownership): presumed
    # returning, counts as capacity, never acted on this tick
    suspects = [v for v in views
                if not v.healthy and v.probe_misses < threshold]
    fleet = len(live) + len(suspects)

    if dead:
        return ScaleAction(SCALE_REPLACE, count=len(dead), targets=dead,
                           state=state,
                           reason=f"{len(dead)} replica(s) down")

    if fleet < targets.min_replicas:
        n = targets.min_replicas - fleet
        return ScaleAction(
            SCALE_UP, count=n,
            state=AutoscalerState(desired=fleet + n,
                                  last_up_t=now,
                                  last_down_t=state.last_down_t),
            reason=f"below min_replicas ({fleet} < "
                   f"{targets.min_replicas})")

    starved = sum(1 for v in live
                  if v.decode_free_slots == 0 or v.decode_pages_free == 0)
    fleet_starved = (len(live) > 0 and
                     starved >= targets.starved_fraction_up * len(live))
    wait = queue_wait_p95_ms
    overloaded = (wait is not None and wait > targets.queue_wait_high_ms)

    if (overloaded or fleet_starved) and fleet < targets.max_replicas:
        if now - state.last_up_t < targets.up_cooldown_s:
            return ScaleAction(SCALE_HOLD, state=state,
                               reason="up-cooldown")
        if overloaded:
            # one replica per full band-width of overshoot: a 2x step in
            # queue wait asks for proportionally more capacity than a 5%
            # drift over the edge, without a model of service rate
            band = max(targets.queue_wait_high_ms, 1e-9)
            step = 1 + int((wait - targets.queue_wait_high_ms) / band)
        else:
            step = 1
        step = min(step, targets.max_step_up,
                   targets.max_replicas - fleet)
        why = (f"queue wait p95 {wait:.0f}ms > "
               f"{targets.queue_wait_high_ms:.0f}ms" if overloaded
               else f"{starved}/{len(live)} replicas starved")
        return ScaleAction(
            SCALE_UP, count=step,
            state=AutoscalerState(desired=fleet + step,
                                  last_up_t=now,
                                  last_down_t=state.last_down_t),
            reason=why)

    idle_wait = wait if wait is not None else 0.0
    candidates = [v for v in live if v.managed]
    if (idle_wait < targets.queue_wait_low_ms and not fleet_starved
            and len(live) > targets.min_replicas and candidates):
        ref = max(state.last_down_t, state.last_up_t)
        if now - ref < targets.down_cooldown_s:
            return ScaleAction(SCALE_HOLD, state=state,
                               reason="down-cooldown")
        victim = scale_down_order(candidates)[0]
        return ScaleAction(
            SCALE_DOWN, count=1, targets=(victim,),
            state=AutoscalerState(desired=len(live) - 1,
                                  last_up_t=state.last_up_t,
                                  last_down_t=now),
            reason=f"queue wait p95 {idle_wait:.0f}ms < "
                   f"{targets.queue_wait_low_ms:.0f}ms")

    return ScaleAction(SCALE_HOLD, state=state, reason="in band")
