"""Hyperparameter parallelism: vmapped learning-rate sweeps."""

import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.parallel.hyper import hyperparameter_search


def clf():
    x = nn.placeholder([None, 6], name="x")
    y = nn.placeholder([None, 1], name="y")
    h = nn.dense(x, 8, activation="relu")
    nn.sigmoid_cross_entropy(y, nn.dense(h, 1, name="out"))


@pytest.fixture(scope="module")
def data():
    rs = np.random.RandomState(0)
    X = rs.randn(200, 6).astype(np.float32)
    Y = (X @ rs.randn(6) > 0).astype(np.float32)
    return X, Y


def test_vmapped_sweep_trains_every_config(data):
    X, Y = data
    lrs = [1e-4, 1e-2, 0.1]
    res = hyperparameter_search(build_graph(clf), "x:0", "y:0", X, Y,
                                learning_rates=lrs, iters=12,
                                mini_batch_size=64)
    assert res.loss_curves.shape == (3, 12)
    # every config's loss decreased; faster rates learned more on this easy
    # problem than the tiny 1e-4 rate
    for k in range(3):
        assert res.loss_curves[k, -1] < res.loss_curves[k, 0]
    assert res.final_losses[1] < res.final_losses[0]
    assert res.best_learning_rate in (1e-2, 0.1)
    # best_params is a single (unbatched) params tree usable for inference
    from sparkflow_tpu.core import make_predict_fn, predict_in_chunks
    from sparkflow_tpu.models import model_from_json
    m = model_from_json(build_graph(clf))
    preds = predict_in_chunks(
        make_predict_fn(m, "x:0", "out/BiasAdd:0"), res.best_params, X)
    assert (((preds[:, 0] > 0.0) == (Y > 0.5)).mean()) > 0.8  # logits


def test_sweep_same_init_isolates_lr_effect(data):
    X, Y = data
    res = hyperparameter_search(build_graph(clf), "x:0", "y:0", X, Y,
                                learning_rates=[0.0, 0.0], iters=3,
                                mini_batch_size=64, same_init=True)
    # identical rates + identical init -> identical curves
    np.testing.assert_allclose(res.loss_curves[0], res.loss_curves[1],
                               rtol=1e-6)


def test_sweep_unknown_optimizer_falls_back(data):
    X, Y = data
    res = hyperparameter_search(build_graph(clf), "x:0", "y:0", X, Y,
                                learning_rates=[0.5], optimizer="not_real",
                                iters=5, mini_batch_size=64)
    assert res.loss_curves[0, -1] < res.loss_curves[0, 0]  # sgd fallback trains
