"""graftcheck v2 concurrency analyzers: planted defects fire, clean code
passes, the repo itself gates clean.

Covers the three analyzers of the concurrency-soundness layer:

- GC-L304/L305 (:mod:`sparkflow_tpu.analysis.lockgraph`): a two-lock cycle
  planted ACROSS two synthetic modules, blocking ops under a held lock,
  and the inline-suppression contract (suppressed site silent, an
  unsuppressed duplicate in the same file still fires);
- GC-R402 (:mod:`sparkflow_tpu.analysis.racecheck`): a racy unguarded
  counter hit from two real threads reports exactly once with both access
  stacks; the same counter under a lock — or read-only after publication —
  stays silent; instrumentation is a no-op without an installed tracker;
- GC-J107 (:mod:`sparkflow_tpu.analysis.jaxpr_lint`): a ``psum`` under
  ``lax.cond`` / inside ``lax.while_loop`` is flagged, the hoisted version
  and static ``lax.scan`` pass.

Plus the whole-repo gates: the lock graph over ``sparkflow_tpu`` +
``examples`` is cycle-free with zero unsuppressed findings, and the
elastic threaded driver runs clean under the lockset detector.
"""

import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkflow_tpu.analysis import jaxpr_lint, lockgraph, racecheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# GC-L304: lock-order cycles
# ---------------------------------------------------------------------------

_MOD_A = '''
import threading


class Alpha:
    def __init__(self, peer: "Beta"):
        self._lock = threading.Lock()
        self.peer = peer

    def hit(self):
        with self._lock:
            self.peer.poke()   # Alpha._lock -> Beta._lock

    def poke(self):
        with self._lock:
            return 1
'''

_MOD_B_CYCLIC = '''
import threading


class Beta:
    def __init__(self, back: "Alpha" = None):
        self._lock = threading.Lock()
        self.back = back

    def hit(self):
        with self._lock:
            self.back.poke()   # Beta._lock -> Alpha._lock: the inversion

    def poke(self):
        with self._lock:
            return 2
'''

_MOD_B_CLEAN = '''
import threading


class Beta:
    def __init__(self, back: "Alpha" = None):
        self._lock = threading.Lock()
        self.back = back

    def hit(self):
        self.back.poke()       # outside the lock: consistent order
        with self._lock:
            return 2

    def poke(self):
        with self._lock:
            return 2
'''


def _write_pkg(tmp_path, mod_b_src):
    (tmp_path / "mod_a.py").write_text(_MOD_A)
    (tmp_path / "mod_b.py").write_text(mod_b_src)
    return str(tmp_path)


def test_l304_cross_module_cycle_detected(tmp_path):
    fs = lockgraph.lint_paths([_write_pkg(tmp_path, _MOD_B_CYCLIC)])
    cycles = [f for f in fs if f.rule == "GC-L304"]
    assert cycles, "the planted Alpha/Beta inversion was not reported"
    cyc = cycles[0].detail["cycle"]
    assert any("Alpha._lock" in n for n in cyc)
    assert any("Beta._lock" in n for n in cyc)
    # the report names both legs with file:line sites
    assert "mod_a.py" in cycles[0].message
    assert "mod_b.py" in cycles[0].message


def test_l304_consistent_order_clean(tmp_path):
    fs = lockgraph.lint_paths([_write_pkg(tmp_path, _MOD_B_CLEAN)])
    assert [f for f in fs if f.rule == "GC-L304"] == [], \
        "\n".join(f.render() for f in fs)


def test_l304_self_reacquire_through_call_chain(tmp_path):
    # non-reentrant lock re-acquired via an intra-class call: self-deadlock
    (tmp_path / "mod_c.py").write_text('''
import threading


class Gamma:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            return 3
''')
    fs = lockgraph.lint_paths([str(tmp_path)])
    assert any(f.rule == "GC-L304" and "re-acquired" in f.message
               for f in fs), "\n".join(f.render() for f in fs)


def test_l304_rlock_reentry_exempt(tmp_path):
    (tmp_path / "mod_d.py").write_text('''
import threading


class Delta:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            return 4
''')
    fs = lockgraph.lint_paths([str(tmp_path)])
    assert fs == [], "\n".join(f.render() for f in fs)


# ---------------------------------------------------------------------------
# GC-L305: blocking under a held lock (+ suppression contract)
# ---------------------------------------------------------------------------

_SLEEPER = '''
import threading
import time


class Sleeper:
    def __init__(self):
        self._lock = threading.Lock()

    def nap(self):
        with self._lock:
            time.sleep(0.1)
'''


def test_l305_sleep_under_lock_detected(tmp_path):
    (tmp_path / "mod_s.py").write_text(_SLEEPER)
    fs = lockgraph.lint_paths([str(tmp_path)])
    hits = [f for f in fs if f.rule == "GC-L305"]
    assert len(hits) == 1
    assert "sleep" in hits[0].message
    assert "Sleeper._lock" in hits[0].message


def test_l305_sleep_outside_lock_clean(tmp_path):
    (tmp_path / "mod_s.py").write_text(_SLEEPER.replace(
        "        with self._lock:\n            time.sleep(0.1)",
        "        with self._lock:\n            pass\n        time.sleep(0.1)"))
    fs = lockgraph.lint_paths([str(tmp_path)])
    assert fs == [], "\n".join(f.render() for f in fs)


def test_l305_blocking_through_call_chain(tmp_path):
    # the blocking op hides one call away; the lint must follow the chain
    (tmp_path / "mod_t.py").write_text('''
import threading
import time


class Chained:
    def __init__(self):
        self._lock = threading.Lock()

    def entry(self):
        with self._lock:
            self._helper()

    def _helper(self):
        time.sleep(0.5)
''')
    fs = lockgraph.lint_paths([str(tmp_path)])
    hits = [f for f in fs if f.rule == "GC-L305"]
    assert len(hits) == 1
    assert "_helper" in hits[0].message


def test_l305_suppressed_site_silent_unsuppressed_duplicate_fires(tmp_path):
    # the satellite contract: an inline disable quiets EXACTLY its line;
    # an identical unsuppressed defect in the same file still fires
    (tmp_path / "mod_u.py").write_text('''
import threading
import time


class Two:
    def __init__(self):
        self._lock = threading.Lock()

    def intentional(self):
        with self._lock:
            time.sleep(0.1)  # graftcheck: disable=GC-L305

    def accidental(self):
        with self._lock:
            time.sleep(0.1)
''')
    fs = lockgraph.lint_paths([str(tmp_path)])
    hits = [f for f in fs if f.rule == "GC-L305"]
    assert len(hits) == 1, "\n".join(f.render() for f in fs)
    assert "accidental" in hits[0].message
    assert hits[0].line == 16  # the unsuppressed duplicate's sleep


def test_condition_wait_exempt_event_wait_flagged(tmp_path):
    # Condition.wait releases the lock (the point of a condition); a bare
    # Event.wait under the lock stalls every contender
    (tmp_path / "mod_w.py").write_text('''
import threading


class Waits:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._evt = threading.Event()

    def good(self):
        with self._cond:
            self._cond.wait()

    def bad(self):
        with self._lock:
            self._evt.wait()
''')
    fs = lockgraph.lint_paths([str(tmp_path)])
    hits = [f for f in fs if f.rule == "GC-L305"]
    assert len(hits) == 1
    assert "Event" in hits[0].message


# ---------------------------------------------------------------------------
# GC-R402: dynamic lockset race detection
# ---------------------------------------------------------------------------


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0


def _hammer(fn, nthreads=2):
    threads = [threading.Thread(target=fn) for _ in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_r402_unguarded_counter_reported_with_stacks():
    with racecheck.RaceTracker() as tracker:
        c = _Counter()
        racecheck.instrument_object(c, fields=("n",))

        def bump():
            for _ in range(500):
                c.n += 1

        _hammer(bump)
    fs = tracker.findings()
    assert len(fs) == 1, [f.render() for f in fs]  # reported once, not 500x
    f = fs[0]
    assert f.rule == "GC-R402"
    assert "_Counter.n" in f.message
    # both access stacks present and pointing at the racy line
    assert "bump" in str(f.detail["first_stack"]) or \
        "bump" in str(f.detail["second_stack"])
    assert "bump" in str(f.detail["race_stack"])
    assert len(f.detail["threads"]) >= 2
    with pytest.raises(AssertionError):
        tracker.assert_clean()


def test_r402_sequential_nonoverlapping_threads_still_report():
    # the OS reuses thread idents: a worker that fully finishes before its
    # sibling starts can hand the sibling the SAME get_ident() value, which
    # used to alias both into one "thread" and silently miss the race (the
    # exact interleaving a loaded 1-core run produces). The tracker now
    # assigns its own per-thread serials, so two non-overlapping threads
    # touching an unguarded field must still report.
    with racecheck.RaceTracker() as tracker:
        c = _Counter()
        racecheck.instrument_object(c, fields=("n",))

        def bump():
            for _ in range(50):
                c.n += 1

        for _ in range(2):          # start/join one at a time: zero overlap
            t = threading.Thread(target=bump)
            t.start()
            t.join()
    fs = tracker.findings()
    assert len(fs) == 1 and fs[0].rule == "GC-R402", \
        [f.render() for f in fs]
    assert len(fs[0].detail["threads"]) >= 2


def test_r402_guarded_counter_clean():
    with racecheck.RaceTracker() as tracker:
        c = _Counter()
        racecheck.instrument_object(c, fields=("n",))

        def bump():
            for _ in range(500):
                with c._lock:
                    c.n += 1

        _hammer(bump)
    tracker.assert_clean()


def test_r402_read_only_after_publish_clean():
    # immutable-after-init fields read lock-free are NOT races (the Eraser
    # shared state): this is why the detector doesn't drown in config reads
    with racecheck.RaceTracker() as tracker:
        c = _Counter()
        racecheck.instrument_object(c, fields=("n",))
        c.n = 42
        seen = []
        _hammer(lambda: seen.append(c.n), nthreads=4)
    tracker.assert_clean()
    assert seen == [42] * 4


def test_r402_condition_wait_releases_lock_in_lockset():
    # cond.wait() must drop the lock from the waiter's lockset while it
    # sleeps and re-add it on wake — no false positive, no false negative
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self.v = 0

    with racecheck.RaceTracker() as tracker:
        b = Box()
        racecheck.instrument_object(b, fields=("v",))

        def producer():
            for _ in range(50):
                with b._cond:
                    b.v += 1
                    b._cond.notify_all()

        def consumer():
            with b._cond:
                while b.v < 50:
                    b._cond.wait(timeout=2.0)

        threads = [threading.Thread(target=producer),
                   threading.Thread(target=consumer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    tracker.assert_clean()


def test_racecheck_noop_without_tracker():
    # zero-overhead contract: with no tracker installed the object is
    # untouched — same class, raw lock, no tracking properties
    assert racecheck.active() is None
    c = _Counter()
    cls_before = type(c)
    lock_before = c._lock
    racecheck.instrument_object(c, fields=("n",))
    assert type(c) is cls_before
    assert c._lock is lock_before
    assert racecheck.tracked(c, "n") is c
    assert type(c) is cls_before


def test_racecheck_env_flag():
    old = os.environ.pop("SPARKFLOW_TPU_RACECHECK", None)
    try:
        assert not racecheck.enabled()
        os.environ["SPARKFLOW_TPU_RACECHECK"] = "1"
        assert racecheck.enabled()
        os.environ["SPARKFLOW_TPU_RACECHECK"] = "0"
        assert not racecheck.enabled()
    finally:
        if old is None:
            os.environ.pop("SPARKFLOW_TPU_RACECHECK", None)
        else:
            os.environ["SPARKFLOW_TPU_RACECHECK"] = old


def test_elastic_threaded_driver_clean_under_tracker():
    # the wired chaos harness: ElasticDPEngine.run_threads instruments its
    # store when a tracker is active; the real protocol must be race-free
    from sparkflow_tpu.parallel.elastic import ElasticDPEngine

    def loss_fn(params, x, y, mask, rng):
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    rs = np.random.RandomState(0)
    X = rs.rand(64, 3).astype(np.float32)
    Y = (X @ np.array([[1.0], [-1.0], [0.5]], np.float32)).astype(np.float32)
    eng = ElasticDPEngine(loss_fn, optax.sgd(0.05),
                          {"w": jnp.zeros((3, 1))})
    with racecheck.RaceTracker() as tracker:
        res = eng.run_threads([(X[0::2], Y[0::2]), (X[1::2], Y[1::2])],
                              epochs=3, batch_size=16, seed=0)
    assert res.examples > 0
    tracker.assert_clean()
    # the instrumentation actually engaged: store fields were tracked
    assert any("_version" in fs.label
               for fs in tracker._fields.values())


# ---------------------------------------------------------------------------
# GC-J107: collectives under data-dependent control flow
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def one_mesh():
    return Mesh(np.array(jax.devices()[:1]), ("dp",))


def test_j107_psum_under_cond_detected(one_mesh):
    def bad(v):
        return lax.cond(v.sum() > 0,
                        lambda u: lax.psum(u, "dp"),
                        lambda u: u * 2.0, v)

    fs = jaxpr_lint.lint_collective_divergence(
        bad, (jnp.ones((4, 2)),), mesh=one_mesh, in_specs=(P("dp"),),
        out_specs=P("dp"))
    assert len(fs) == 1 and fs[0].rule == "GC-J107"
    assert fs[0].detail["control"] == "cond"
    assert "psum" in str(fs[0].detail["collectives"])


def test_j107_psum_hoisted_clean(one_mesh):
    def good(v):
        s = lax.psum(v, "dp")
        return lax.cond(v.sum() > 0, lambda u: u, lambda u: u * 2.0, s)

    fs = jaxpr_lint.lint_collective_divergence(
        good, (jnp.ones((4, 2)),), mesh=one_mesh, in_specs=(P("dp"),),
        out_specs=P("dp"))
    assert fs == [], [f.render() for f in fs]


def test_j107_psum_in_while_body_detected(one_mesh):
    def bad(v):
        def body(c):
            i, u = c
            return i + 1, lax.psum(u, "dp")
        return lax.while_loop(lambda c: c[0] < 3, body, (0, v))[1]

    fs = jaxpr_lint.lint_collective_divergence(
        bad, (jnp.ones((4, 2)),), mesh=one_mesh, in_specs=(P("dp"),),
        out_specs=P("dp"))
    assert len(fs) == 1 and fs[0].detail["control"] == "while"


def test_j107_scan_is_static_and_clean(one_mesh):
    # scan's trip count is static — every device agrees — so a collective
    # in a scan body is NOT divergence
    def good(v):
        def body(c, _):
            return lax.psum(c, "dp"), None
        return lax.scan(body, v, None, length=3)[0]

    fs = jaxpr_lint.lint_collective_divergence(
        good, (jnp.ones((4, 2)),), mesh=one_mesh, in_specs=(P("dp"),),
        out_specs=P("dp"))
    assert fs == [], [f.render() for f in fs]


def test_j107_ignore_and_lint_fn_integration(one_mesh):
    from sparkflow_tpu.jax_compat import shard_map

    def bad(v):
        return lax.cond(v.sum() > 0,
                        lambda u: lax.psum(u, "dp"),
                        lambda u: u * 2.0, v)

    fs = jaxpr_lint.lint_collective_divergence(
        bad, (jnp.ones((4, 2)),), mesh=one_mesh, in_specs=(P("dp"),),
        out_specs=P("dp"), ignore=("GC-J107",))
    assert fs == []
    # the generic lint_fn entry point sees it too (shard_map'd by hand)
    wrapped = shard_map(bad, mesh=one_mesh, in_specs=(P("dp"),),
                        out_specs=P("dp"), check_vma=False)
    fs2 = jaxpr_lint.lint_fn(wrapped, (jnp.ones((4, 2)),),
                             ignore=("GC-J103", "GC-J104"))
    assert any(f.rule == "GC-J107" for f in fs2)


# ---------------------------------------------------------------------------
# whole-repo gates
# ---------------------------------------------------------------------------


def test_repo_lock_graph_clean():
    paths = [os.path.join(REPO, "sparkflow_tpu"),
             os.path.join(REPO, "examples")]
    fs = lockgraph.lint_paths(paths)
    assert fs == [], "\n" + "\n".join(f.render() for f in fs)


def test_repo_lock_graph_is_acyclic_with_real_edges():
    # the serving plane's documented hierarchy: engines/batchers take their
    # own lock, then (transitively) the KV pool's, then Metrics' — never
    # the other way. The graph must SEE those edges (the analysis has
    # teeth) and contain no multi-node SCC.
    g = lockgraph.build_graph([os.path.join(REPO, "sparkflow_tpu")])
    flat = {(src, dst) for src, tgts in g.edges.items() for dst in tgts}
    assert ("sparkflow_tpu.serving.kvcache.PagedKVCache._lock",
            "sparkflow_tpu.utils.metrics.Metrics._lock") in flat
    assert ("sparkflow_tpu.serving.decode.DecodeEngine._lock",
            "sparkflow_tpu.serving.kvcache.PagedKVCache._lock") in flat
    sccs = [c for c in lockgraph._sccs(g.edges) if len(c) > 1]
    assert sccs == [], f"lock-order cycle in the repo: {sccs}"


def test_native_build_allowlist_is_line_anchored():
    # the one intentional L305 site (subprocess.run under the native build
    # lock) is suppressed by an inline comment, not by weakening the rule:
    # the raw findings must still contain it
    path = os.path.join(REPO, "sparkflow_tpu", "native", "build.py")
    g = lockgraph.build_graph([os.path.join(REPO, "sparkflow_tpu")])
    raw = lockgraph._graph_findings(g)
    assert any(f.rule == "GC-L305" and f.path == path for f in raw), \
        "expected the intentional native-build site in the raw findings"
    assert lockgraph._filter_by_file(raw) == [
        f for f in lockgraph._filter_by_file(raw) if f.path != path]
