"""Online serving subsystem: engine, micro-batcher, HTTP front.

Covers the PR's acceptance criteria directly: AOT parity with direct
GraphModel apply across mixed request sizes with zero post-warmup compiles,
concurrent HTTP clients getting correctly-routed responses, and bounded-queue
overload rejection with a structured error instead of a hang.
"""

import json
import threading
import time

import numpy as np
import pytest

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.serving import (InferenceEngine, InferenceServer,
                                   MicroBatcher, QueueFull, ServingClient,
                                   ServingError)
from sparkflow_tpu.utils.metrics import Metrics

IN, OUT = "x:0", "out/BiasAdd:0"


def mlp_graph():
    x = nn.placeholder([None, 4], name="x")
    h = nn.dense(x, 3, activation="relu")
    out = nn.dense(h, 2, name="out")
    nn.mean_squared_error(x, out)


@pytest.fixture(scope="module")
def graph_json():
    return build_graph(mlp_graph)


@pytest.fixture(scope="module")
def weights():
    rs = np.random.RandomState(0)
    return [rs.randn(4, 3).astype(np.float32),
            rs.randn(3).astype(np.float32),
            rs.randn(3, 2).astype(np.float32),
            rs.randn(2).astype(np.float32)]


@pytest.fixture(scope="module")
def manual(weights):
    def fwd(x):
        h = np.maximum(np.asarray(x) @ weights[0] + weights[1], 0.0)
        return h @ weights[2] + weights[3]
    return fwd


@pytest.fixture(scope="module")
def engine(graph_json, weights):
    return InferenceEngine(graph_json, weights, input_name=IN,
                           output_name=OUT, max_batch=16)


# -- engine ------------------------------------------------------------------

def test_bucket_ladder_and_warmup(engine):
    assert engine.buckets == [1, 2, 4, 8, 16]
    assert engine.aot_compiles == len(engine.buckets)
    assert engine.fallback_compiles == 0


def test_parity_mixed_sizes_zero_recompiles(engine, manual, rng):
    # every bucket boundary, odd sizes, and an over-max_batch request that
    # must chunk — none may trigger a post-warmup compile
    for n in (1, 2, 3, 5, 8, 11, 16, 40):
        x = rng.randn(n, 4).astype(np.float32)
        out = engine.predict(x)
        assert out.shape == (n, 2)
        np.testing.assert_allclose(out, manual(x), rtol=1e-5, atol=1e-5)
    assert engine.fallback_compiles == 0


def test_single_unbatched_row(engine, manual):
    row = np.arange(4, dtype=np.float32)
    out = engine.predict(row)
    assert out.shape == (1, 2)
    np.testing.assert_allclose(out, manual(row[None]), rtol=1e-5, atol=1e-5)


def test_row_shape_mismatch_rejected(engine):
    with pytest.raises(ValueError, match="model expects"):
        engine.predict(np.zeros((3, 5), np.float32))


def test_bad_names_fail_at_construction(graph_json, weights):
    with pytest.raises(KeyError, match="not found in graph"):
        InferenceEngine(graph_json, weights, input_name="nope:0",
                        output_name=OUT, max_batch=2)
    with pytest.raises(ValueError, match="quantize must be one of"):
        InferenceEngine(graph_json, weights, input_name=IN, output_name=OUT,
                        quantize="int4", max_batch=2)
    with pytest.raises(ValueError, match="weights are required"):
        InferenceEngine(graph_json, None, input_name=IN, output_name=OUT,
                        max_batch=2)


def test_engine_on_dp_mesh(graph_json, weights, manual, dp_mesh, rng):
    eng = InferenceEngine(graph_json, weights, input_name=IN, output_name=OUT,
                          max_batch=16, mesh=dp_mesh)
    for n in (1, 3, 8, 13, 16):  # sub-dp buckets replicate, dp-divisible shard
        x = rng.randn(n, 4).astype(np.float32)
        np.testing.assert_allclose(eng.predict(x), manual(x),
                                   rtol=1e-5, atol=1e-5)
    assert eng.fallback_compiles == 0
    assert eng.stats()["mesh"] == {"dp": dp_mesh.size}


@pytest.mark.parametrize("mode", ["weight_only", "dynamic"])
def test_engine_quantized(graph_json, weights, manual, rng, mode):
    eng = InferenceEngine(graph_json, weights, input_name=IN, output_name=OUT,
                          max_batch=8, quantize=mode, quant_min_size=1)
    x = rng.randn(5, 4).astype(np.float32)
    err = np.abs(eng.predict(x) - manual(x)).max()
    assert err < 0.2  # int8 rounding, not exact
    assert eng.stats()["quantize"] == mode


def test_engine_from_checkpoint(graph_json, weights, manual, tmp_path, rng):
    from sparkflow_tpu.checkpoint import CheckpointManager
    from sparkflow_tpu.graphdef import list_to_params
    from sparkflow_tpu.models import model_from_json
    model = model_from_json(graph_json)
    CheckpointManager.save_weights(str(tmp_path), model,
                                   list_to_params(model, weights))
    eng = InferenceEngine.from_checkpoint(str(tmp_path), graph_json,
                                          input_name=IN, output_name=OUT,
                                          max_batch=4)
    x = rng.randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(eng.predict(x), manual(x),
                               rtol=1e-5, atol=1e-5)


def test_engine_weights_param_string(graph_json, weights, manual, rng):
    # the estimator wire format: inline JSON list-of-nested-lists
    wire = json.dumps([w.tolist() for w in weights])
    eng = InferenceEngine(graph_json, wire, input_name=IN, output_name=OUT,
                          max_batch=4)
    x = rng.randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(eng.predict(x), manual(x),
                               rtol=1e-5, atol=1e-5)


def test_lazy_engine_counts_fallback_compiles(graph_json, weights):
    eng = InferenceEngine(graph_json, weights, input_name=IN, output_name=OUT,
                          max_batch=4, warmup=False)
    assert eng.aot_compiles == 0
    eng.predict(np.zeros((3, 4), np.float32))
    assert eng.fallback_compiles == 1  # bucket 4, compiled on first use


# -- micro-batcher -----------------------------------------------------------

def test_batcher_coalesces_concurrent_requests(engine, manual):
    metrics = Metrics()
    with MicroBatcher(engine, max_delay_ms=25.0, max_queue=256,
                      metrics=metrics) as batcher:
        results = {}
        def hit(i):
            results[i] = batcher.predict(np.full((2, 4), i, np.float32))
        threads = [threading.Thread(target=hit, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(8):
            np.testing.assert_allclose(
                results[i], manual(np.full((2, 4), i, np.float32)),
                rtol=1e-5, atol=1e-5)
    summary = metrics.summary()
    hists = summary["histograms"]
    # 8 requests of 2 rows under a generous deadline: strictly fewer engine
    # calls than requests proves coalescing actually happened
    assert metrics.counters()["serving/batches"] < 8
    assert hists["serving/batch_rows"]["max"] > 2
    assert "serving/request_latency_ms" in hists


def test_batcher_bounded_queue_rejects_overload(graph_json, weights):
    class SlowEngine:
        max_batch = 4
        def predict(self, x):
            time.sleep(0.2)
            return np.asarray(x)

    with MicroBatcher(SlowEngine(), max_delay_ms=0.0,
                      max_queue=4) as batcher:
        futures = [batcher.submit(np.zeros((2, 1), np.float32))]
        time.sleep(0.05)  # first batch now in flight; queue capacity = 4 rows
        futures.append(batcher.submit(np.zeros((4, 1), np.float32)))
        with pytest.raises(QueueFull, match="queue at capacity"):
            batcher.submit(np.zeros((2, 1), np.float32))
        for f in futures:
            assert f.result(timeout=5.0) is not None
    assert batcher.metrics.counters()["serving/queue_rejections"] == 1


def test_batcher_oversized_request_rejected(engine):
    with MicroBatcher(engine, max_delay_ms=0.0) as batcher:
        with pytest.raises(ValueError, match="exceeds max_batch"):
            batcher.submit(np.zeros((engine.max_batch + 1, 4), np.float32))


def test_batcher_propagates_engine_errors(engine):
    with MicroBatcher(engine, max_delay_ms=0.0) as batcher:
        fut = batcher.submit(np.zeros((2, 9), np.float32))  # wrong feature dim
        with pytest.raises(ValueError, match="model expects"):
            fut.result(timeout=5.0)


def test_batcher_close_is_idempotent_and_rejects_after(engine):
    batcher = MicroBatcher(engine, max_delay_ms=0.0)
    batcher.close()
    batcher.close()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit(np.zeros((1, 4), np.float32))


# -- HTTP server + client ----------------------------------------------------

@pytest.fixture()
def server(engine):
    with InferenceServer(engine, max_delay_ms=2.0) as srv:
        yield srv


def test_http_predict_healthz_metrics(server, manual, rng):
    client = ServingClient(server.url)
    x = rng.randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(client.predict(x.tolist()), manual(x),
                               rtol=1e-4, atol=1e-4)
    health = client.healthz()
    assert health["status"] == "ok"
    assert health["engine"]["fallback_compiles"] == 0
    metrics = client.metrics()
    assert "serving/request_latency_ms" in metrics["histograms"]
    assert set(metrics["histograms"]["serving/request_latency_ms"]) >= {
        "p50", "p95", "p99"}


def test_http_concurrent_clients_routed_correctly(server, manual):
    client = ServingClient(server.url)
    results, errors = {}, []

    def hit(i):
        try:
            results[i] = client.predict(np.full((2, 4), i, np.float32))
        except Exception as exc:  # noqa: BLE001
            errors.append((i, exc))

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for i in range(12):
        np.testing.assert_allclose(
            results[i], manual(np.full((2, 4), i, np.float32)),
            rtol=1e-4, atol=1e-4)


def test_http_bad_requests_are_structured_400s(server):
    client = ServingClient(server.url)
    with pytest.raises(ServingError) as exc_info:
        client._request("/v1/predict", {"wrong_key": [[1, 2, 3, 4]]})
    assert exc_info.value.status == 400
    assert exc_info.value.code == "bad_request"
    with pytest.raises(ServingError) as exc_info:
        client.predict(np.zeros((2, 7), np.float32))  # wrong feature dim
    assert exc_info.value.status == 400
    with pytest.raises(ServingError) as exc_info:
        client._request("/nope", {})
    assert exc_info.value.status == 404


@pytest.mark.slow
def test_http_sustained_load_soak(engine, manual, rng):
    """Longer e2e soak (excluded from tier-1): sustained concurrent traffic,
    mixed request sizes, zero recompiles, sane percentiles at the end."""
    with InferenceServer(engine, max_delay_ms=2.0, max_queue=4096) as srv:
        client = ServingClient(srv.url)
        errors = []

        def worker(k):
            local = np.random.RandomState(k)
            for _ in range(25):
                n = int(local.randint(1, 9))
                x = local.randn(n, 4).astype(np.float32)
                try:
                    out = client.predict(x)
                    np.testing.assert_allclose(out, manual(x),
                                               rtol=1e-4, atol=1e-4)
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        metrics = client.metrics()
        lat = metrics["histograms"]["serving/request_latency_ms"]
        assert lat["count"] >= 8 * 25 * 0.9  # batches of several requests
        assert lat["p50"] <= lat["p95"] <= lat["p99"]
        assert client.healthz()["engine"]["fallback_compiles"] == 0


def test_http_queue_full_is_structured_503(engine):
    class SlowEngine:
        max_batch = 2
        _multi = False
        _in_shapes = [(4,)]
        def predict(self, x):
            time.sleep(0.3)
            return np.asarray(x)[:, :2]
        def stats(self):
            return {}

    with InferenceServer(SlowEngine(), max_delay_ms=0.0, max_queue=2) as srv:
        # retries=0: the client's default 503 backoff would absorb the
        # rejection this test exists to observe
        client = ServingClient(srv.url, retries=0)
        codes = []

        def hit():
            try:
                client.predict(np.zeros((2, 4), np.float32))
                codes.append(200)
            except ServingError as exc:
                codes.append((exc.status, exc.code))

        threads = [threading.Thread(target=hit) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert (503, "queue_full") in codes  # overload sheds, not hangs
        assert 200 in codes                  # and real work still completes
