"""Mixture-of-Experts transformer with expert parallelism over ``ep``.

Switch-style top-1 routing with a load-balancing auxiliary loss (Fedus et al.,
Switch Transformer; retrieved PAPERS.md pattern). Experts live stacked on a
leading axis sharded over the ``ep`` mesh axis (``param_pspecs``), so with
E == ep-size each device stores and computes exactly one expert's FFN over the
token stream and GSPMD inserts the combine reduction over ICI — expert
parallelism without manual all_to_all. Token-level hard capacity (dropping) is
a later scheduling optimization; routing, gating, auxiliary loss, and the EP
sharding are the real thing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .base import _Names
from .registry import register_model
from .transformer import _TransformerBase, _dense, _layer_norm


class _MoEMixin:
    """Replaces the dense FFN with a routed expert bank on MoE layers."""

    def _init_moe(self, num_experts: int, moe_every: int, aux_weight: float):
        self.num_experts = num_experts
        self.moe_every = max(1, moe_every)
        self.aux_weight = aux_weight
        self._aux_losses = []

    def _is_moe_layer(self, i: int) -> bool:
        return (i % self.moe_every) == (self.moe_every - 1)

    def _moe_block_specs(self):
        h, m, e = self.hidden, self.mlp_dim, self.num_experts
        specs = super()._block_specs()
        for k in ("fc1_kernel", "fc1_bias", "fc2_kernel", "fc2_bias"):
            del specs[k]
        specs.update({
            "router": ((h, e), "normal(0.02)"),
            "experts_fc1": ((e, h, m), "normal(0.02)"),
            "experts_b1": ((e, m), "zeros"),
            "experts_fc2": ((e, m, h), "normal(0.02)"),
            "experts_b2": ((e, h), "zeros"),
        })
        return specs

    def _moe_block_pspecs(self):
        specs = super()._block_pspecs()
        for k in ("fc1_kernel", "fc1_bias", "fc2_kernel", "fc2_bias"):
            del specs[k]
        specs.update({
            "router": P(),
            "experts_fc1": P("ep", None, None),
            "experts_b1": P("ep", None),
            "experts_fc2": P("ep", None, None),
            "experts_b2": P("ep", None),
        })
        return specs

    def param_specs(self):
        specs = super().param_specs()
        for i in range(self.num_layers):
            if self._is_moe_layer(i):
                specs[f"block_{i}"] = self._moe_block_specs()
        return specs

    def param_pspecs(self):
        specs = super().param_pspecs()
        for i in range(self.num_layers):
            if self._is_moe_layer(i):
                specs[f"block_{i}"] = self._moe_block_pspecs()
        return specs

    def _moe_mlp(self, bp, x):
        """x [B,S,H] -> routed expert FFN output + records the aux loss."""
        b, s, h = x.shape
        e = self.num_experts
        router_logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32),
                                   bp["router"])
        probs = jax.nn.softmax(router_logits, axis=-1)          # [B,S,E]
        expert_idx = jnp.argmax(probs, axis=-1)                 # [B,S]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        gate = jnp.sum(probs * onehot, axis=-1, keepdims=True)  # [B,S,1]

        # Switch load-balancing loss: E * sum_e fraction_tokens_e * mean_prob_e
        frac = jnp.mean(onehot, axis=(0, 1))                    # [E]
        mean_prob = jnp.mean(probs, axis=(0, 1))                # [E]
        self._aux_losses.append(e * jnp.sum(frac * mean_prob))

        # expert bank, leading axis sharded over 'ep': each device computes its
        # expert over the full token stream; the e-contraction below becomes a
        # psum over ep under GSPMD. Non-selected contributions are zeroed by
        # the one-hot combine.
        xc = x
        hmid = jnp.einsum("bsh,ehm->ebsm", xc, bp["experts_fc1"].astype(xc.dtype))
        hmid = jax.nn.gelu(hmid + bp["experts_b1"].astype(hmid.dtype)[:, None, None, :])
        out = jnp.einsum("ebsm,emh->ebsh", hmid, bp["experts_fc2"].astype(hmid.dtype))
        out = out + bp["experts_b2"].astype(out.dtype)[:, None, None, :]
        combined = jnp.einsum("ebsh,bse->bsh", out,
                              (onehot * gate).astype(out.dtype))
        return combined

    def _block(self, bp, x, mask, causal, train, rng):
        if "router" not in bp:
            return super()._block(bp, x, mask, causal, train, rng)
        b, s, h = x.shape
        y = _layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
        qkv = _dense(y, bp["qkv_kernel"], bp["qkv_bias"])
        qkv = qkv.reshape(b, s, 3, self.num_heads, self.head_dim)
        q, k, v = [jnp.transpose(qkv[:, :, i], (0, 2, 1, 3)) for i in range(3)]
        att = self._attention(q, k, v, mask, causal)
        att = jnp.transpose(att, (0, 2, 1, 3)).reshape(b, s, h)
        att, rng = self._dropout(_dense(att, bp["o_kernel"], bp["o_bias"]), train, rng)
        x = x + att
        y = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
        y = self._moe_mlp(bp, y)
        y, rng = self._dropout(y, train, rng)
        return x + y, rng

    def _collect_aux(self) -> jnp.ndarray:
        """Sum and clear aux losses recorded during the last forward."""
        if not self._aux_losses:
            return jnp.zeros(())
        total = sum(self._aux_losses[1:], self._aux_losses[0])
        self._aux_losses = []
        return total * self.aux_weight


@register_model("transformer_moe_lm")
class MoETransformerLM(_MoEMixin, _TransformerBase):
    """Causal MoE LM: Switch FFN every ``moe_every``-th block, EP shardable."""

    def __init__(self, vocab_size: int, num_experts: int = 8, moe_every: int = 2,
                 router_aux_weight: float = 0.01, **kw):
        self._init_moe(num_experts, moe_every, router_aux_weight)
        super().__init__(vocab_size, **kw)
        self.TENSORS = ("input_ids", "attention_mask", "logits", "pred")
        self.graphdef = _Names(self.TENSORS)

    def _forward(self, params, feeds, train, rng):
        self._aux_losses = []
        x, _ = self._encode(params, feeds, causal=True, train=train, rng=rng)
        logits = jnp.matmul(x.astype(jnp.float32),
                            params["embed"]["tok"].T.astype(jnp.float32))
        return {"logits": logits,
                "pred": jnp.argmax(logits, axis=-1).astype(jnp.float32)}

    def _loss(self, params, feeds, train, rng):
        ids = feeds["input_ids"].astype(jnp.int32)
        logits = self._forward(params, feeds, train, rng)["logits"]
        aux = self._collect_aux()
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tgt = ids[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        if "attention_mask" in feeds and feeds["attention_mask"] is not None:
            w = feeds["attention_mask"][:, 1:].astype(jnp.float32)
            per = jnp.sum(nll * w, axis=-1) / jnp.maximum(jnp.sum(w, axis=-1), 1e-6)
        else:
            per = jnp.mean(nll, axis=-1)
        # aux spread per-example so the masked-mean trainer stays correct
        return per + aux
