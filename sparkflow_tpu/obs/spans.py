"""Structured spans: low-overhead host-side tracing with nesting.

The reference's only temporal signal is a fixed 8-second sleep and a print
per loss (``sparkflow/HogwildSparkModel.py:94-98``); nothing in it can answer
"where did this step/request spend its time". :class:`Tracer` closes that
gap on the host side of this framework: a :class:`Span` is a named
``[t0, t1)`` interval with parent/child nesting (thread-local, so ``with``
blocks nest naturally within a thread; cross-thread chains pass the parent
explicitly — the MicroBatcher worker parents its per-request spans to the
HTTP handler's span this way).

Finished spans land in a bounded ring buffer, exportable two ways:

- :meth:`Tracer.export_chrome_trace` — Chrome-trace ``traceEvents`` JSON
  (open in ``chrome://tracing`` or ui.perfetto.dev), one ``ph: "X"``
  complete event per span plus thread-name metadata.
- :meth:`Tracer.export_jsonl` — one JSON object per span for log pipelines.

Device-side integration: ``span(..., jax_annotation=True)`` additionally
enters :func:`sparkflow_tpu.utils.tracing.annotate`, so when a JAX profiler
capture (``utils.tracing.trace``) is active the same named range shows up in
the device timeline — host spans and device annotations line up by name.

Overhead discipline (pinned by ``python bench.py --span-overhead``): a span
is two ``perf_counter`` calls, one small allocation, and one locked ring
append — no formatting, no I/O, no jax import on this module's path. The
framework's cross-cutting span sites (checkpoint save/restore, retry
backoffs, serving requests) go through the module-level :func:`span`, which
routes to the innermost :meth:`Tracer.activate`-d tracer on this thread
(``default_tracer`` otherwise), so a traced ``fit`` collects its own
checkpoint spans without any plumbing through call signatures.

Fleet-native tracing: :class:`TraceContext` is a W3C-traceparent-style
context (128-bit trace id, parent span id, sampled flag) minted at the
router (or accepted from the client) and carried over HTTP alongside
``X-Request-Id``. Span ids are process-local ``itertools.count`` integers,
so exports namespace them with the tracer's :attr:`Tracer.fingerprint`
(``"<pidhex><random>:<n>"``) — merged multi-process traces cannot collide —
and each tracer carries one ``(perf_counter, epoch)`` origin pair so
intervals recorded in different processes land on ONE wall-clock timeline
(:meth:`Tracer.wall_time`). Assembly/sampling live in
:mod:`sparkflow_tpu.obs.collector`; the crash flight recorder in
:mod:`sparkflow_tpu.obs.flight`.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Union

__all__ = ["Span", "TraceContext", "Tracer", "default_tracer", "span",
           "current_tracer"]

_span_ids = itertools.count(1)
_now = time.perf_counter
_get_ident = threading.get_ident

# Default ring capacity: bounded so an always-on default tracer in a
# months-long serving process cannot grow without limit (same contract as
# the metrics histogram reservoir).
MAX_SPANS = 65536

#: HTTP header that carries a :class:`TraceContext` across processes,
#: alongside the existing ``X-Request-Id`` plumbing.
TRACEPARENT_HEADER = "traceparent"

_NO_PARENT = "0" * 16  # traceparent parent field for "no parent span"


class TraceContext:
    """W3C-traceparent-style context: ``00-<trace_id>-<parent>-<flags>``.

    ``trace_id`` is 32 hex chars (128 bits), minted once per request at the
    router (or accepted from the client) and carried through every process
    the request touches. ``parent`` is the *exported* span uid of the span
    the next process should hang its root under — a
    ``"<fingerprint>:<n>"`` string (no dashes, so the 4-field dash format
    still splits), or the all-zero sentinel for "no parent". ``sampled``
    rides the flags octet; tail-based retention decisions happen at the
    collector, so the flag is a head-sampling hint, not the verdict.
    """

    __slots__ = ("trace_id", "parent", "sampled")

    def __init__(self, trace_id: str, parent: Optional[str] = None,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.parent = parent
        self.sampled = bool(sampled)

    @classmethod
    def mint(cls, sampled: bool = True) -> "TraceContext":
        """A fresh 128-bit trace id with no parent span."""
        return cls(uuid.uuid4().hex, None, sampled)

    @classmethod
    def parse(cls, header: Optional[str]) -> Optional["TraceContext"]:
        """Tolerant decode of a ``traceparent`` header; None on anything
        malformed (a bad client header must never fail the request —
        the router just mints a fresh context instead)."""
        if not header:
            return None
        parts = header.strip().split("-")
        if len(parts) != 4 or parts[0] != "00":
            return None
        trace_id, parent, flags = parts[1], parts[2], parts[3]
        if len(trace_id) != 32 or not _is_hex(trace_id):
            return None
        if int(trace_id, 16) == 0:
            return None
        if parent == _NO_PARENT:
            parent = None
        try:
            sampled = bool(int(flags, 16) & 0x01)
        except ValueError:
            return None
        return cls(trace_id, parent, sampled)

    def to_header(self) -> str:
        return (f"00-{self.trace_id}-{self.parent or _NO_PARENT}-"
                f"{'01' if self.sampled else '00'}")

    def child(self, parent_uid: str) -> "TraceContext":
        """Same trace, re-parented under an exported span uid — what the
        router stamps per dispatch attempt so each replica's spans hang
        under the attempt that actually reached it."""
        return TraceContext(self.trace_id, parent_uid, self.sampled)

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, parent={self.parent!r}, "
                f"sampled={self.sampled})")


def _is_hex(s: str) -> bool:
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


class Span:
    """One named time interval. ``t0``/``t1`` are ``perf_counter`` seconds
    (monotonic, tracer-relative at export time); ``parent_id`` links child
    spans to the enclosing one (or to an explicitly passed cross-thread
    parent)."""

    __slots__ = ("name", "span_id", "parent_id", "tid", "t0", "t1", "args")

    def __init__(self, name: str, parent_id: Optional[int], tid: int,
                 t0: float, args: Optional[Dict[str, Any]]):
        self.name = name
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.tid = tid
        self.t0 = t0
        self.t1: Optional[float] = None
        self.args = args

    @property
    def duration_s(self) -> float:
        return (self.t1 if self.t1 is not None else time.perf_counter()) \
            - self.t0

    def __repr__(self):
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, dur={self.duration_s * 1e3:.3f}ms)")


class _SpanCtx:
    """The ``with tracer.span(...)`` handle — a plain object (not a
    generator contextmanager) to keep per-span overhead minimal."""

    __slots__ = ("tracer", "name", "args", "parent", "jax_annotation",
                 "span", "_ann", "_stack")

    def __init__(self, tracer, name, args, parent, jax_annotation):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.parent = parent
        self.jax_annotation = jax_annotation
        self.span: Optional[Span] = None
        self._ann = None
        self._stack = None

    def __enter__(self) -> Span:
        self._stack = stack = self.tracer._stack()
        parent = self.parent
        if parent is None:
            parent_id = stack[-1].span_id if stack else None
        else:
            parent_id = parent.span_id if isinstance(parent, Span) else parent
        sp = Span(self.name, parent_id, _get_ident(), _now(), self.args)
        self.span = sp
        stack.append(sp)
        if self.jax_annotation:
            from ..utils.tracing import annotate
            self._ann = annotate(self.name)
            self._ann.__enter__()
        return sp

    def __exit__(self, *exc):
        t1 = _now()  # stamp first: nothing below belongs to the span
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        sp = self.span
        sp.t1 = t1
        stack = self._stack
        if stack and stack[-1] is sp:
            stack.pop()
        elif sp in stack:  # mis-nested exit (rare; keep the stack sane)
            stack.remove(sp)
        self.tracer._commit(sp)
        return False


class _NoopSpanCtx:
    """Shared do-nothing handle returned by a disabled tracer's
    :meth:`Tracer.span` — the tracing-off baseline ``bench.py
    --trace-overhead`` compares against."""

    __slots__ = ()

    def __enter__(self) -> Optional[Span]:
        return None

    def __exit__(self, *exc):
        return False


_NOOP_CTX = _NoopSpanCtx()


class Tracer:
    """Collects finished spans from any number of threads.

    ``max_spans`` bounds the ring (oldest dropped first; :meth:`dropped`
    reports how many). Each thread keeps its own span stack, so nesting
    inside one thread needs no lock; only the final commit does.

    ``enabled=False`` turns the tracer into a no-op (``span()`` returns a
    shared null context, ``record()`` drops the span) — the off-baseline
    for overhead benchmarks and a kill switch for span-heavy sites.

    :attr:`fingerprint` namespaces this tracer's process-local span-id
    counter at export time (``"<pidhex><random>:<n>"`` via
    :meth:`span_uid`), so spans merged from many processes — or many
    tracers — cannot collide; :meth:`wall_time` maps the tracer's
    ``perf_counter`` stamps onto the wall clock with one origin pair, so
    merged intervals share a timeline.
    """

    def __init__(self, max_spans: int = MAX_SPANS, enabled: bool = True):
        self.max_spans = int(max_spans)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.max_spans)
        self._total = 0
        self._tids: Dict[int, str] = {}
        self._local = threading.local()
        # one time origin pair so exports can map monotonic perf_counter
        # stamps onto the wall clock
        self._origin = time.perf_counter()
        self._origin_epoch = time.time()
        # per-process (and per-tracer) fingerprint: span ids come from a
        # process-local itertools.count, so merged multi-process traces
        # need this namespace to keep ids collision-free
        self.fingerprint = f"{os.getpid():x}{uuid.uuid4().hex[:6]}"

    # -- cross-process identity ----------------------------------------------

    def span_uid(self, span_id: Optional[int]) -> Optional[str]:
        """Exported (fingerprinted) form of a process-local span id."""
        if span_id is None:
            return None
        return f"{self.fingerprint}:{span_id}"

    def wall_time(self, t: float) -> float:
        """Map one of this tracer's ``perf_counter`` stamps onto the wall
        clock (epoch seconds) via the tracer's origin pair."""
        return self._origin_epoch + (t - self._origin)

    # -- recording -----------------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[Span]:
        """The innermost open span on THIS thread (capture it before handing
        work to another thread, then pass it as that work's ``parent=``)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def span(self, name: str, args: Optional[Dict[str, Any]] = None,
             parent: Union[Span, int, None] = None,
             jax_annotation: bool = False):
        """``with tracer.span('phase') as sp:`` — times the block, nests
        under the current span (or the explicit ``parent``). A disabled
        tracer returns a shared no-op context (``sp`` is None)."""
        if not self.enabled:
            return _NOOP_CTX
        return _SpanCtx(self, name, args, parent, jax_annotation)

    def record(self, name: str, t0: float, t1: float,
               parent: Union[Span, int, None] = None,
               args: Optional[Dict[str, Any]] = None) -> Optional[Span]:
        """Post-hoc span from already-measured ``perf_counter`` stamps (how
        the micro-batcher reconstructs each request's queue-wait interval
        after the batch completes). Dropped (returns None) when the tracer
        is disabled."""
        if not self.enabled:
            return None
        parent_id = parent.span_id if isinstance(parent, Span) else parent
        sp = Span(name, parent_id, threading.get_ident(), t0, args)
        sp.t1 = t1
        self._commit(sp)
        return sp

    def _commit(self, sp: Span) -> None:
        name = (threading.current_thread().name
                if sp.tid not in self._tids else None)
        with self._lock:
            if name is not None:
                self._tids.setdefault(sp.tid, name)
            self._spans.append(sp)
            self._total += 1

    # -- activation (module-level span() routing) ----------------------------

    @contextlib.contextmanager
    def activate(self):
        """Make this tracer the target of the module-level :func:`span` on
        this thread for the duration (how ``Trainer.fit(trace_spans=True)``
        collects the checkpoint/retry spans fired deep in the stack)."""
        stack = _active_stack()
        stack.append(self)
        try:
            yield self
        finally:
            stack.pop()

    # -- introspection / export ----------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def dropped(self) -> int:
        """Spans evicted from the ring (recorded beyond ``max_spans``)."""
        with self._lock:
            return max(0, self._total - len(self._spans))

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._total = 0

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace dict: ``{"traceEvents": [...]}`` with one complete
        (``ph: "X"``) event per span (ts/dur in microseconds) plus
        thread-name metadata events — loads in chrome://tracing and
        Perfetto."""
        with self._lock:
            spans = list(self._spans)
            tids = dict(self._tids)
        pid = os.getpid()
        events: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": "sparkflow-tpu"}}]
        for tid in sorted(tids):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tids[tid]}})
        origin = self._origin
        for s in spans:
            t1 = s.t1 if s.t1 is not None else s.t0
            args = dict(s.args) if s.args else {}
            # export-time namespacing: the raw ids are process-local
            # counters; the fingerprint keeps merged traces collision-free
            args["span_id"] = self.span_uid(s.span_id)
            if s.parent_id is not None:
                args["parent_id"] = self.span_uid(s.parent_id)
            events.append({
                "name": s.name, "ph": "X", "cat": "obs",
                "ts": round((s.t0 - origin) * 1e6, 3),
                "dur": round((t1 - s.t0) * 1e6, 3),
                "pid": pid, "tid": s.tid, "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Write :meth:`to_chrome_trace` JSON to ``path`` (tmp + atomic
        replace, so a concurrent reader never sees a torn file). Returns
        the path."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path: str) -> str:
        """One JSON object per span: name, ids, thread, wall-clock start,
        duration, args."""
        with self._lock:
            spans = list(self._spans)
            tids = dict(self._tids)
        origin, epoch = self._origin, self._origin_epoch
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            for s in spans:
                t1 = s.t1 if s.t1 is not None else s.t0
                rec = {"name": s.name, "span_id": self.span_uid(s.span_id),
                       "parent_id": self.span_uid(s.parent_id),
                       "process": self.fingerprint,
                       "thread": tids.get(s.tid, str(s.tid)),
                       "ts": epoch + (s.t0 - origin),
                       "duration_s": round(t1 - s.t0, 9)}
                if s.args:
                    rec["args"] = s.args
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# module-level routing: span() goes to the innermost activated tracer
# ---------------------------------------------------------------------------

default_tracer = Tracer()

_active = threading.local()


def _active_stack() -> List[Tracer]:
    stack = getattr(_active, "stack", None)
    if stack is None:
        stack = _active.stack = []
    return stack


def current_tracer() -> Tracer:
    """The innermost :meth:`Tracer.activate`-d tracer on this thread, or
    :data:`default_tracer`."""
    stack = getattr(_active, "stack", None)
    return stack[-1] if stack else default_tracer


def span(name: str, args: Optional[Dict[str, Any]] = None,
         parent: Union[Span, int, None] = None,
         jax_annotation: bool = False) -> _SpanCtx:
    """Record a span on the current thread's active tracer. This is the
    entry point for cross-cutting sites (checkpoint, retry, serving engine)
    that should not care which tracer is collecting."""
    return current_tracer().span(name, args, parent, jax_annotation)
