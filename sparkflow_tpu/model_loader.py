"""Pre-trained model import (reference ``sparkflow/tensorflow_model_loader.py``).

The reference imports TF1 ``Saver`` checkpoints into a ``SparkAsyncDLModel``
(``tensorflow_model_loader.py:8-32``). Here the native checkpoint formats are
JAX-ecosystem ones — ``.npz`` flat weight lists and orbax checkpoints — plus an
optional TF1-checkpoint path that activates only if TensorFlow happens to be
installed (it is not required by this framework).
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

import numpy as np

from .ml_util import convert_weights_to_json
from .spark_async import SparkAsyncDLModel


def _weights_from_npz(path: str) -> List[np.ndarray]:
    with np.load(path) as z:
        return [z[k] for k in sorted(z.files, key=lambda s: int(s.split("_")[-1]))]


def save_weights_npz(path: str, weights: List[np.ndarray]) -> None:
    """Save a flat weight list as ``.npz`` (keys ``w_0..w_{n-1}`` keep order)."""
    np.savez(path, **{f"w_{i}": w for i, w in enumerate(weights)})


def load_checkpoint_model(checkpoint_path: str,
                          graph_json: str,
                          inputCol: str,
                          tfInput: str,
                          tfOutput: str,
                          predictionCol: str = "predicted",
                          tfDropout: Optional[str] = None,
                          toKeepDropout: bool = False) -> SparkAsyncDLModel:
    """Load saved weights (npz or orbax dir) + a graph spec into a fitted
    ``SparkAsyncDLModel`` — the JAX-native equivalent of the reference's
    ``load_tensorflow_model`` (``tensorflow_model_loader.py:8-32``)."""
    from .models import model_from_json
    model = model_from_json(graph_json)
    if os.path.isdir(checkpoint_path):
        from .checkpoint import CheckpointManager
        weights = CheckpointManager.load_weights(checkpoint_path, model)
    else:
        weights = _weights_from_npz(checkpoint_path)
    # validate against the graph before wrapping
    from .graphdef import list_to_params
    list_to_params(model, weights)
    return SparkAsyncDLModel(
        inputCol=inputCol,
        modelJson=graph_json,
        modelWeights=convert_weights_to_json(weights),
        tfInput=tfInput,
        tfOutput=tfOutput,
        tfDropout=tfDropout,
        toKeepDropout=toKeepDropout,
        predictionCol=predictionCol)


# optimizer slot variables a TF1 Saver checkpoint carries alongside the
# trainables; the reference imported tf.trainable_variables() only
# (tensorflow_model_loader.py:23-24). Matched as full path SEGMENTS so a
# legitimate layer scope like "power_head" or "global_step_embed" is kept.
_TF_SLOT_SEGMENTS = frozenset(
    ["Adam", "Adam_1", "Momentum", "RMSProp", "RMSProp_1", "Adadelta",
     "Adagrad", "Ftrl", "Ftrl_1", "beta1_power", "beta2_power",
     "global_step", "save_counter", "_CHECKPOINTABLE_OBJECT_GRAPH",
     # batch-norm moving statistics: never trainable, would otherwise enter
     # the shape-matching import and collide with gamma/beta shapes
     "moving_mean", "moving_variance"])


def _is_tf_slot_variable(name: str) -> bool:
    return any(seg in _TF_SLOT_SEGMENTS for seg in name.split("/"))


def _tf_scope_sort_key(name: str):
    """Creation order of tf.layers-style variable names: ``dense/kernel`` <
    ``dense_1/kernel`` < ``dense_2/bias``; within a scope kernel before bias
    (TF1 layer creation order)."""
    import re
    scope = name.rsplit("/", 1)[0]
    leaf = name.rsplit("/", 1)[-1]
    m = re.match(r"^(.*?)(?:_(\d+))?$", scope)
    base, idx = m.group(1), int(m.group(2) or 0)
    leaf_rank = {"kernel": 0, "weights": 0, "w": 0,
                 "bias": 1, "biases": 1, "b": 1}.get(leaf, 2)
    return (base, idx, leaf_rank, leaf)


def _read_tf_variables(checkpoint_path: str):
    """name -> array for a TF checkpoint's non-slot variables, in TF1
    layer-naming order (``dense`` < ``dense_1``, kernel before bias). TF is
    required for reading only; no graph ever executes."""
    try:
        import tensorflow as tf
    except ImportError as e:
        raise ImportError(
            "reading TF1 checkpoints needs TensorFlow installed; for native "
            "checkpoints use load_checkpoint_model (npz/orbax)") from e
    reader = tf.train.load_checkpoint(checkpoint_path)
    names = sorted((n for n in reader.get_variable_to_shape_map()
                    if not _is_tf_slot_variable(n)),
                   key=_tf_scope_sort_key)
    return {n: np.asarray(reader.get_tensor(n)) for n in names}


def extract_tensorflow_weights(checkpoint_path: str,
                               var_order: Optional[List[str]] = None
                               ) -> List[np.ndarray]:
    """Read a TF1 Saver (or TF2) checkpoint's variables into a flat weight
    list WITHOUT executing any TF graph — ``tf.train.load_checkpoint`` reads
    tensors straight off the checkpoint shards (reference behavior:
    ``sess.run(tf.trainable_variables())``, ``tensorflow_model_loader.py:
    16-24``). Optimizer slot variables are excluded.

    Order: ``var_order`` (explicit checkpoint variable names) when given,
    else TF1 layer-*naming* order (``dense`` < ``dense_1`` < ..., kernel
    before bias). NOTE: checkpoints record no creation order, so for
    auto-numbered ``tf.layers``-style names this matches
    ``tf.trainable_variables``, but hand-named scopes sort alphabetically —
    use ``var_order`` (or :func:`load_tensorflow_model`'s shape matching)
    for those.
    """
    allv = _read_tf_variables(checkpoint_path)
    if var_order is not None:
        missing = [n for n in var_order if n not in allv]
        if missing:
            raise KeyError(f"variables {missing} not in checkpoint "
                           f"{checkpoint_path} (has: {sorted(allv)})")
        return [allv[n] for n in var_order]
    return list(allv.values())


def _greedy_match(unused, flat_specs, adapt, what: str) -> List[np.ndarray]:
    """Assign named tensors to the graph's flat param slots by SHAPE
    (name order breaks ties); ``adapt(name, arr, shape)`` returns the
    layout-fixed array or None when the tensor can't fill the slot.
    Cross-layer swaps between different-shaped layers are impossible this
    way; same-shape groups keep name order and emit a warning since neither
    source records creation order."""
    import logging
    if len(unused) != len(flat_specs):
        raise ValueError(
            f"{what} has {len(unused)} tensors; graph needs "
            f"{len(flat_specs)} — pass var_order= to select/pin them")
    out, ambiguous = [], set()
    for lname, pname, shape in flat_specs:
        fits = [(i, adapt(n, a, shape)) for i, (n, a) in enumerate(unused)]
        cands = [(i, arr) for i, arr in fits if arr is not None]
        if not cands:
            raise ValueError(
                f"no {what} tensor fits graph slot {lname}/{pname} "
                f"{shape}; remaining: "
                f"{[(n, a.shape) for n, a in unused]}")
        if len(cands) > 1:
            ambiguous.add(shape)
        i, arr = cands[0]
        unused.pop(i)
        out.append(arr)
    if ambiguous:
        logging.getLogger("sparkflow_tpu").warning(
            "%s import: multiple tensors fit shape(s) %s; assignment within "
            "those groups follows name order, which may not be creation "
            "order — pass var_order= to pin it.", what, sorted(ambiguous))
    return out


def _match_tf_weights_to_graph(allv, model) -> List[np.ndarray]:
    flat_specs = [(lname, pname, tuple(shape))
                  for lname, pspec in model.param_specs().items()
                  for pname, (shape, _init) in pspec.items()]
    return _greedy_match(
        list(allv.items()), flat_specs,
        lambda _n, a, shape: a if a.shape == tuple(shape) else None,
        "TF checkpoint")


def load_tensorflow_model(path: str,
                          inputCol: str,
                          tfInput: str,
                          tfOutput: str,
                          predictionCol: str = "predicted",
                          tfDropout: Optional[str] = None,
                          toKeepDropout: bool = False,
                          graph_json: Optional[str] = None,
                          var_order: Optional[List[str]] = None) -> SparkAsyncDLModel:
    """Import a TF1 Saver checkpoint into a fitted ``SparkAsyncDLModel``
    (reference ``load_tensorflow_model``, ``tensorflow_model_loader.py:8-32``).

    Like the reference, the checkpoint's own ``.meta`` MetaGraphDef is the
    default serving graph (``tensorflow_model_loader.py:16-17``): it is
    converted to JSON and executed by the :mod:`sparkflow_tpu.tf1_compat`
    interpreter — no TF graph ever runs. Alternatively pass ``graph_json``
    (a :mod:`sparkflow_tpu.nn` re-expression OR a MetaGraphDef JSON string).
    Weights are read straight off the checkpoint shards; TF is required only
    for reading.
    """
    if graph_json is None:
        meta = path + ".meta"
        if os.path.exists(meta):
            try:
                import tensorflow as tf
                from google.protobuf import json_format
                mg = tf.compat.v1.MetaGraphDef()
                with open(meta, "rb") as f:
                    mg.ParseFromString(f.read())
                graph_json = json_format.MessageToJson(mg)
            except ImportError:
                pass  # fall through to the explicit error below
            except Exception as e:  # corrupted/truncated .meta
                raise ValueError(
                    f"failed to parse {meta} as a MetaGraphDef ({e}); pass "
                    f"graph_json= explicitly to bypass it") from e
    if graph_json is None:
        raise ValueError(
            "graph_json is required (no readable .meta next to the "
            "checkpoint): pass the model re-expressed with sparkflow_tpu.nn "
            "or a MetaGraphDef JSON string.")
    from .graphdef import list_to_params
    from .models import model_from_json
    from .tf1_compat import TF1GraphModel, bake_nontrainable_values
    model = model_from_json(graph_json)
    if isinstance(model, TF1GraphModel):
        # restore NON-trainable state too (batch-norm moving statistics):
        # the reference imports tf.trainable_variables() only
        # (tensorflow_model_loader.py:23-24), so trained BN models serve
        # with fresh 0/1 stats there — here the checkpoint values are baked
        # into the graph JSON as Const initializers and ride the wire format
        state_names = model.nontrainable_variables()
        if state_names:
            import tensorflow as tf
            reader = tf.train.load_checkpoint(path)
            in_ckpt = reader.get_variable_to_shape_map()
            state = {n: np.asarray(reader.get_tensor(n))
                     for n in state_names if n in in_ckpt}
            if state:
                graph_json = bake_nontrainable_values(graph_json, state)
                model = model_from_json(graph_json)
    try:
        if var_order is None and isinstance(model, TF1GraphModel):
            # metagraph knows its variables BY NAME in creation order —
            # exact assignment, no heuristics needed
            var_order = list(model._var_order)
        if var_order is not None:
            weights = extract_tensorflow_weights(path, var_order=var_order)
        else:
            # shape-driven assignment: immune to scope names that don't sort
            # in creation order (checkpoints record names, not order)
            weights = _match_tf_weights_to_graph(_read_tf_variables(path),
                                                 model)
        list_to_params(model, weights)  # shape/count validation
    except (ValueError, TypeError, KeyError) as e:
        raise ValueError(
            f"checkpoint variables do not match graph_json params: {e}. "
            f"If the checkpoint uses non-standard variable naming, pass "
            f"var_order= with the checkpoint variable names in graph layer "
            f"order.") from e
    return SparkAsyncDLModel(
        inputCol=inputCol,
        modelJson=graph_json,
        modelWeights=convert_weights_to_json(weights),
        tfInput=tfInput,
        tfOutput=tfOutput,
        tfDropout=tfDropout,
        toKeepDropout=toKeepDropout,
        predictionCol=predictionCol)


def attach_pretrained_model_to_pipeline(checkpoint_path: str, graph_json: str,
                                        pipeline_model, inputCol: str,
                                        tfInput: str, tfOutput: str,
                                        predictionCol: str = "predicted"):
    """Append an imported model to an existing PipelineModel (reference
    ``attach_tensorflow_model_to_pipeline``, ``tensorflow_model_loader.py:35-45``)."""
    from .compat import PipelineModel
    model = load_checkpoint_model(checkpoint_path, graph_json, inputCol,
                                  tfInput, tfOutput, predictionCol)
    return PipelineModel(stages=list(pipeline_model.stages) + [model])


# reference-named alias (same role; native checkpoint formats)
attach_tensorflow_model_to_pipeline = attach_pretrained_model_to_pipeline


# ---------------------------------------------------------------------------
# PyTorch state_dict import (capability upgrade: the reference only imports
# TF1 Saver checkpoints, tensorflow_model_loader.py:8-32; torch-era users
# get the same side-door)
# ---------------------------------------------------------------------------

_TORCH_SKIP_SUFFIXES = ("num_batches_tracked",)


def _torch_state_dict(path: str):
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in this image
        raise ImportError(
            "PyTorch import requires torch (not a dependency of this "
            "framework); install torch or convert the weights to npz") from e
    obj = torch.load(path, map_location="cpu", weights_only=True)
    sd = obj.get("state_dict", obj) if isinstance(obj, dict) else obj
    out = {}
    for name, t in sd.items():
        if any(name.endswith(suf) for suf in _TORCH_SKIP_SUFFIXES):
            continue
        if not hasattr(t, "detach"):
            raise ValueError(
                f"{path}: entry {name!r} is {type(t).__name__}, not a "
                f"tensor — this looks like a checkpoint wrapper; load it "
                f"yourself and torch.save() just the state_dict (keys: "
                f"{sorted(sd)[:10]})")
        out[name] = np.asarray(t.detach().cpu().numpy())
    return out


def _adapt_torch_layout(name: str, arr: np.ndarray,
                        target_shape) -> Optional[np.ndarray]:
    """Match a torch tensor to a target slot, adapting the layout:

    - 2-D ``*.weight`` -> ``.T`` (torch Linear stores [out, in]; kernels
      here are [in, out])
    - 4-D ``*.weight`` -> OIHW -> HWIO permute (torch conv layout)
    - exact shape otherwise

    SQUARE shapes fit both ways with no shape signal, so the ``.weight``
    name decides: Linear/conv weights transform, everything else (biases,
    norm scales, embeddings accessed by other names) stays as-is. torch
    ``nn.Embedding`` tables also end in ``.weight`` but are [num, dim]
    un-transposed — pass ``var_order`` with explicit names if a SQUARE
    embedding must import (non-square ones disambiguate by shape).
    """
    target_shape = tuple(target_shape)
    is_weight = name.endswith(".weight")
    if is_weight and arr.ndim == 2:
        t = np.ascontiguousarray(arr.T)
        if t.shape == target_shape:
            return t
    if is_weight and arr.ndim == 4:
        hwio = np.ascontiguousarray(np.transpose(arr, (2, 3, 1, 0)))
        if hwio.shape == target_shape:
            return hwio
    if arr.shape == target_shape:
        return arr
    if not is_weight and arr.ndim == 2 and arr.T.shape == target_shape:
        # transposed non-.weight 2-D tensors still adapt (unusual naming)
        return np.ascontiguousarray(arr.T)
    return None


def extract_torch_weights(path: str, graph_json: str,
                          var_order: Optional[List[str]] = None
                          ) -> List[np.ndarray]:
    """Read a torch ``state_dict`` into the flat weight list of ``graph_json``
    (any model spec: DSL / registry / TF1 metagraph).

    With ``var_order`` (state_dict key names), weights map positionally onto
    the graph's flat slots; otherwise assignment is by shape (with automatic
    Linear-transpose / OIHW->HWIO adaptation), name order breaking ties —
    the same contract as the TF1 checkpoint import."""
    from .models import model_from_json

    model = model_from_json(graph_json)
    flat_specs = [(lname, pname, tuple(int(d) for d in shape))
                  for lname, pspec in model.param_specs().items()
                  for pname, (shape, _init) in pspec.items()]
    sd = _torch_state_dict(path)

    if var_order is not None:
        missing = [n for n in var_order if n not in sd]
        if missing:
            raise KeyError(f"state_dict keys {missing} not found "
                           f"(has: {sorted(sd)})")
        if len(var_order) != len(flat_specs):
            raise ValueError(f"var_order has {len(var_order)} names; graph "
                             f"needs {len(flat_specs)} weights")
        out = []
        for name, (lname, pname, shape) in zip(var_order, flat_specs):
            fit = _adapt_torch_layout(name, sd[name], shape)
            if fit is None:
                raise ValueError(
                    f"state_dict[{name!r}] shape {sd[name].shape} does not "
                    f"fit graph slot {lname}/{pname} {shape} (even "
                    f"transposed/permuted)")
            out.append(fit)
        return out

    def natural(name):
        # '10.weight' must sort AFTER '2.weight' (torch Sequential numbering)
        return [int(t) if t.isdigit() else t
                for t in re.split(r"(\d+)", name)]

    unused = sorted(sd.items(), key=lambda kv: natural(kv[0]))
    return _greedy_match(unused, flat_specs, _adapt_torch_layout,
                         "torch state_dict")


def load_torch_model(path: str,
                     graph_json: str,
                     inputCol: str,
                     tfInput: str,
                     tfOutput: str,
                     predictionCol: str = "predicted",
                     var_order: Optional[List[str]] = None,
                     tfDropout: Optional[str] = None,
                     toKeepDropout: bool = False) -> SparkAsyncDLModel:
    """torch ``state_dict`` -> fitted ``SparkAsyncDLModel`` (the
    :func:`load_tensorflow_model` analog for the torch ecosystem)."""
    weights = extract_torch_weights(path, graph_json, var_order)
    return SparkAsyncDLModel(
        inputCol=inputCol, modelJson=graph_json,
        modelWeights=convert_weights_to_json(weights),
        tfInput=tfInput, tfOutput=tfOutput, predictionCol=predictionCol,
        tfDropout=tfDropout, toKeepDropout=toKeepDropout)
