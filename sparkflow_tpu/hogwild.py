"""``HogwildTrainer``: the ``HogwildSparkModel``-shaped direct training entry.

The reference lets users bypass the Estimator and train an RDD of
``(features, label)`` pairs directly (``HogwildSparkModel(...).train(rdd)``,
``sparkflow/HogwildSparkModel.py:110-143,246-266``; exercised by
``tests/dl_runner.py:187-214``). This class keeps that constructor surface —
including the parameter-server-era arguments — and returns the trained flat
weight list. There is no server: ``master_url``, ``serverStartup`` and ``port``
are accepted and ignored (no process to spawn, no fixed 8-second startup sleep
— an anti-feature per SURVEY.md), and ``stop_server`` is a no-op kept for
try/except cleanup code written against the reference.

Also exported under the reference's class name ``HogwildSparkModel``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np
import optax

from .ml_util import handle_features
from .optimizers import build_optimizer
from .parallel.mesh import default_mesh
from .trainer import Trainer


class HogwildTrainer:
    def __init__(self,
                 tensorflowGraph: Optional[str] = None,
                 iters: int = 1000,
                 tfInput: Optional[str] = None,
                 tfLabel: Optional[str] = None,
                 optimizer: Any = None,
                 master_url: Optional[str] = None,   # ignored: no HTTP server
                 serverStartup: int = 8,             # ignored: nothing to wait for
                 acquire_lock: bool = False,         # no-op under sync all-reduce
                 mini_batch: int = -1,
                 mini_stochastic_iters: int = -1,
                 shuffle: bool = True,
                 verbose: int = 0,
                 partition_shuffles: int = 1,
                 loss_callback: Optional[Callable] = None,
                 port: int = 5000,                   # ignored: no port to bind
                 mesh=None):
        if tensorflowGraph is None:
            raise ValueError("tensorflowGraph (JSON graph spec) is required")
        if optimizer is None:
            optimizer = build_optimizer("adam", 0.01, None)
        elif isinstance(optimizer, str):
            optimizer = build_optimizer(optimizer, 0.01, None)
        elif not isinstance(optimizer, optax.GradientTransformation):
            raise TypeError(
                "optimizer must be an optax.GradientTransformation or a name; "
                "TF optimizer objects do not exist in this framework — build one "
                "with sparkflow_tpu.optimizers.build_optimizer")
        self._trainer = Trainer(
            tensorflowGraph, tfInput, tfLabel,
            optimizer=optimizer,
            iters=iters,
            mini_batch_size=mini_batch,
            mini_stochastic_iters=mini_stochastic_iters,
            shuffle_per_iter=shuffle,
            partition_shuffles=partition_shuffles,
            verbose=verbose,
            loss_callback=loss_callback,
            acquire_lock=acquire_lock,
            mesh=mesh if mesh is not None else default_mesh(),
        )
        self.tfLabel = tfLabel
        self.weights: Optional[List[np.ndarray]] = None

    def train(self, rdd) -> List[np.ndarray]:
        """Train on an RDD (or any iterable) of ``(features, label)`` pairs —
        bare features when unsupervised — and return the flat weight list
        (reference ``HogwildSparkModel.train``, ``HogwildSparkModel.py:246-269``)."""
        items = rdd.collect() if hasattr(rdd, "collect") else list(rdd)
        features, labels = handle_features(items,
                                           is_supervised=self.tfLabel is not None)
        self._trainer.fit(features, labels)
        self.weights = self._trainer.weights_list()
        return self.weights

    def stop_server(self) -> None:
        """No server exists; kept so reference-style cleanup code runs
        (``tests/dl_runner.py:209-214``)."""

    @staticmethod
    def determine_master(port: Optional[int] = None) -> str:
        """Reference API parity (``HogwildSparkModel.determine_master``,
        ``HogwildSparkModel.py:145-154``): resolves a coordinator address.
        The reference's default was the Flask port (5000), which no longer
        exists; with no argument this now matches
        :func:`parallel.distributed.determine_master` so both bootstrap paths
        agree on the address."""
        from .parallel.distributed import determine_master as _dm
        return _dm(port) if port is not None else _dm()

    # reference attribute some callers poke at
    @property
    def server(self):
        return None


HogwildSparkModel = HogwildTrainer
