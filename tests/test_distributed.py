"""Real multi-process jax.distributed coverage (2 CPU processes).

Mirrors the reference's trick of testing the real distributed path locally
(its tests ran a real Flask parameter server on localhost,
``tests/dl_runner.py:26-40``): here two actual OS processes form a JAX
process group over a localhost coordinator, build one global mesh, assemble
per-host shards, and run a cross-process all-reduced train step.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_group_global_mesh_and_train_step():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_dist_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker pins its own device count
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH", "")) if p)
    # file-backed capture: a pipe-blocked worker inside a collective would
    # deadlock its peer (and then this test) until the timeout
    import tempfile
    files = [tempfile.TemporaryFile(mode="w+") for _ in range(2)]
    procs = [subprocess.Popen([sys.executable, worker, str(i), "2", str(port)],
                              stdout=files[i],
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    try:
        for p in procs:
            p.wait(timeout=240)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    outs = []
    for f in files:
        f.seek(0)
        outs.append(f.read())
        f.close()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert "GROUP ok" in out and "devices=4" in out, out
        assert "GLOBAL_SUM ok" in out, out
        assert "TRAIN_STEP ok" in out, out
        assert "DONE" in out, out
    # the all-reduced update must be identical on both processes
    w0 = [l for l in outs[0].splitlines() if l.startswith("TRAIN_STEP")]
    w1 = [l for l in outs[1].splitlines() if l.startswith("TRAIN_STEP")]
    assert w0 == w1, (w0, w1)
