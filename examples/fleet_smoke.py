"""Fleet chaos smoke: 3 real replica processes behind a RouterServer.

Run via ``make fleet-smoke`` (or directly). The script

1. spawns three replica *processes* (re-invoking itself with
   ``--replica PORT``), each an :class:`InferenceServer` over a tiny AOT
   MLP engine with SIGTERM drain handlers installed;
2. starts a :class:`RouterServer` in front of them (health probes,
   circuit breakers, least-loaded dispatch, retry/reroute);
3. drives sustained concurrent load through a plain :class:`ServingClient`
   pointed at the router with client-side retries DISABLED — every
   recovery below is the router's doing;
4. mid-burst, SIGKILLs one replica, then restarts it on the same port;
5. asserts zero client-visible failures, that every response echoed its
   originating ``X-Request-Id``, and that the restarted replica rejoined
   the rotation (healthy_replicas back to 3).

Everything runs on CPU (`JAX_PLATFORMS=cpu`) in a few seconds.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkflow_tpu.utils.hw import ensure_live_backend

ensure_live_backend()

import sparkflow_tpu.nn as nn
from sparkflow_tpu.graph_utils import build_graph
from sparkflow_tpu.serving import (InferenceEngine, InferenceServer,
                                   RouterServer, ServingClient)

N_REPLICAS = 3
WORKERS = 6
REQUESTS_PER_WORKER = 15


def mlp_graph():
    x = nn.placeholder([None, 4], name="x")
    h = nn.dense(x, 3, activation="relu")
    out = nn.dense(h, 2, name="out")
    nn.mean_squared_error(x, out)


def make_engine() -> InferenceEngine:
    rs = np.random.RandomState(0)  # every replica serves identical weights
    weights = [rs.randn(4, 3).astype(np.float32),
               rs.randn(3).astype(np.float32),
               rs.randn(3, 2).astype(np.float32),
               rs.randn(2).astype(np.float32)]
    return InferenceEngine(build_graph(mlp_graph), weights,
                           input_name="x:0", output_name="out/BiasAdd:0",
                           max_batch=16)


def run_replica(port: int) -> None:
    from sparkflow_tpu.resilience.lifecycle import ServerState
    server = InferenceServer(make_engine(), port=port, max_delay_ms=1.0)
    server.start()
    server.install_signal_handlers()
    print(f"replica up on {server.url}", flush=True)
    # serve until SIGTERM flips the lifecycle to DRAINING, then finish
    # in-flight work and exit (drain leaves the socket up; stop tears down)
    while server.lifecycle.state in (ServerState.STARTING,
                                     ServerState.SERVING):
        time.sleep(0.2)
    server.stop()


def free_ports(n: int):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def spawn_replica(port: int) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, __file__, "--replica",
                             str(port)])


def wait_healthy(url: str, timeout_s: float = 60.0) -> None:
    client = ServingClient(url, retries=0)
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            if client.healthz(timeout_s=1.0)["status"] == "ok":
                client.close()
                return
        except Exception:
            pass
        time.sleep(0.2)
    raise TimeoutError(f"replica at {url} never became healthy")


def main() -> None:
    from sparkflow_tpu.analysis import racecheck, restrack

    ports = free_ports(N_REPLICAS)
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    procs = {p: spawn_replica(p) for p in ports}
    errors, echoes = [], []
    router = None
    # SPARKFLOW_TPU_RACECHECK=1 runs the whole chaos burst under the Eraser
    # lockset detector (zero overhead otherwise); any empty-lockset field in
    # the router's shared state fails the smoke with both access stacks
    tracker = racecheck.RaceTracker().install() if racecheck.enabled() \
        else None
    # SPARKFLOW_TPU_RESTRACK=1 additionally audits resource balance: every
    # pooled-connection checkout must be returned and every
    # router/replica<i>/* gauge family must leave the registry with its
    # replica (deregister or stop), or the smoke fails with the stacks
    retracker = restrack.ResourceTracker().install() \
        if restrack.enabled() else None
    clean = False
    try:
        for u in urls:
            wait_healthy(u)
        router = RouterServer(urls, probe_interval_s=0.1, recovery_s=0.3,
                              dispatch_retries=5)
        if tracker is not None:  # before start(): threads must see wrappers
            # wrap Membership._lock FIRST — it is the lock guarding every
            # per-replica field below; without the wrapper the tracker
            # can't see it held and reports false empty locksets
            racecheck.instrument_object(router.membership, name="Membership")
            for rep in router.membership._replicas:
                racecheck.instrument_object(
                    rep, fields=("healthy", "inflight", "queue_depth",
                                 "successes", "failures"),
                    name=f"Replica{rep.index}")
                racecheck.instrument_object(
                    rep.breaker, fields=("_state", "_consecutive_failures"),
                    name=f"Replica{rep.index}.breaker")
            if router.cache is not None:
                racecheck.instrument_object(
                    router.cache, fields=("hits", "misses"),
                    name="ResultCache")
        if retracker is not None:  # before start(), like racecheck
            restrack.instrument_metrics(router.metrics,
                                        prefixes=("router/replica",))
            for rep in router.membership._replicas:
                restrack.instrument_pool(rep.pool)
        router.start()
        print(f"router up on {router.url} fronting {N_REPLICAS} replicas",
              flush=True)

        def worker(k: int) -> None:
            client = ServingClient(router.url, retries=0)
            local = np.random.RandomState(100 + k)
            for j in range(REQUESTS_PER_WORKER):
                rid = f"smoke-{k}-{j}"
                x = local.randn(1 + j % 4, 4).astype(np.float32)
                try:
                    full = client.predict_full(x, request_id=rid,
                                               timeout_s=30.0)
                    echoes.append((rid, full["request_id"]))
                except Exception as exc:  # noqa: BLE001
                    errors.append((rid, exc))
            client.close()

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(WORKERS)]
        for t in threads:
            t.start()

        # chaos: hard-kill one replica mid-burst, then restart it on the
        # same port — the router must absorb both transitions
        time.sleep(0.2)
        victim_port = ports[0]
        procs[victim_port].send_signal(signal.SIGKILL)
        procs[victim_port].wait()
        print(f"killed replica :{victim_port} (SIGKILL)", flush=True)
        time.sleep(0.5)
        procs[victim_port] = spawn_replica(victim_port)
        print(f"restarted replica :{victim_port}", flush=True)

        for t in threads:
            t.join(timeout=120)

        total = WORKERS * REQUESTS_PER_WORKER
        assert not errors, (f"{len(errors)} client-visible failures, "
                            f"first: {errors[:3]}")
        assert len(echoes) == total, (len(echoes), total)
        assert all(sent == got for sent, got in echoes), \
            "a response lost its X-Request-Id"

        # the restarted replica must rejoin the rotation
        probe = ServingClient(router.url)
        deadline = time.time() + 30
        health = probe.healthz()
        while health["healthy_replicas"] < N_REPLICAS \
                and time.time() < deadline:
            time.sleep(0.2)
            health = probe.healthz()
        assert health["healthy_replicas"] == N_REPLICAS, health
        counters = probe.metrics()["counters"]
        probe.close()
        if tracker is not None:
            tracker.assert_clean()
            print("racecheck: zero data races across the chaos burst",
                  flush=True)
        print(f"fleet-smoke OK: {total}/{total} requests served with zero "
              f"failures through kill+restart "
              f"(rerouted={counters.get('router/rerouted', 0):.0f}, "
              f"healthy_replicas={health['healthy_replicas']})", flush=True)
        clean = True
    finally:
        if tracker is not None:
            tracker.uninstall()
        if router is not None:
            router.stop()
        # balance is only meaningful after router.stop() took the replica
        # gauges down; skip the assert when the smoke already failed so the
        # original error isn't shadowed by the leaks it caused
        if retracker is not None:
            retracker.uninstall()
            if clean:
                retracker.assert_balanced()
                print(f"restrack: zero unbalanced resources "
                      f"({retracker.acquired} acquired, "
                      f"{retracker.released} released)", flush=True)
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replica", type=int, metavar="PORT",
                        help="internal: run one replica process on PORT")
    ns = parser.parse_args()
    if ns.replica is not None:
        run_replica(ns.replica)
    else:
        main()
