"""Deadline-based micro-batching for concurrent inference requests.

Per-request device dispatch wastes the accelerator: each call pays the fixed
host-side overhead (python → runtime → device and back) for a handful of rows.
The SparkNet observation (arXiv:1511.06051) is that the fix for exactly this
shape of overhead is batching work before it reaches the device — here applied
on the serving side. The :class:`MicroBatcher` coalesces requests that arrive
within a small deadline window (``max_delay_ms``) into one engine call of up
to ``max_batch`` rows, then fans the rows of the batched output back out to
per-request futures.

Backpressure is explicit: the pending-row queue is bounded, and submissions
beyond the bound raise :class:`QueueFull` immediately instead of stretching
tail latency without limit. The HTTP front maps that to a structured 503.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import (TYPE_CHECKING, Any, Dict, List, Optional, Sequence,
                    Tuple)

import numpy as np

if TYPE_CHECKING:  # type-only: batcher must not pull in the engines at import
    from .decode import DecodeEngine
    from .engine import InferenceEngine

from ..obs import spans as spans_mod
from ..utils import metrics as metrics_mod


class QueueFull(Exception):
    """Raised by :meth:`MicroBatcher.submit` when the pending queue is at
    capacity — the caller should shed the request (HTTP 503), not wait."""


class Draining(QueueFull):
    """Raised by :meth:`MicroBatcher.submit` once :meth:`begin_drain` was
    called: queued work still completes, but no new work is admitted. The
    HTTP front maps this to ``503`` + ``Retry-After`` so a load balancer
    re-routes instead of surfacing an error."""


class _Pending:
    __slots__ = ("rows", "future", "enqueued_at", "request_id", "parent",
                 "trace_id")

    def __init__(self, rows, future, enqueued_at, request_id=None,
                 parent=None, trace_id=None):
        self.rows = rows
        self.future = future
        self.enqueued_at = enqueued_at
        self.request_id = request_id  # X-Request-Id from the HTTP front
        self.parent = parent  # submitter's open Span (cross-thread link)
        self.trace_id = trace_id  # fleet trace id (obs.TraceContext)


class MicroBatcher:
    """Thread-safe request coalescer in front of an
    :class:`~sparkflow_tpu.serving.engine.InferenceEngine`.

    Parameters
    ----------
    engine : object
        Anything with a ``predict(x) -> np.ndarray`` that maps rows to rows
        (row i of the output answers row i of the input).
    max_batch : int | None
        Rows per engine call; defaults to ``engine.max_batch``.
    max_delay_ms : float
        How long the worker waits for co-riders once a request is pending.
        0 disables coalescing delay (still batches whatever is queued).
    max_queue : int
        Bound on queued rows (excluding the batch in flight). Submissions
        that would exceed it raise :class:`QueueFull`.
    """

    def __init__(self, engine: "InferenceEngine", *,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 2.0, max_queue: int = 1024,
                 metrics: Optional[metrics_mod.Metrics] = None,
                 tracer: Optional[spans_mod.Tracer] = None):
        self.engine = engine
        self.max_batch = int(max_batch if max_batch is not None
                             else getattr(engine, "max_batch", 64))
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue = int(max_queue)
        self.metrics = (metrics if metrics is not None
                        else getattr(engine, "metrics", None)
                        or metrics_mod.Metrics())
        # request tracing: batch/compute spans land here, and the worker
        # activates it so engine-level span() calls nest under them
        self.tracer = (tracer if tracer is not None
                       else spans_mod.default_tracer)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[_Pending] = []
        self._queued_rows = 0
        self._inflight_rows = 0  # rows popped into the batch being served
        self._closed = False
        self._draining = False
        self._worker = threading.Thread(target=self._loop,
                                        name="microbatcher", daemon=True)
        self._worker.start()

    # -- client side ---------------------------------------------------------

    def submit(self, x, request_id: Optional[str] = None,
               parent: Optional[spans_mod.Span] = None,
               trace_id: Optional[str] = None
               ) -> "Future[np.ndarray]":
        """Queue one request (``[n, ...]`` array, or one unbatched row, or a
        tuple of arrays for multi-input engines) and return a Future that
        resolves to its rows of the batched output.

        ``request_id`` rides along for tracing; ``parent`` (the caller's
        open :class:`~sparkflow_tpu.obs.Span`) parents the worker-side
        spans so the cross-thread chain stays connected. On completion the
        Future additionally carries ``.request_id`` and ``.timing`` — the
        per-request latency decomposition
        ``{queue_wait_ms, batch_assembly_ms, compute_ms, total_ms}``
        (set before the result is published, so ``result()`` returners
        always see it)."""
        rows = self._as_rows(x)
        n = rows[0].shape[0]
        if n > self.max_batch:
            raise ValueError(
                f"request of {n} rows exceeds max_batch={self.max_batch}; "
                f"split it client-side or call engine.predict directly")
        fut: "Future[np.ndarray]" = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if self._draining:
                self.metrics.incr("serving/drain_rejections")
                raise Draining("MicroBatcher is draining; in-flight work "
                               "completes but new requests are refused")
            if self._queued_rows + n > self.max_queue:
                self.metrics.incr("serving/queue_rejections")
                raise QueueFull(
                    f"queue at capacity ({self._queued_rows}/{self.max_queue}"
                    f" rows); retry later")
            self._pending.append(_Pending(rows, fut, time.perf_counter(),
                                          request_id, parent, trace_id))
            self._queued_rows += n
            self.metrics.observe("serving/queue_depth_rows",
                                 self._queued_rows)
            self._cond.notify()
        return fut

    def predict(self, x, timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapper: ``submit(x).result(timeout)``."""
        return self.submit(x).result(timeout)

    def begin_drain(self) -> None:
        """Stop admitting work (submits raise :class:`Draining`) while the
        worker finishes everything already queued. Idempotent; pair with
        :meth:`wait_drained`, then :meth:`close`."""
        with self._cond:
            if self._closed or self._draining:
                return
            self._draining = True
            self._cond.notify_all()

    def wait_drained(self, timeout: Optional[float] = 10.0) -> bool:
        """Block until no request is queued or being served. Returns False
        if ``timeout`` expired with work still in flight."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._pending or self._inflight_rows:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def close(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop the worker. With ``drain`` (default) queued requests are
        served first; otherwise they fail with RuntimeError."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if not drain:
                for p in self._pending:
                    p.future.set_exception(
                        RuntimeError("MicroBatcher closed"))
                self._pending.clear()
                self._queued_rows = 0
            self._cond.notify_all()
        self._worker.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def depth(self) -> int:
        """Rows currently queued (diagnostics / tests)."""
        with self._lock:
            return self._queued_rows

    def inflight_rows(self) -> int:
        """Rows in the batch currently on the device — together with
        :meth:`depth` this is the replica's load signal (``/healthz``
        exposes both for the router's least-loaded dispatch)."""
        with self._lock:
            return self._inflight_rows

    # -- worker side ---------------------------------------------------------

    def _as_rows(self, x) -> Tuple[np.ndarray, ...]:
        multi = bool(getattr(self.engine, "_multi", False))
        xs = (tuple(np.asarray(a) for a in x) if multi
              else (np.asarray(x),))
        shapes = getattr(self.engine, "_in_shapes", None)
        if shapes is not None and xs[0].ndim == len(shapes[0]):
            xs = tuple(a[None] for a in xs)  # single unbatched row
        n = xs[0].shape[0]
        if any(a.shape[0] != n for a in xs):
            raise ValueError("multi-input arrays must share the batch dim")
        if n == 0:
            raise ValueError("empty request")
        return xs

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until there is work (or close), wait out the coalescing
        deadline, then pop up to max_batch rows worth of whole requests."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return None  # closed and drained
            if self.max_delay_ms > 0 and not self._draining:
                oldest = self._pending[0].enqueued_at
                deadline = oldest + self.max_delay_ms / 1000.0
                while (self._queued_rows < self.max_batch
                       and not self._closed and not self._draining):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
            batch, rows = [], 0
            while self._pending:
                n = self._pending[0].rows[0].shape[0]
                if batch and rows + n > self.max_batch:
                    break
                p = self._pending.pop(0)
                batch.append(p)
                rows += n
            self._queued_rows -= rows
            self._inflight_rows += rows
            return batch

    def _loop(self) -> None:
        # activate(): module-level span() calls made while serving (e.g. in
        # the engine) land on this batcher's tracer, nested under the
        # batch span, instead of on the process-default one
        with self.tracer.activate():
            while True:
                batch = self._take_batch()
                if batch is None:
                    return
                try:
                    self._serve(batch)
                finally:
                    with self._cond:
                        self._inflight_rows -= sum(p.rows[0].shape[0]
                                                   for p in batch)
                        self._cond.notify_all()  # wait_drained watches this

    def _serve(self, batch: List[_Pending]) -> None:
        sizes = [p.rows[0].shape[0] for p in batch]
        total = sum(sizes)
        multi = len(batch[0].rows) > 1
        tracer = self.tracer
        with tracer.span("serving/batch",
                         args={"rows": total, "requests": len(batch)}):
            try:
                with tracer.span("serving/batch_assembly"):
                    t_asm = time.perf_counter()
                    joined = tuple(
                        np.concatenate([p.rows[i] for p in batch], axis=0)
                        for i in range(len(batch[0].rows)))
                    t0 = time.perf_counter()
                with tracer.span("serving/engine_compute"):
                    out = self.engine.predict(joined if multi else joined[0])
                    t1 = time.perf_counter()
                dt = t1 - t0
            except Exception as exc:  # noqa: BLE001 - fan the failure out
                for p in batch:
                    if not p.future.cancelled():
                        p.future.set_exception(exc)
                self.metrics.incr("serving/batch_errors")
                return
        asm_ms = (t0 - t_asm) * 1000.0
        compute_ms = dt * 1000.0
        self.metrics.observe("serving/batch_rows", total)
        self.metrics.observe("serving/batch_fill_ratio",
                             total / self.max_batch)
        self.metrics.observe("serving/batch_assembly_ms", asm_ms)
        self.metrics.observe("serving/compute_ms", compute_ms)
        self.metrics.observe("serving/batch_latency_ms", dt * 1000.0)
        self.metrics.incr("serving/batches")
        self.metrics.incr("serving/requests", len(batch))
        offset = 0
        now = time.perf_counter()
        for p, n in zip(batch, sizes):
            queue_wait_ms = (t_asm - p.enqueued_at) * 1000.0
            total_ms = (now - p.enqueued_at) * 1000.0
            self.metrics.observe("serving/queue_wait_ms", queue_wait_ms)
            self.metrics.observe("serving/request_latency_ms", total_ms)
            # post-hoc span: the wait interval is only known once the batch
            # forms; parent = the submitter's request span, so the chain
            # reads request -> queue_wait even across threads
            wargs: Dict[str, Any] = {}
            if p.request_id:
                wargs["request_id"] = p.request_id
            if p.trace_id:
                wargs["trace_id"] = p.trace_id
            tracer.record("serving/queue_wait", p.enqueued_at, t_asm,
                          parent=p.parent, args=wargs or None)
            if not p.future.cancelled():
                # attach BEFORE set_result: anyone woken by result() must
                # already see the decomposition
                p.future.request_id = p.request_id
                p.future.timing = {
                    "queue_wait_ms": queue_wait_ms,
                    "batch_assembly_ms": asm_ms,
                    "compute_ms": compute_ms,
                    "total_ms": total_ms,
                }
                p.future.set_result(out[offset:offset + n])
            offset += n


class _GenPending:
    __slots__ = ("prompt", "max_new_tokens", "temperature", "top_k",
                 "eos_id", "seed", "future", "enqueued_at", "request_id",
                 "parent", "trace_id", "admitted_at", "prefill_done_at",
                 "slot", "tokens")

    def __init__(self, prompt, max_new_tokens, temperature, top_k, eos_id,
                 seed, future, enqueued_at, request_id=None, parent=None,
                 trace_id=None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.seed = seed
        self.future = future
        self.enqueued_at = enqueued_at
        self.request_id = request_id
        self.parent = parent
        self.trace_id = trace_id
        self.admitted_at = None
        self.prefill_done_at = None
        self.slot = None
        self.tokens: List[int] = []


class ContinuousBatcher:
    """Continuous (token-boundary) batching in front of a
    :class:`~sparkflow_tpu.serving.decode.DecodeEngine`.

    Where :class:`MicroBatcher` coalesces at CALL boundaries — a batch forms,
    runs once, disperses — generation needs coalescing at TOKEN boundaries:
    a 2048-token completion and a 10-token one share a decode step per token,
    and the short one must leave (and its slot be refilled) the moment it
    finishes, not when the convoy does. The worker loop therefore interleaves
    three things every iteration: **admit** queued requests into free slots
    (engine prefill + reservation-based admission), **step** the whole slot
    batch one token, and **retire** sequences that hit EOS or their token
    budget — returning pages and the lane to the pool immediately.

    With ``prefill_split=True`` admission/prefill runs on its own worker so a
    long prompt's prefill never stalls the decode loop; the decode worker
    keeps stepping whatever is live and picks the new slot up next iteration.

    Backpressure and drain semantics mirror :class:`MicroBatcher` exactly —
    bounded queue raising :class:`QueueFull`, :meth:`begin_drain` /
    :meth:`wait_drained` / :meth:`close`, :meth:`depth` /
    :meth:`inflight_rows` as the ``/healthz`` load signals — so
    ``InferenceServer``/``RouterServer`` front either batcher unchanged.

    Futures resolve to ``{"tokens", "num_tokens", "finish_reason"}`` and
    carry ``.request_id`` and ``.timing``
    (``{queue_wait_ms, prefill_ms, decode_ms, total_ms, tokens}``) exactly
    like the predict path's futures.
    """

    def __init__(self, engine: "DecodeEngine", *, max_queue: int = 256,
                 prefill_split: bool = False,
                 metrics: Optional[metrics_mod.Metrics] = None,
                 tracer: Optional[spans_mod.Tracer] = None):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.metrics = (metrics if metrics is not None
                        else getattr(engine, "metrics", None)
                        or metrics_mod.Metrics())
        self.tracer = (tracer if tracer is not None
                       else spans_mod.default_tracer)
        self.prefill_split = bool(prefill_split)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[_GenPending] = []
        self._active: Dict[int, _GenPending] = {}   # slot -> request
        self._prefilling = 0   # requests popped for prefill, no slot yet
        self._closed = False
        self._draining = False
        # admission accounting: offered vs refused-at-the-door. The
        # quantized-pool benchmarks read the rejection RATE off these (a
        # roomier pool admits more of the same offered load), and capacity
        # dashboards get them without scraping the metrics registry.
        self._submitted = 0
        self._rejected = 0
        self._workers = [threading.Thread(target=self._decode_loop,
                                          name="continuous-batcher",
                                          daemon=True)]
        if self.prefill_split:
            self._workers.append(threading.Thread(
                target=self._prefill_loop, name="continuous-prefill",
                daemon=True))
        for w in self._workers:
            w.start()

    # -- client side ---------------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None, seed: Optional[int] = None,
               request_id: Optional[str] = None,
               parent: Optional[spans_mod.Span] = None,
               trace_id: Optional[str] = None) -> "Future[Dict]":
        """Queue one generation; the Future resolves to
        ``{"tokens": [...], "num_tokens": n, "finish_reason": "eos"|"length"}``."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) > self.engine.max_prompt_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds max_prompt_len="
                f"{self.engine.max_prompt_len}")
        if len(prompt) + max_new_tokens > self.engine.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {len(prompt) + max_new_tokens} "
                f"exceeds max_seq_len={self.engine.max_seq_len}")
        fut: "Future[Dict]" = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("ContinuousBatcher is closed")
            self._submitted += 1
            if self._draining:
                self._rejected += 1
                self.metrics.incr("serving/drain_rejections")
                raise Draining("ContinuousBatcher is draining; in-flight "
                               "generations complete but new requests are "
                               "refused")
            if len(self._pending) >= self.max_queue:
                self._rejected += 1
                self.metrics.incr("serving/queue_rejections")
                raise QueueFull(
                    f"generate queue at capacity ({len(self._pending)}/"
                    f"{self.max_queue}); retry later")
            self._pending.append(_GenPending(
                prompt, max_new_tokens, float(temperature), int(top_k),
                eos_id, seed, fut, time.perf_counter(), request_id, parent,
                trace_id))
            self.metrics.observe("serving/decode/queue_depth",
                                 len(self._pending))
            self._cond.notify_all()
        return fut

    def generate(self, prompt: Sequence[int], timeout: Optional[float] = None,
                 **kw) -> Dict[str, Any]:
        """Blocking convenience wrapper: ``submit(...).result(timeout)``."""
        return self.submit(prompt, **kw).result(timeout)

    def begin_drain(self) -> None:
        """Stop admitting requests (submits raise :class:`Draining`); queued
        and in-flight generations still run to completion. Idempotent."""
        with self._cond:
            if self._closed or self._draining:
                return
            self._draining = True
            self._cond.notify_all()

    def wait_drained(self, timeout: Optional[float] = 10.0) -> bool:
        """Block until nothing is queued, prefilling, or decoding."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending or self._active or self._prefilling:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the workers. With ``drain`` (default) queued + in-flight
        generations finish first; otherwise they fail with RuntimeError."""
        if drain:
            self.begin_drain()
            self.wait_drained(timeout)
        failed = []
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if not drain:
                failed = [p.future for p in self._pending]
                self._pending.clear()
            self._cond.notify_all()
        for w in self._workers:
            w.join(timeout)
        # workers are parked; whatever is still active (drain=False, a
        # drain that timed out, or a prefill that landed mid-close) holds
        # an engine slot and KV pages — retire them, or they leak
        with self._cond:
            abandoned = list(self._active.values())
            self._active.clear()
        for p in abandoned:
            failed.append(p.future)
            self.engine.release(p.slot)
        for f in failed:
            if not f.cancelled():
                f.set_exception(RuntimeError("ContinuousBatcher closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def depth(self) -> int:
        """Requests queued, not yet admitted into a slot."""
        with self._lock:
            return len(self._pending)

    def inflight_rows(self) -> int:
        """Sequences currently generating (slots held + prefills in
        flight) — the replica load signal ``/healthz`` exposes."""
        with self._lock:
            return len(self._active) + self._prefilling

    def stats(self) -> Dict[str, Any]:
        """Admission accounting: offered load vs refused-at-the-door, plus
        the engine's pool layout so capacity benchmarks correlate the
        rejection rate with bytes-per-page in one read."""
        with self._lock:
            submitted, rejected = self._submitted, self._rejected
            depth = len(self._pending)
            inflight = len(self._active) + self._prefilling
        return {
            "submitted": submitted,
            "rejected": rejected,
            "rejection_rate": rejected / submitted if submitted else 0.0,
            "queue_depth": depth,
            "inflight_rows": inflight,
            "kv_quant": getattr(self.engine, "kv_quant", "bf16"),
        }

    # -- worker side ---------------------------------------------------------

    def _try_admit_locked(self) -> Optional[_GenPending]:
        """Pop the oldest admissible request, or None. Caller holds the
        lock. FIFO head-of-line only: skipping ahead would starve big
        requests behind a stream of small ones."""
        if not self._pending:
            return None
        req = self._pending[0]
        # the actual prompt tokens let prefix-cache hits shrink the demand
        if not self.engine.can_admit(len(req.prompt), req.max_new_tokens,
                                     prompt=req.prompt):
            return None
        self._pending.pop(0)
        req.admitted_at = time.perf_counter()
        self._prefilling += 1
        return req

    def _prefill_one(self, req: _GenPending) -> None:
        """Run the engine prefill for one popped request and activate its
        slot (any-thread half; state updates re-acquire the lock)."""
        aargs: Dict[str, Any] = {}
        if req.request_id:
            aargs["request_id"] = req.request_id
        if req.trace_id:
            aargs["trace_id"] = req.trace_id
        try:
            with self.tracer.span("serving/decode_admit",
                                  args=aargs or None,
                                  parent=req.parent):
                info = self.engine.prefill(
                    req.prompt, max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    seed=req.seed)
        except Exception as exc:  # noqa: BLE001 - fan to the caller
            with self._cond:
                self._prefilling -= 1
                self._cond.notify_all()
            if not req.future.cancelled():
                req.future.set_exception(exc)
            return
        req.slot = info["slot"]
        tok = info.get("token")
        if tok is None:
            # chunked prefill: the suffix advances inside the decode loop's
            # fused steps; the first token arrives via step() like any other
            # (prefill_done_at is stamped when it does)
            self.metrics.incr("serving/decode/admitted")
            with self._cond:
                self._prefilling -= 1
                self._active[req.slot] = req
                self._cond.notify_all()
            return
        req.prefill_done_at = time.perf_counter()
        req.tokens.append(tok)
        self.metrics.incr("serving/decode/admitted")
        with self._cond:
            self._prefilling -= 1
            self._active[req.slot] = req
            self._cond.notify_all()

    def _finish(self, req: _GenPending, reason: str) -> None:
        self.engine.release(req.slot)
        now = time.perf_counter()
        # decomposition: enqueued -> admitted (queue wait) -> first token
        # (prefill/TTFT) -> finish (decode). Each leg measures only its own
        # span, whatever mix of chunked prefill and multi-token speculative
        # bursts produced the tokens.
        admitted = req.admitted_at or req.enqueued_at
        queue_wait_ms = (admitted - req.enqueued_at) * 1000.0
        prefill_ms = 0.0
        if req.prefill_done_at is not None:
            prefill_ms = (req.prefill_done_at - admitted) * 1000.0
        decode_ms = (now - (req.prefill_done_at or admitted)) * 1000.0
        total_ms = (now - req.enqueued_at) * 1000.0
        ntok = len(req.tokens)
        self.metrics.observe("serving/decode/request_latency_ms", total_ms)
        self.metrics.observe("serving/decode/tokens_per_request", ntok)
        self.metrics.incr("serving/decode/completed")
        gargs: Dict[str, Any] = {"tokens": ntok}
        if req.request_id:
            gargs["request_id"] = req.request_id
        if req.trace_id:
            gargs["trace_id"] = req.trace_id
        self.tracer.record("serving/decode_generate", req.enqueued_at, now,
                           parent=req.parent, args=gargs)
        if not req.future.cancelled():
            req.future.request_id = req.request_id
            req.future.timing = {
                "queue_wait_ms": queue_wait_ms,
                "prefill_ms": prefill_ms,
                "decode_ms": decode_ms,
                "total_ms": total_ms,
                "tokens": ntok,
            }
            req.future.set_result({"tokens": list(req.tokens),
                                   "num_tokens": ntok,
                                   "finish_reason": reason})

    def _step_active(self) -> None:
        """One decode iteration + retirement. The engine call runs outside
        the batcher lock (it has its own); retirement updates re-acquire."""
        t_tick0 = time.perf_counter()
        produced = self.engine.step()
        t_tick1 = time.perf_counter()
        finished = []
        ticked = []  # (req, tokens) for per-tick spans, recorded post-lock
        with self._cond:
            for slot, burst in produced.items():
                req = self._active.get(slot)
                if req is None:
                    continue
                if req.trace_id:
                    # per-tick decode attribution, only for requests that
                    # carry a fleet trace id (untraced load stays span-free
                    # on the hot path)
                    ticked.append((req, len(burst)))
                if req.prefill_done_at is None:
                    # chunked request's first token: TTFT stamps here
                    req.prefill_done_at = time.perf_counter()
                # a speculative step can commit 0..k+1 tokens per slot:
                # consume the burst in order and retire mid-burst on eos or
                # budget, discarding the remainder (the engine's extra KV
                # past the retired length dies with release())
                for tok in burst:
                    req.tokens.append(tok)
                    if req.eos_id is not None and tok == req.eos_id:
                        finished.append((req, "eos"))
                        del self._active[slot]
                        break
                    if len(req.tokens) >= req.max_new_tokens:
                        finished.append((req, "length"))
                        del self._active[slot]
                        break
            if finished:
                self._cond.notify_all()  # wait_drained watches _active
        for req, ntok in ticked:
            self.tracer.record("serving/decode_tick", t_tick0, t_tick1,
                               parent=req.parent,
                               args={"trace_id": req.trace_id,
                                     "slot": req.slot, "tokens": ntok})
        for req, reason in finished:
            self._finish(req, reason)

    def _decode_loop(self) -> None:
        with self.tracer.activate():
            while True:
                admitted = False
                if not self.prefill_split:
                    # inline admission: fill every free slot before stepping
                    while True:
                        with self._cond:
                            if self._closed:
                                return
                            req = self._try_admit_locked()
                        if req is None:
                            break
                        self._prefill_one(req)
                        admitted = True
                with self._cond:
                    if self._closed:
                        return
                    if not self._active and not admitted:
                        # idle (or head-of-line request doesn't fit yet):
                        # sleep until a submit / prefill / retire notifies.
                        # Bounded wait while work is queued or prefilling so
                        # admission capacity is re-checked promptly.
                        self._cond.wait(0.05 if (self._pending
                                                 or self._prefilling)
                                        else None)
                        continue
                    have_active = bool(self._active)
                if have_active:
                    self._step_active()

    def _prefill_loop(self) -> None:
        while True:
            with self._cond:
                req = None
                while not self._closed:
                    req = self._try_admit_locked()
                    if req is not None:
                        break
                    self._cond.wait(0.05 if self._pending else None)
                if req is None:  # closed with nothing admitted
                    return
            # an admission that raced close() still runs its prefill; the
            # slot it activates is retired by close()'s abandoned sweep
            self._prefill_one(req)
