"""Profiling/tracing: JAX profiler capture + named step annotations.

The reference has no tracing at all (SURVEY.md §5 — its only temporal control
is a fixed 8-second startup sleep). Here: ``trace(dir)`` captures a Perfetto/
TensorBoard-loadable profile of the wrapped region on TPU, and
``annotate(name)`` marks named ranges (visible in the trace viewer and nestable
inside jit via jax.named_scope).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device+host profile of the enclosed region into ``log_dir``
    (open with TensorBoard's profile plugin or ui.perfetto.dev)."""
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named range: shows up in profiles; usable inside and outside jit."""
    with jax.named_scope(name):
        with jax.profiler.TraceAnnotation(name):
            yield


def device_memory_stats() -> dict:
    """Per-device live memory, when the backend exposes it."""
    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(d)] = {k: stats[k] for k in
                           ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                           if k in stats}
    return out
